"""Platform selection helper and the environment-variable registry.

Some TPU environments install a sitecustomize hook that force-registers a
PJRT plugin and rewrites ``jax.config.jax_platforms`` at interpreter start,
which silently overrides a user's ``JAX_PLATFORMS=cpu``.  This helper
re-asserts the user's explicit choice (needed by the CPU-mesh test harness
and any non-TPU deployment) without touching the TPU default path.

This module is also the ONLY legal home for environment reads in the
package (enforced by seqlint SEQ002): every knob is declared once in
:data:`ENV_VARS` with its type, default, and one-line doc, and consumers
go through the typed accessors (:func:`env_str` / :func:`env_int` /
:func:`env_flag`).  Reads happen at CALL time, not import time, so
tests' ``monkeypatch.setenv`` keeps working.  Centralising the parse
also centralises the error message: a malformed integer raises one
uniform, actionable ``ValueError`` naming the variable and the observed
text, instead of each call site improvising its own.
"""

from __future__ import annotations

import dataclasses
import os


# --------------------------------------------------------------------------
# Environment-variable registry (PR 3 satellite: the SEQ002 consolidation).
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class EnvVar:
    """One declared environment knob: its name, value type ('str' /
    'int' / 'flag'), default, and a one-line doc for --help and docs."""

    name: str
    kind: str
    default: str | int | bool | None
    doc: str


ENV_VARS: tuple[EnvVar, ...] = (
    EnvVar(
        "JAX_PLATFORMS",
        "str",
        None,
        "jax backend override (cpu for the virtual-device test mesh)",
    ),
    EnvVar(
        "XLA_FLAGS",
        "str",
        None,
        "XLA flags; xla_force_host_platform_device_count sets the "
        "virtual CPU mesh width",
    ),
    EnvVar(
        "TPU_SEQALIGN_COMPILE_CACHE",
        "str",
        None,
        "persistent compile-cache directory ('off'/'0' disables)",
    ),
    EnvVar(
        "SEQALIGN_CACHE_DIR",
        "str",
        None,
        "warm-plane cache home: persistent compile cache under "
        "<dir>/jax/<platform-tag> and the AOT warm-set manifest under "
        "<dir>/aot (TPU_SEQALIGN_COMPILE_CACHE=off still disables)",
    ),
    EnvVar(
        "SEQALIGN_PREWARM",
        "flag",
        False,
        "AOT-prewarm the scorer executables at process start (same as "
        "--prewarm): manifest replay + the problem's warm set",
    ),
    EnvVar(
        "TPU_SEQALIGN_STREAM_DEPTH",
        "int",
        4,
        "in-flight device batches in the streaming scorer",
    ),
    EnvVar(
        "TPU_SEQALIGN_FEED_OVERLAP",
        "flag",
        True,
        "double-buffer the host feed: prestage the next chunk's "
        "host->device transfers while the current chunk computes "
        "(0 disables; A/B hook)",
    ),
    EnvVar(
        "SEQALIGN_FAULTS",
        "str",
        None,
        "deterministic fault-injection spec (see --faults)",
    ),
    EnvVar(
        "SEQALIGN_FAULT_RETRIES",
        "int",
        0,
        "extra retry-budget floor when a fault spec is armed",
    ),
    EnvVar(
        "SEQALIGN_BACKOFF_BASE",
        "float",
        None,
        "override the retry policy's backoff base delay in seconds",
    ),
    EnvVar(
        "SEQALIGN_CHECK",
        "flag",
        False,
        "enable runtime dispatch-contract validation (same as --check)",
    ),
    EnvVar(
        "SEQALIGN_DEADLINE_S",
        "float",
        None,
        "watchdog deadline (seconds) around device work and coordinator "
        "collectives (same as --deadline; expiry is a transient fault)",
    ),
    EnvVar(
        "SEQALIGN_DRAIN",
        "flag",
        False,
        "pre-arm the graceful-preemption drain: the run flushes and "
        "exits 75 (resumable) at its first chunk boundary",
    ),
    EnvVar(
        "SEQALIGN_BEACON_S",
        "float",
        None,
        "liveness-beacon / shard-gather deadline (seconds) enabling the "
        "lost-shard rescue tier under --distributed batch runs",
    ),
    EnvVar(
        "SEQALIGN_METRICS",
        "flag",
        False,
        "arm the observability plane: counters/spans collected for the "
        "run (same as --metrics; implied by SEQALIGN_METRICS_OUT)",
    ),
    EnvVar(
        "SEQALIGN_METRICS_OUT",
        "str",
        None,
        "write the versioned JSON run report (plus a .prom Prometheus "
        "text sidecar) here on exit, including exits 65/75 (same as "
        "--metrics-out)",
    ),
    EnvVar(
        "SEQALIGN_HEARTBEAT_S",
        "float",
        None,
        "emit a periodic '[obs] ...' status line from the watchdog "
        "monitor thread every this-many quiet seconds (same as "
        "--heartbeat; implies --metrics)",
    ),
    EnvVar(
        "SEQALIGN_SERVE_PORT",
        "int",
        None,
        "loopback port for the --serve request socket (same as --port; "
        "0 = OS-assigned, announced on stderr)",
    ),
    EnvVar(
        "SEQALIGN_SERVE_MAX_QUEUE",
        "int",
        256,
        "serve admission cap: requests queued past this depth are "
        "rejected with a 'queue full' error record",
    ),
    EnvVar(
        "SEQALIGN_SERVE_WINDOW_S",
        "float",
        0.05,
        "serve gather window (seconds): after the first queued request "
        "the loop lingers this long so a concurrent burst coalesces "
        "into shared superblocks",
    ),
    EnvVar(
        "SEQALIGN_SERVE_BLOCK_ROWS",
        "int",
        64,
        "rows per serve superblock; every dispatch has exactly this row "
        "count (padded), pinning the compiled shapes",
    ),
    EnvVar(
        "SEQALIGN_SERVE_MAX_POP",
        "int",
        0,
        "max requests popped per serve tick (0 = unlimited); bounds one "
        "tick's latency under backlog",
    ),
    EnvVar(
        "SEQALIGN_SERVE_DEADLINE_S",
        "float",
        None,
        "default per-request deadline (seconds) for serve requests that "
        "carry no 'deadline_s' field; past-deadline requests are "
        "answered with a typed 'deadline' error instead of occupying "
        "superblock rows",
    ),
    EnvVar(
        "SEQALIGN_SERVE_COST_BUDGET_S",
        "float",
        4.0,
        "admission token bucket: max modelled superblock-wall seconds "
        "(analysis/costmodel) of admitted-but-unfinished serve work; "
        "over-budget requests get a typed 'overloaded' rejection with "
        "retry_after_s",
    ),
    EnvVar(
        "SEQALIGN_SERVE_COST_SCALE",
        "float",
        1.0,
        "admission cost-model refit multiplier (the load harness's "
        "closing loop): request prices are the modelled superblock "
        "wall x this scale, so a measured-load refit (load/refit.py, "
        "scripts/load_smoke.py) can calibrate the bucket to observed "
        "walls while the static model stays the audited prior; 1.0 = "
        "trust the prior",
    ),
    EnvVar(
        "SEQALIGN_SERVE_SHED_WAIT_S",
        "float",
        30.0,
        "load-shedding threshold: when the p90 of recent queue waits "
        "reaches this many seconds the serve loop escalates "
        "accept -> shed-new -> drain-only (de-escalates below half)",
    ),
    EnvVar(
        "SEQALIGN_SERVE_WRITE_TIMEOUT_S",
        "float",
        5.0,
        "per-connection socket send timeout (seconds): a client whose "
        "socket buffer stays full this long is classified dead and its "
        "sessions abandoned (0 disables)",
    ),
    EnvVar(
        "SEQALIGN_TRACE",
        "str",
        None,
        "write the request-scoped Perfetto/Chrome-trace JSON timeline "
        "here when the run exits (same as --trace-out; implies "
        "--metrics; distinct from --trace, the jax.profiler device "
        "trace directory)",
    ),
    EnvVar(
        "SEQALIGN_TELEMETRY_PORT",
        "int",
        None,
        "loopback port for the --serve plain-HTTP telemetry endpoint "
        "(same as --telemetry-port; 0 = OS-assigned, announced on "
        "stderr): GET /metrics | /healthz | /trace",
    ),
    EnvVar(
        "SEQALIGN_FLIGHTREC_DEPTH",
        "int",
        256,
        "flight recorder ring depth (bus events + span closures taped "
        "whenever --serve or --metrics is armed; dumped on watchdog "
        "expiry, breaker open, fatal exit, SIGUSR2; 0 disables)",
    ),
    EnvVar(
        "SEQALIGN_BREAKER_THRESHOLD",
        "int",
        3,
        "circuit breaker: transient primary-dispatch failures within "
        "the window that open the breaker (pinning the degraded "
        "backend; requires --degrade)",
    ),
    EnvVar(
        "SEQALIGN_BREAKER_WINDOW",
        "int",
        16,
        "circuit breaker failure-memory window, in serve-loop ticks "
        "(deterministic — never wall clock)",
    ),
    EnvVar(
        "SEQALIGN_BREAKER_COOLDOWN",
        "int",
        8,
        "serve-loop ticks an open breaker waits before probing the "
        "primary backend half-open",
    ),
    EnvVar(
        "SEQALIGN_FLEET_WORKERS",
        "int",
        0,
        "expected scoring-worker count for the elastic serve fleet "
        "(--fleet-board): an observability hint only — the fleet is "
        "elastic, workers join and leave mid-serve; the coordinator "
        "logs when the fleet first reaches this size",
    ),
    EnvVar(
        "SEQALIGN_LEASE_S",
        "float",
        2.0,
        "fleet superblock lease: nominal seconds a claimed (or never-"
        "claimed) offer may sit without a result before the coordinator "
        "fences its epoch and re-dispatches; converted to board-poll "
        "ticks so membership/lease decisions stay tick-counted",
    ),
    EnvVar(
        "SEQALIGN_WORKER_HEARTBEAT_S",
        "float",
        0.02,
        "fleet worker heartbeat/board-poll cadence in seconds; a worker "
        "whose heartbeat value stalls for a full lease window is "
        "declared dead and its claimed superblocks re-dispatched",
    ),
    EnvVar(
        "SEQALIGN_FLEET_MAX_REDISPATCH",
        "int",
        5,
        "re-dispatch attempts one fleet superblock may burn (the lease "
        "epoch doubles as the counter) before the coordinator dead-"
        "letters it to the local quarantine ladder (retry -> degrade -> "
        "poison bisection), so an offer no worker can finish still "
        "answers every request with a typed error instead of "
        "re-offering forever",
    ),
    EnvVar(
        "SEQALIGN_FLEET_GC_TICKS",
        "int",
        0,
        "grace window, in coordinator board-poll ticks, before the "
        "board GC sweeps a key classified as debris (retired epochs, "
        "dead generations' posts, dead workers' registrations); 0 "
        "means two lease windows — late enough that stale-post fencing "
        "was counted first",
    ),
    EnvVar(
        "SEQALIGN_FLEET_OBSSNAP_S",
        "float",
        0.25,
        "fleet worker observability-snapshot cadence in seconds: how "
        "often a --fleet-worker posts its bounded metrics + trace + "
        "flight-recorder snapshot to the board (overwritten in place); "
        "the coordinator federates these into per-worker /metrics "
        "families, merged Perfetto tracks, and the post-mortem tape it "
        "collects when the worker is declared dead",
    ),
    EnvVar(
        "JAX_COORDINATOR_ADDRESS",
        "str",
        None,
        "multi-host coordinator address for jax.distributed.initialize",
    ),
    EnvVar(
        "JAX_NUM_PROCESSES",
        "int",
        None,
        "multi-host process count for jax.distributed.initialize",
    ),
    EnvVar(
        "JAX_PROCESS_ID",
        "int",
        None,
        "this host's process index for jax.distributed.initialize",
    ),
)

_REGISTRY = {v.name: v for v in ENV_VARS}

_FLAG_TRUE = ("1", "true", "yes", "on")
_FLAG_FALSE = ("0", "false", "no", "off", "")


def _declared(name: str, kind: str) -> EnvVar:
    var = _REGISTRY.get(name)
    if var is None or var.kind != kind:
        raise KeyError(
            f"{name} is not a declared {kind} env var; add it to "
            "utils.platform.ENV_VARS (seqlint SEQ002 keeps reads here)"
        )
    return var


def env_str(name: str, default: str | None = None) -> str | None:
    """Raw string accessor for a declared env var."""
    var = _declared(name, "str")
    raw = os.environ.get(name)
    if raw is None:
        return default if default is not None else var.default
    return raw


def env_int(name: str, default: int | None = None) -> int | None:
    """Integer accessor; raises one uniform actionable ValueError on a
    malformed value (each former call site improvised its own)."""
    var = _declared(name, "int")
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default if default is not None else var.default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"{name} must be an integer, got {raw!r} ({var.doc})"
        ) from None


def env_float(name: str, default: float | None = None) -> float | None:
    """Float accessor with the same uniform error contract."""
    var = _declared(name, "float")
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default if default is not None else var.default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(
            f"{name} must be a number, got {raw!r} ({var.doc})"
        ) from None


def env_flag(name: str, default: bool | None = None) -> bool:
    """Boolean accessor: 1/true/yes/on vs 0/false/no/off (empty =
    unset); anything else is an error, not a silent False."""
    var = _declared(name, "flag")
    raw = os.environ.get(name)
    if raw is None:
        return bool(default if default is not None else var.default)
    low = raw.strip().lower()
    if low in _FLAG_TRUE:
        return True
    if low in _FLAG_FALSE:
        return False
    raise ValueError(
        f"{name} must be a boolean flag (1/0/true/false/yes/no/on/off), "
        f"got {raw!r} ({var.doc})"
    )


def apply_platform_override() -> None:
    """Re-apply ``JAX_PLATFORMS`` from the environment if a site hook
    overrode it.  No-op for TPU-targeting values."""
    envp = os.environ.get("JAX_PLATFORMS")
    if not envp:
        return
    # Only force non-TPU targets: the TPU plugin default is what site hooks
    # set up, and narrowing e.g. "axon,cpu" -> "axon" would drop a fallback.
    if any(p in envp for p in ("axon", "tpu")):
        return
    import jax

    if jax.config.jax_platforms != envp:
        jax.config.update("jax_platforms", envp)


def platform_tag() -> str:
    """The cache-partition tag for this process's platform configuration:
    ``JAX_PLATFORMS`` (or, unset, an init-free TPU-plugin-presence proxy —
    querying the backend here would initialize it, which must stay AFTER
    ``jax.distributed.initialize`` on multi-host) plus any virtual
    host-device count from ``XLA_FLAGS``.  Shared by the persistent
    compilation cache AND the AOT warm-set manifest (``aot/manifest``):
    both partition on it so writers and readers agree on the whole
    platform configuration, never just the backend name."""
    tag = os.environ.get("JAX_PLATFORMS", "").replace(",", "-")
    if not tag:
        import importlib.util

        tag = (
            "tpu-plugin"
            if importlib.util.find_spec("libtpu") is not None
            else "default"
        )
    for tok in os.environ.get("XLA_FLAGS", "").split():
        if "xla_force_host_platform_device_count" in tok:
            tag += "-hd" + tok.split("=")[-1]
    return tag


# Back-compat alias (pre-AOT-plane name).
_platform_tag = platform_tag


def cache_home() -> str | None:
    """The warm-plane root directory, or ``None`` when caching is
    disabled (``TPU_SEQALIGN_COMPILE_CACHE=off``/``0``).

    Precedence: ``SEQALIGN_CACHE_DIR`` (the warm-plane home: compile
    cache under ``<dir>/jax/<tag>``, AOT manifests under ``<dir>/aot``),
    else the legacy ``TPU_SEQALIGN_COMPILE_CACHE`` directory, else
    ``~/.cache/mpi_openmp_cuda_tpu``."""
    legacy = os.environ.get("TPU_SEQALIGN_COMPILE_CACHE")
    if legacy is not None and legacy.strip().lower() in ("off", "0", ""):
        return None
    explicit = os.environ.get("SEQALIGN_CACHE_DIR")
    if explicit:
        return explicit
    if legacy:
        return legacy
    return os.path.join(os.path.expanduser("~"), ".cache", "mpi_openmp_cuda_tpu")


def compilation_cache_dir() -> str | None:
    """The resolved, platform-partitioned persistent compile-cache
    directory, or ``None`` when disabled.

    A legacy explicit ``TPU_SEQALIGN_COMPILE_CACHE=<dir>`` keeps its
    pre-AOT layout ``<dir>/<tag>`` exactly (existing caches stay valid);
    the ``SEQALIGN_CACHE_DIR`` home and the default both use
    ``<home>/jax/<tag>``."""
    home = cache_home()
    if home is None:
        return None
    if os.environ.get("TPU_SEQALIGN_COMPILE_CACHE") and not os.environ.get(
        "SEQALIGN_CACHE_DIR"
    ):
        return os.path.join(home, platform_tag())
    return os.path.join(home, "jax", platform_tag())


def enable_compilation_cache() -> None:
    """Point JAX's persistent compilation cache at a stable directory.

    The reference deployment is a COLD batch run (`mpiexec -np 2 ./final
    < input.txt`, makefile:11): every invocation pays its full startup.
    Here a cold process pays ~10 s of XLA/Mosaic compiles — the dominant
    end-to-end cost on every fixture — so all entry points (CLI, native
    bridge, bench) enable the on-disk cache and the second cold process
    skips straight to execution (VERDICT r3 item 4).

    ``TPU_SEQALIGN_COMPILE_CACHE`` overrides the location; ``off`` (or
    ``0``) disables.  Explicit locations get the same per-platform-config
    subdirectory as the default (see ``compilation_cache_dir``): an override names
    where the cache lives, never permission to share one directory across
    platform configurations — that sharing is exactly the cross-config
    deserialization crash the partitioning exists to prevent.  Failures
    are non-fatal: a read-only home directory degrades to the in-memory
    cache, never to an error.  Idempotent and once-per-process: the
    native bridge calls this on every scoring batch, which must not
    repeat the mkdir/config writes on a hot path.
    """
    if getattr(enable_compilation_cache, "_done", False):
        return
    enable_compilation_cache._done = True
    # Partitioned by platform configuration (compilation_cache_dir).  One
    # shared directory is NOT safe: entries written by a TPU-plugin
    # process and read by a JAX_PLATFORMS=cpu process (or written under a
    # different virtual-device-count XLA_FLAGS) deserialize XLA:CPU
    # executables compiled for a different machine configuration —
    # observed as "Compile machine features ... doesn't match" warnings
    # and, reproducibly, a segfault inside
    # compilation_cache.get_executable_and_time during the test suite.
    # Writers and readers must share the tag exactly, so explicit
    # override paths are partitioned too.
    loc = compilation_cache_dir()
    if loc is None:
        return
    try:
        os.makedirs(loc, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", loc)
        # Cache every compile worth having: the kernel's Mosaic compiles
        # take seconds, but even sub-second XLA epilogues add up across
        # the six fixtures' bucket shapes.  (aot/compile.ensure_persistence
        # drops the floor to 0 during a prewarm so fast CPU executables
        # persist too.)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # pragma: no cover - depends on local FS/jax
        # advisory: the persistent cache is a speed-up — compiles still
        # happen, just uncached; the log line says why.
        from ..obs.events import log_line

        log_line(
            f"mpi_openmp_cuda_tpu: persistent compilation cache disabled ({e})"
        )
