"""Platform selection helper.

Some TPU environments install a sitecustomize hook that force-registers a
PJRT plugin and rewrites ``jax.config.jax_platforms`` at interpreter start,
which silently overrides a user's ``JAX_PLATFORMS=cpu``.  This helper
re-asserts the user's explicit choice (needed by the CPU-mesh test harness
and any non-TPU deployment) without touching the TPU default path.
"""

from __future__ import annotations

import os


def apply_platform_override() -> None:
    """Re-apply ``JAX_PLATFORMS`` from the environment if a site hook
    overrode it.  No-op for TPU-targeting values."""
    envp = os.environ.get("JAX_PLATFORMS")
    if not envp:
        return
    # Only force non-TPU targets: the TPU plugin default is what site hooks
    # set up, and narrowing e.g. "axon,cpu" -> "axon" would drop a fallback.
    if any(p in envp for p in ("axon", "tpu")):
        return
    import jax

    if jax.config.jax_platforms != envp:
        jax.config.update("jax_platforms", envp)
