"""Platform selection helper.

Some TPU environments install a sitecustomize hook that force-registers a
PJRT plugin and rewrites ``jax.config.jax_platforms`` at interpreter start,
which silently overrides a user's ``JAX_PLATFORMS=cpu``.  This helper
re-asserts the user's explicit choice (needed by the CPU-mesh test harness
and any non-TPU deployment) without touching the TPU default path.
"""

from __future__ import annotations

import os


def apply_platform_override() -> None:
    """Re-apply ``JAX_PLATFORMS`` from the environment if a site hook
    overrode it.  No-op for TPU-targeting values."""
    envp = os.environ.get("JAX_PLATFORMS")
    if not envp:
        return
    # Only force non-TPU targets: the TPU plugin default is what site hooks
    # set up, and narrowing e.g. "axon,cpu" -> "axon" would drop a fallback.
    if any(p in envp for p in ("axon", "tpu")):
        return
    import jax

    if jax.config.jax_platforms != envp:
        jax.config.update("jax_platforms", envp)


def _platform_tag() -> str:
    """The cache-partition tag for this process's platform configuration:
    ``JAX_PLATFORMS`` (or, unset, an init-free TPU-plugin-presence proxy —
    querying the backend here would initialize it, which must stay AFTER
    ``jax.distributed.initialize`` on multi-host) plus any virtual
    host-device count from ``XLA_FLAGS``."""
    tag = os.environ.get("JAX_PLATFORMS", "").replace(",", "-")
    if not tag:
        import importlib.util

        tag = (
            "tpu-plugin"
            if importlib.util.find_spec("libtpu") is not None
            else "default"
        )
    for tok in os.environ.get("XLA_FLAGS", "").split():
        if "xla_force_host_platform_device_count" in tok:
            tag += "-hd" + tok.split("=")[-1]
    return tag


def enable_compilation_cache() -> None:
    """Point JAX's persistent compilation cache at a stable directory.

    The reference deployment is a COLD batch run (`mpiexec -np 2 ./final
    < input.txt`, makefile:11): every invocation pays its full startup.
    Here a cold process pays ~10 s of XLA/Mosaic compiles — the dominant
    end-to-end cost on every fixture — so all entry points (CLI, native
    bridge, bench) enable the on-disk cache and the second cold process
    skips straight to execution (VERDICT r3 item 4).

    ``TPU_SEQALIGN_COMPILE_CACHE`` overrides the location; ``off`` (or
    ``0``) disables.  Explicit locations get the same per-platform-config
    subdirectory as the default (see ``_platform_tag``): an override names
    where the cache lives, never permission to share one directory across
    platform configurations — that sharing is exactly the cross-config
    deserialization crash the partitioning exists to prevent.  Failures
    are non-fatal: a read-only home directory degrades to the in-memory
    cache, never to an error.  Idempotent and once-per-process: the
    native bridge calls this on every scoring batch, which must not
    repeat the mkdir/config writes on a hot path.
    """
    if getattr(enable_compilation_cache, "_done", False):
        return
    enable_compilation_cache._done = True
    loc = os.environ.get("TPU_SEQALIGN_COMPILE_CACHE")
    if loc is not None and loc.strip().lower() in ("off", "0", ""):
        return
    if loc is None:
        loc = os.path.join(
            os.path.expanduser("~"), ".cache", "mpi_openmp_cuda_tpu", "jax"
        )
    # Partition the location by platform configuration.  One shared
    # directory is NOT safe: entries written by a TPU-plugin process and
    # read by a JAX_PLATFORMS=cpu process (or written under a different
    # virtual-device-count XLA_FLAGS) deserialize XLA:CPU executables
    # compiled for a different machine configuration — observed as
    # "Compile machine features ... doesn't match" warnings and,
    # reproducibly, a segfault inside
    # compilation_cache.get_executable_and_time during the test suite.
    # Writers and readers must share the tag exactly, so explicit
    # override paths are partitioned too.
    loc = os.path.join(loc, _platform_tag())
    try:
        os.makedirs(loc, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", loc)
        # Cache every compile worth having: the kernel's Mosaic compiles
        # take seconds, but even sub-second XLA epilogues add up across
        # the six fixtures' bucket shapes.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # pragma: no cover - depends on local FS/jax
        print(
            f"mpi_openmp_cuda_tpu: persistent compilation cache disabled ({e})",
            file=__import__("sys").stderr,
        )
