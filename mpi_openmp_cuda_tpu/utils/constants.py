"""Domain constants (reference parity: C1).

The reference fixes maximum sequence buffer sizes as compile-time constants
(`myProto.h:3-4`): Seq1 buffers are 3000 chars, each Seq2 record is a
fixed-stride 2000-char slot in a flat batch buffer.  The TPU build keeps the
same *capability* caps, but uses them only as upper bounds for shape
bucketing — actual compiled shapes are rounded up per batch, not always
padded to the maximum.
"""

from __future__ import annotations

# Maximum supported sequence lengths (reference: myProto.h:3-4).
BUF_SIZE_SEQ1: int = 3000
BUF_SIZE_SEQ2: int = 2000

# Character-code alphabet: 0 is reserved (pad / hyphen — the reference's
# pair matrices are 27x27 with "do not use index 0", main.c:38); codes
# 1..26 are 'A'..'Z'.
PAD_CODE: int = 0
ALPHABET_SIZE: int = 27

# Sentinel score for undefined problems (len2 > len1).  Matches the
# reference kernel's behaviour of reporting INT_MIN when the offset loop
# is empty (cudaFunctions.cu:113,116; SURVEY B12).
INT32_MIN: int = -(2**31)

# Number of scoring weights (w1..w4 in the spec; indexed 0..3 here).
NUM_WEIGHTS: int = 4

# Pair classification classes, in precedence order ($ > % > # > space),
# per spec PDF p.1-2 and the kernel's if/else chain (cudaFunctions.cu:88-95).
CLASS_DOLLAR: int = 0  # identical characters            -> +w[0]
CLASS_PERCENT: int = 1  # same conservative group          -> -w[1]
CLASS_HASH: int = 2  # same semi-conservative group     -> -w[2]
CLASS_SPACE: int = 3  # otherwise                        -> -w[3]

CLASS_SIGNS: str = "$%# "  # class id -> printable sign
