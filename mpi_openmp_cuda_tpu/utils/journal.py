"""Per-sequence result journal: checkpoint / resume (SURVEY §5).

The reference has no checkpointing — it is a stateless single-shot batch run
(stdin → stdout) whose failure model is fail-stop (`cudaFunctions.cu:15-33`).
SURVEY §5 names the upgrade worth building: a per-sequence result journal so
a preempted batch resumes at the first unscored sequence instead of
recomputing everything.

Format: JSON-lines.  Line 1 is a header carrying a fingerprint of the
problem (weights + Seq1 + the Seq2 batch); every later line is one scored
result ``{"index": i, "score": S, "n": N, "k": K}``.  A journal whose
fingerprint does not match the current problem is rejected (fail-stop, not
silent corruption).  Appends are flushed + fsync'd per chunk so a kill at
any point loses at most the in-flight chunk.
"""

from __future__ import annotations

import hashlib
import json
import os

import numpy as np

_FORMAT = "mpi_openmp_cuda_tpu.journal.v1"

# Sequences scored per journal append.  Small enough that a preemption
# loses little work; large enough to amortise dispatch overhead.
DEFAULT_CHUNK = 64


class JournalMismatchError(RuntimeError):
    """Journal on disk belongs to a different problem (or is corrupt)."""


def problem_fingerprint(problem) -> str:
    """Stable content hash of (weights, seq1, seq2 batch)."""
    h = hashlib.sha256()
    h.update(json.dumps([int(w) for w in problem.weights]).encode())
    h.update(problem.seq1_codes.tobytes())
    h.update(np.int64(len(problem.seq2_codes)).tobytes())
    for codes in problem.seq2_codes:
        h.update(np.int64(codes.size).tobytes())
        h.update(codes.tobytes())
    return h.hexdigest()


class ResultJournal:
    """Journalled scoring: skip already-scored sequences on restart."""

    def __init__(self, path: str, chunk: int = DEFAULT_CHUNK):
        self.path = path
        self.chunk = max(1, int(chunk))

    # -- on-disk state -----------------------------------------------------
    def _read(self, fingerprint: str) -> dict[int, tuple[int, int, int]]:
        """Load completed entries; reject foreign or malformed journals."""
        if not os.path.exists(self.path):
            return {}
        done: dict[int, tuple[int, int, int]] = {}
        with open(self.path, "r", encoding="utf-8") as f:
            header_line = f.readline()
            if not header_line.strip():
                return {}
            try:
                header = json.loads(header_line)
            except json.JSONDecodeError as e:
                raise JournalMismatchError(
                    f"journal {self.path!r}: unreadable header: {e}"
                ) from e
            if header.get("format") != _FORMAT:
                raise JournalMismatchError(
                    f"journal {self.path!r}: not a {_FORMAT} file"
                )
            if header.get("fingerprint") != fingerprint:
                raise JournalMismatchError(
                    f"journal {self.path!r} was written for a different problem; "
                    "delete it (or pass a fresh --journal path) to rescore"
                )
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                    done[int(rec["index"])] = (
                        int(rec["score"]),
                        int(rec["n"]),
                        int(rec["k"]),
                    )
                except (json.JSONDecodeError, KeyError, ValueError):
                    # A torn final line from a mid-write kill is expected;
                    # that sequence simply gets rescored.
                    continue
        return done

    def _append(self, f, indices, rows) -> None:
        for i, (score, n, k) in zip(indices, rows):
            f.write(
                json.dumps(
                    {"index": int(i), "score": int(score), "n": int(n), "k": int(k)}
                )
                + "\n"
            )
        f.flush()
        os.fsync(f.fileno())

    # -- the resumable scoring loop ---------------------------------------
    def score_with_resume(self, scorer, problem) -> np.ndarray:
        """Score ``problem``, journalling per chunk; returns [B, 3] int32."""
        fingerprint = problem_fingerprint(problem)
        done = self._read(fingerprint)
        total = len(problem.seq2_codes)
        pending = [i for i in range(total) if i not in done]

        results = np.zeros((total, 3), dtype=np.int32)
        for i, row in done.items():
            if i < total:
                results[i] = row

        fresh = not os.path.exists(self.path) or not done
        mode = "w" if fresh else "a"
        if not fresh:
            # A kill mid-write can leave a torn final line with no trailing
            # newline; appending straight onto it would glue the next record
            # to the fragment and lose it on the following resume.
            with open(self.path, "rb") as rf:
                rf.seek(0, os.SEEK_END)
                if rf.tell() > 0:
                    rf.seek(-1, os.SEEK_END)
                    needs_newline = rf.read(1) != b"\n"
                else:
                    needs_newline = False
        with open(self.path, mode, encoding="utf-8") as f:
            if not fresh and needs_newline:
                f.write("\n")
            if fresh:
                f.write(
                    json.dumps(
                        {
                            "format": _FORMAT,
                            "fingerprint": fingerprint,
                            "num_seq2": total,
                        }
                    )
                    + "\n"
                )
                f.flush()
                os.fsync(f.fileno())
            for start in range(0, len(pending), self.chunk):
                idx = pending[start : start + self.chunk]
                rows = scorer.score_codes(
                    problem.seq1_codes,
                    [problem.seq2_codes[i] for i in idx],
                    problem.weights,
                )
                for i, row in zip(idx, rows):
                    results[i] = row
                self._append(f, idx, rows)
        return results
