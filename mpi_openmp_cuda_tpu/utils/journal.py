"""Per-sequence result journal: checkpoint / resume (SURVEY §5).

The reference has no checkpointing — it is a stateless single-shot batch run
(stdin → stdout) whose failure model is fail-stop (`cudaFunctions.cu:15-33`).
SURVEY §5 names the upgrade worth building: a per-sequence result journal so
a preempted batch resumes at the first unscored sequence instead of
recomputing everything.

Format: JSON-lines.  Line 1 is a header carrying a fingerprint of the
problem (weights + Seq1 + the Seq2 batch); every later line is one scored
result ``{"index": i, "score": S, "n": N, "k": K}``.  A journal whose
fingerprint does not match the current problem is rejected (fail-stop, not
silent corruption).  Appends are flushed + fsync'd per chunk so a kill at
any point loses at most the in-flight chunk.

Two variants share the on-disk shape:

* :class:`ResultJournal` — whole-batch mode: the fingerprint covers every
  sequence up front (the problem is fully materialised anyway).
* :class:`StreamJournal` — ``--stream`` mode: the problem is never held in
  memory at once, so the header fingerprints only (weights, Seq1, N) and
  every record carries a short per-sequence content hash instead; on
  resume an entry is trusted only if its hash matches the re-parsed
  sequence (a changed input fails fast, same contract as batch mode).
"""

from __future__ import annotations

import hashlib
import json
import os

import numpy as np

from ..obs.metrics import drain_snapshot
from ..resilience.drain import DrainInterrupt, drain_requested
from ..resilience.faults import fire as _fault

_FORMAT = "mpi_openmp_cuda_tpu.journal.v1"
_STREAM_FORMAT = "mpi_openmp_cuda_tpu.stream-journal.v1"

# Sequences scored per journal append.  Small enough that a preemption
# loses little work; large enough to amortise dispatch overhead.
DEFAULT_CHUNK = 64


class JournalMismatchError(RuntimeError):
    """Journal on disk belongs to a different problem (or is corrupt)."""


def _read_records(path, fmt, fingerprint, parse_rec, foreign_hint="", mismatch_hint=""):
    """Shared journal reader: header validation + tolerant record parse.

    ``parse_rec(rec) -> (key, value)``; malformed lines (a torn tail from a
    mid-write kill) are skipped — those sequences simply get rescored.
    Event records (``{"event": ...}`` — e.g. the drain's resumable-exit
    marker) are skipped the same way: they are audit state, not results.

    Kill-shaped header damage is repaired, never escalated: a zero-length
    file, a header-only file, and a torn (newline-less, nothing-after-it)
    header line all read as an EMPTY journal — the header is fsync'd
    before the first record, so none of those shapes can hold resumable
    state.  A malformed header WITH content after it is real corruption
    and still fails fast.
    """
    if not os.path.exists(path):
        return {}
    done = {}
    with open(path, "r", encoding="utf-8") as f:
        header_line = f.readline()
        if not header_line.strip():
            return {}
        try:
            header = json.loads(header_line)
        except json.JSONDecodeError as e:
            if not header_line.endswith("\n") and not f.read(1):
                # Torn header from a mid-write kill: the header write is
                # fsync'd before any record, so a torn header means no
                # record was ever durable — fresh journal, not an error.
                return {}
            raise JournalMismatchError(
                f"journal {path!r}: unreadable header: {e}"
            ) from e
        if header.get("format") != fmt:
            raise JournalMismatchError(
                f"journal {path!r}: not a {fmt} file{foreign_hint}"
            )
        if header.get("fingerprint") != fingerprint:
            raise JournalMismatchError(
                f"journal {path!r} was written for a different problem"
                f"{mismatch_hint}; delete it (or pass a fresh --journal "
                "path) to rescore"
            )
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                key, value = parse_rec(rec)
                done[key] = value
            except (json.JSONDecodeError, KeyError, ValueError):
                continue
    return done


def _write_records(f, recs) -> None:
    """Append JSON records, then flush + fsync (a kill loses at most the
    in-flight chunk)."""
    # Fault site BEFORE any byte is written: an injected append failure
    # models a full kill of the in-flight chunk, never a torn record.
    _fault("journal_append")
    for rec in recs:
        f.write(json.dumps(rec) + "\n")
    f.flush()
    os.fsync(f.fileno())


def _write_event(f, name: str) -> None:
    """Append one flushed event record (e.g. the drain's resumable-exit
    marker).  Deliberately NOT a fault site: the event is advisory audit
    state written on the way out of an already-exceptional path — resume
    works whether or not it landed, and an injected failure here would
    only mask the drain in flight.

    When the obs plane is armed the record also carries a metrics
    snapshot (counters at the moment of the drain) — the resume reader
    skips event records wholesale, so the payload costs nothing on
    resume.  The snapshot's timing comes from the obs clock; this module
    stays clock-free (seqlint SEQ005)."""
    rec = {"event": name}
    payload = drain_snapshot()
    if payload:
        rec.update(payload)
    f.write(json.dumps(rec) + "\n")
    f.flush()
    os.fsync(f.fileno())


def problem_fingerprint(problem) -> str:
    """Stable content hash of (weights, seq1, seq2 batch)."""
    h = hashlib.sha256()
    h.update(json.dumps([int(w) for w in problem.weights]).encode())
    h.update(problem.seq1_codes.tobytes())
    h.update(np.int64(len(problem.seq2_codes)).tobytes())
    for codes in problem.seq2_codes:
        h.update(np.int64(codes.size).tobytes())
        h.update(codes.tobytes())
    return h.hexdigest()


def stream_fingerprint(weights, seq1_codes, num_seq2: int) -> str:
    """Header hash for streaming mode: (weights, Seq1, N) only — the batch
    itself is validated per record via :func:`seq_hash`."""
    h = hashlib.sha256()
    h.update(json.dumps([int(w) for w in weights]).encode())
    h.update(np.asarray(seq1_codes).tobytes())
    h.update(np.int64(num_seq2).tobytes())
    return h.hexdigest()


def seq_hash(codes) -> str:
    """Short per-sequence content hash (16 hex chars: collision odds over
    even a billion-sequence batch are negligible, and a collision only
    risks skipping a rescore, never wrong output for an unchanged input)."""
    return hashlib.sha256(np.asarray(codes).tobytes()).hexdigest()[:16]


class StreamJournal:
    """Per-sequence journal for the --stream pipeline.

    Usage: construct, :meth:`load` the validated done-map, then use as a
    context manager and :meth:`append` each freshly scored chunk::

        journal = StreamJournal(path, weights, seq1_codes, n)
        done = journal.load()
        with journal:
            journal.append(indices, hashes, rows)
    """

    def __init__(self, path: str, weights, seq1_codes, num_seq2: int):
        self.path = path
        self.fingerprint = stream_fingerprint(weights, seq1_codes, num_seq2)
        self._f = None
        self._fresh = True
        self._loaded = False

    def load(self) -> dict[int, tuple[str, tuple[int, int, int]]]:
        """index -> (seq_hash, (score, n, k)); rejects foreign journals."""
        done = _read_records(
            self.path,
            _STREAM_FORMAT,
            self.fingerprint,
            lambda rec: (
                int(rec["index"]),
                (
                    str(rec["h"]),
                    (int(rec["score"]), int(rec["n"]), int(rec["k"])),
                ),
            ),
            foreign_hint=" (a whole-batch journal cannot resume a --stream run)",
            mismatch_hint=" (weights/Seq1/N changed)",
        )
        self._fresh = not done
        self._loaded = True
        return done

    def __enter__(self):
        if not self._loaded:
            # A caller that skips load() must not bypass header validation
            # and silently truncate a resumable journal ('w' below): run
            # the load here (the done-map is discarded, but _fresh and the
            # fingerprint check now reflect the on-disk state).
            self.load()
        fresh = self._fresh or not os.path.exists(self.path)
        if not fresh:
            _repair_torn_tail(self.path)
        self._f = open(self.path, "w" if fresh else "a", encoding="utf-8")
        if fresh:
            self._f.write(
                json.dumps(
                    {"format": _STREAM_FORMAT, "fingerprint": self.fingerprint}
                )
                + "\n"
            )
            self._f.flush()
            os.fsync(self._f.fileno())
        return self

    def __exit__(self, *exc):
        closing, self._f = self._f, None
        closing.close()
        return False

    def append(self, indices, hashes, rows) -> None:
        _write_records(
            self._f,
            (
                {
                    "index": int(i),
                    "h": h,
                    "score": int(score),
                    "n": int(n),
                    "k": int(k),
                }
                for i, h, (score, n, k) in zip(indices, hashes, rows)
            ),
        )

    def append_event(self, name: str) -> None:
        """Append a flushed event record (the drain path's resumable-exit
        marker); the resume reader skips it like any non-result line."""
        _write_event(self._f, name)


def _repair_torn_tail(path: str) -> None:
    """Append a newline if a mid-write kill left a torn final line (gluing
    the next record onto the fragment would lose it on the next resume)."""
    with open(path, "rb") as rf:
        rf.seek(0, os.SEEK_END)
        if rf.tell() == 0:
            return
        rf.seek(-1, os.SEEK_END)
        torn = rf.read(1) != b"\n"
    if torn:
        with open(path, "a", encoding="utf-8") as f:
            f.write("\n")


class ResultJournal:
    """Journalled scoring: skip already-scored sequences on restart."""

    def __init__(self, path: str, chunk: int = DEFAULT_CHUNK):
        self.path = path
        self.chunk = max(1, int(chunk))

    # -- on-disk state -----------------------------------------------------
    def _read(self, fingerprint: str) -> dict[int, tuple[int, int, int]]:
        """Load completed entries; reject foreign or malformed journals."""
        return _read_records(
            self.path,
            _FORMAT,
            fingerprint,
            lambda rec: (
                int(rec["index"]),
                (int(rec["score"]), int(rec["n"]), int(rec["k"])),
            ),
        )

    def _append(self, f, indices, rows) -> None:
        _write_records(
            f,
            (
                {"index": int(i), "score": int(score), "n": int(n), "k": int(k)}
                for i, (score, n, k) in zip(indices, rows)
            ),
        )

    # -- the resumable scoring loop ---------------------------------------
    def load_done(self, problem) -> dict[int, tuple[int, int, int]]:
        """Read + validate the on-disk done-map for ``problem`` (empty if
        no journal exists).  The multi-host coordinator calls this before
        broadcasting the done indices (parallel.distributed
        broadcast_index_set) so every host derives the identical reduced
        schedule."""
        return self._read(problem_fingerprint(problem))

    def score_with_resume(
        self, scorer, problem, done=None, record: bool = True
    ) -> np.ndarray:
        """Score ``problem``, journalling per chunk; returns [B, 3] int32.

        ``done`` overrides the on-disk done-map (multi-host: every host
        receives the coordinator's map — or just its key set — so the
        chunked scoring schedule below is bitwise-identical across hosts;
        values may be None for hosts that only need the schedule).
        ``record=False`` runs that identical schedule WITHOUT touching the
        journal file — worker processes own no journal, they only have to
        stay inside the same collectives as the coordinator.
        """
        if done is None:
            done = self._read(problem_fingerprint(problem))
        total = len(problem.seq2_codes)
        pending = [i for i in range(total) if i not in done]

        results = np.zeros((total, 3), dtype=np.int32)
        for i, row in done.items():
            if i < total and row is not None:
                results[i] = row

        # ONE chunked loop for both modes: the chunking below IS the
        # cross-host collective schedule, so coordinator (append) and
        # workers (append=None) must run literally the same code.
        def _run(append):
            for start in range(0, len(pending), self.chunk):
                if append is not None and drain_requested():
                    # Chunk-boundary drain (coordinator/single-process
                    # only: workers run append=None and follow the
                    # coordinator's schedule).  Everything scored so far
                    # is already flushed + fsync'd; the caller appends
                    # the resumable-exit record and the CLI exits 75.
                    raise DrainInterrupt(
                        f"{total - len(pending) + start} of {total} "
                        "sequences journalled; rerun with --resume to "
                        "score the rest"
                    )
                idx = pending[start : start + self.chunk]
                rows = scorer.score_codes(
                    problem.seq1_codes,
                    [problem.seq2_codes[i] for i in idx],
                    problem.weights,
                )
                for i, row in zip(idx, rows):
                    results[i] = row
                if append is not None:
                    append(idx, rows)

        if not record:
            _run(None)
            return results

        fresh = not os.path.exists(self.path) or not done
        mode = "w" if fresh else "a"
        if not fresh:
            _repair_torn_tail(self.path)
        with open(self.path, mode, encoding="utf-8") as f:
            if fresh:
                f.write(
                    json.dumps(
                        {
                            "format": _FORMAT,
                            "fingerprint": problem_fingerprint(problem),
                            "num_seq2": total,
                        }
                    )
                    + "\n"
                )
                f.flush()
                os.fsync(f.fileno())
            try:
                _run(lambda idx, rows: self._append(f, idx, rows))
            except DrainInterrupt:
                _write_event(f, "drain")
                raise
        return results
