"""Per-phase timing + profiler harness (SURVEY §5 tracing/profiling gap).

The reference has no timers at all (the vendored StopWatch helpers are dead
code).  This provides the phase wall-clock harness (parse / setup / score /
print) and an optional ``jax.profiler`` trace context for TPU runs.
"""

from __future__ import annotations

import contextlib
import sys
import time
from dataclasses import dataclass, field


@dataclass
class PhaseTimer:
    """Accumulates named wall-clock phases; reports to stderr when enabled."""

    enabled: bool = False
    phases: list[tuple[str, float]] = field(default_factory=list)

    @contextlib.contextmanager
    def phase(self, name: str):
        start = time.perf_counter()
        try:
            yield
        finally:
            self.phases.append((name, time.perf_counter() - start))

    def report(self, out=None) -> None:
        if not self.enabled:
            return
        out = out or sys.stderr
        total = sum(d for _, d in self.phases)
        for name, dur in self.phases:
            print(f"[profile] {name:>16}: {dur * 1e3:10.2f} ms", file=out)
        print(f"[profile] {'total':>16}: {total * 1e3:10.2f} ms", file=out)


@contextlib.contextmanager
def device_trace(log_dir: str | None):
    """jax.profiler trace context; no-op when log_dir is None."""
    if log_dir is None:
        yield
        return
    import jax

    with jax.profiler.trace(log_dir):
        yield


def block_until_ready(tree):
    """Barrier helper for wall-clock measurement of async dispatch."""
    import jax

    return jax.block_until_ready(tree)
