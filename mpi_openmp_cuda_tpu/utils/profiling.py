"""Per-phase timing + profiler harness (SURVEY §5 tracing/profiling gap).

The reference has no timers at all (the vendored StopWatch helpers are dead
code).  Since the observability PR the real timing engine is
:mod:`..obs.spans`; :class:`PhaseTimer` stays as a thin shim over
:class:`~..obs.spans.SpanRecorder` preserving the ``--profile`` contract
(byte-compatible ``[profile]`` stderr report, a ``phases`` list of
``(name, seconds)`` tuples).  The CLI hands the shim the run's armed
recorder so profile phases and the run report's span section are one
measurement, not two.
"""

from __future__ import annotations

import contextlib
import sys

from ..obs.spans import SpanRecorder


class PhaseTimer:
    """Accumulates named wall-clock phases; reports to stderr when enabled.

    A shim over :class:`~..obs.spans.SpanRecorder`: ``phase()`` opens a
    top-level span, ``phases`` exposes the completed top-level spans,
    ``report()`` prints the historical byte-exact format.  Pass
    ``recorder=`` to share the obs plane's armed recorder.
    """

    def __init__(self, enabled: bool = False, recorder: SpanRecorder | None = None):
        self.enabled = bool(enabled)
        self._recorder = recorder if recorder is not None else SpanRecorder()

    @property
    def phases(self) -> list[tuple[str, float]]:
        return self._recorder.phases()

    def phase(self, name: str):
        return self._recorder.span(name)

    def report(self, out=None) -> None:
        if not self.enabled:
            return
        self._recorder.report(out or sys.stderr)


@contextlib.contextmanager
def device_trace(log_dir: str | None):
    """jax.profiler trace context; no-op when log_dir is None."""
    if log_dir is None:
        yield
        return
    try:
        import jax
    except ModuleNotFoundError as e:
        # A clear diagnostic instead of an ImportError traceback: --trace
        # is the only profiling feature that hard-requires jax.
        raise RuntimeError(
            "--trace needs jax (jax.profiler) which is not installed in "
            "this environment; install the jax extra or drop --trace"
        ) from e
    with jax.profiler.trace(log_dir):
        yield


def block_until_ready(tree):
    """Barrier helper for wall-clock measurement of async dispatch."""
    import jax

    return jax.block_until_ready(tree)
