"""Result self-check: oracle cross-validation of an accelerated run.

The reference ships data races that make its outputs nondeterministic
(SURVEY Appendix B: B2, B8, B11) and has no way to notice.  Here races are
designed out by construction (pure functional XLA), and this module adds the
runtime counterpart of a race detector / sanitizer (SURVEY §5): after an
accelerated batch is scored, a deterministic sample of sequences is rescored
on the host prefix-sum oracle (ops/oracle.py) and compared bit-exactly.
A mismatch is a framework bug, never input-dependent noise, so it is
fail-stop (C11 stance).
"""

from __future__ import annotations

import numpy as np

from ..ops.oracle import score_batch_oracle

# Bounded sample: the oracle is O(L1*L2) per sequence on the host, so a
# full-batch check would dwarf the accelerated run it validates.
DEFAULT_SAMPLE = 8


class SelfCheckError(RuntimeError):
    """Accelerated result disagrees with the host oracle."""


def sample_indices(total: int, sample: int = DEFAULT_SAMPLE) -> list[int]:
    """Deterministic spread over the batch: first, last, and evenly between.

    Deterministic (not random) so a failure reproduces exactly on rerun.
    """
    if total <= 0:
        return []
    n = min(total, max(1, sample))
    return sorted({int(i) for i in np.linspace(0, total - 1, n)})


def verify_results(
    problem, results: np.ndarray, sample: int = DEFAULT_SAMPLE
) -> int:
    """Rescore a sample on the host oracle; raise SelfCheckError on mismatch.

    Returns the number of sequences checked.
    """
    idx = sample_indices(len(problem.seq2_codes), sample)
    if not idx:
        return 0
    expected = score_batch_oracle(
        problem.seq1_codes,
        [problem.seq2_codes[i] for i in idx],
        problem.weights,
    )
    for i, exp in zip(idx, expected):
        got = tuple(int(v) for v in results[i])
        if got != tuple(exp):
            raise SelfCheckError(
                f"selfcheck: sequence #{i}: accelerated result "
                f"(score, n, k)={got} != oracle {tuple(exp)}"
            )
    return len(idx)
