"""utils subpackage of mpi_openmp_cuda_tpu."""
