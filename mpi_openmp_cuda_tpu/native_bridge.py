"""Python side of the native host-ABI shim (SURVEY §7.3 step 6, component C2).

The reference's only host↔device interface is the 4-function C ABI in
`myProto.h:7-10`; its CUDA side stages read-only state in `__constant__`
memory (`cudaFunctions.cu:35-61`) and scores a fixed-stride batch of
NUL-terminated records (`cudaFunctions.cu:178-242`).  The TPU build keeps
that ABI as the stable native surface: `native/tpu_backend.cpp` embeds
CPython and forwards one call per staged batch to :func:`score_strided`
below, which decodes the wire format and dispatches to the JAX scorer.

Wire format (chosen for a zero-dependency C side — plain bytes, no numpy
C API, no pybind11):

* sequences arrive as ASCII bytes (already uppercased by the C++ driver);
* the batch is one ``rows × stride`` byte buffer, each record a
  NUL-terminated C string (the reference's Scatter buffer layout,
  main.c:110-121);
* the two 27×27 0/1 membership matrices arrive as 729-byte blobs exactly
  as the host built them (C4's `build_mat` output shape);
* results return as ``rows × 3`` little-endian int32 ``(score, n, k)``
  triples packed into one bytes object.
"""

from __future__ import annotations

import numpy as np

from .models.encoding import encode
from .ops.dispatch import AlignmentScorer
from .ops.values import signed_weights
from .utils.constants import ALPHABET_SIZE
from .utils.platform import apply_platform_override, enable_compilation_cache


def value_table_from_levels(mat1: np.ndarray, mat2: np.ndarray, weights) -> np.ndarray:
    """[27, 27] signed pair-value table from host-built membership matrices.

    Applies the kernel's precedence chain ($ > % > # > space,
    cudaFunctions.cu:88-95): identity beats conservative beats
    semi-conservative beats mismatch — regardless of what the matrices say
    about the diagonal.
    """
    mat1 = np.asarray(mat1).reshape(ALPHABET_SIZE, ALPHABET_SIZE)
    mat2 = np.asarray(mat2).reshape(ALPHABET_SIZE, ALPHABET_SIZE)
    sw = signed_weights(weights)
    val = np.full((ALPHABET_SIZE, ALPHABET_SIZE), sw[3], dtype=np.int32)
    val[mat2 == 1] = sw[2]
    val[mat1 == 1] = sw[1]
    idx = np.arange(1, ALPHABET_SIZE)
    val[idx, idx] = sw[0]
    return val


def _decode_record(record: bytes) -> np.ndarray:
    """One fixed-stride record -> codes; C-string semantics (stop at NUL)."""
    nul = record.find(b"\0")
    if nul >= 0:
        record = record[:nul]
    return encode(record.decode("ascii"))


def score_strided(
    seq1: bytes,
    seq2_all: bytes,
    stride: int,
    rows: int,
    mat1: bytes,
    mat2: bytes,
    weights: tuple,
    backend: str,
    mesh: str | int,
) -> bytes:
    """Score a staged fixed-stride batch; returns rows*3 int32 as bytes.

    ``mesh`` is the CLI's full --mesh grammar ('N'/'batch:N' data
    parallel, 'seq:N' Seq1 ring-sharded, 'DxS' 2-D dp x sp), parsed by
    the same parser so the 4-function native ABI reaches every
    parallelism tier the framework has; '' or '0' (or 0 — the r1 integer
    form) runs single-device.
    """
    apply_platform_override()
    enable_compilation_cache()
    if rows <= 0:
        return b""
    if stride <= 0 or len(seq2_all) < rows * stride:
        raise ValueError(
            f"batch buffer too small: {len(seq2_all)} bytes for "
            f"{rows} rows x {stride} stride"
        )
    seq1_codes = encode(seq1.decode("ascii"))
    seq2_codes = [
        _decode_record(seq2_all[r * stride : (r + 1) * stride]) for r in range(rows)
    ]
    val = value_table_from_levels(
        np.frombuffer(mat1, dtype=np.int8), np.frombuffer(mat2, dtype=np.int8), weights
    )
    mesh = str(mesh)
    if mesh in ("", "0"):
        sharding = None
    else:
        from .parallel.specs import build_sharding

        sharding = build_sharding(mesh)
    scorer = AlignmentScorer(backend=backend, sharding=sharding)
    out = scorer.score_codes(seq1_codes, seq2_codes, list(weights), val_table=val)
    return np.ascontiguousarray(out, dtype="<i4").tobytes()
