"""Live telemetry: serve-socket verbs + a plain-HTTP Prometheus scrape.

The run report (obs/export.py) is a *post-mortem* artifact — it flushes
when the process exits.  A serving process is supposed to never exit,
so this module exposes the SAME live registry two ways while the loop
is still running:

* **Socket verbs** — a client already connected to the ndjson serve
  socket sends ``{"cmd": "metrics"}`` / ``{"cmd": "healthz"}`` /
  ``{"cmd": "trace"}`` and gets one JSON record back on the same
  connection (:func:`answer_cmd`, called inline from
  ``ServeLoop.ingest`` — telemetry is never queued and never priced
  against the admission bucket).
* **HTTP scrape** — ``--telemetry-port N`` (0 = OS-assigned; env
  ``SEQALIGN_TELEMETRY_PORT``) binds a loopback
  :class:`TelemetryServer` whose ``GET /metrics`` renders the live
  registry through the one Prometheus serializer
  (:func:`..obs.metrics.to_prometheus` — the same text a scraper sees
  from ``--metrics-out``'s textfile, just live), plus ``/healthz`` and
  ``/trace`` JSON endpoints.

Consistency stance: readers snapshot the registry WITHOUT pausing the
serve loop.  Registry mutation is plain dict arithmetic under the GIL,
so a concurrent ``dict(...)`` copy can only fail transiently
(``RuntimeError: dictionary changed size during iteration``) — the
snapshot helper retries a few times rather than taking a lock the hot
path would have to share.  The scrape is read-only by construction:
nothing here mutates the registry, the tracer, or the loop.
"""

from __future__ import annotations

import http.server
import json
import threading

from .metrics import active_metrics, fleet_to_prometheus, to_prometheus
from .trace import active_trace

#: Transient-retry budget for lock-free registry snapshots (see module
#: docstring — each attempt is a fresh dict copy, so one quiet moment
#: in the mutator suffices).
_SNAPSHOT_TRIES = 8


def live_snapshot() -> dict:
    """A JSON-ready copy of the armed registry (empty dict when the
    metrics plane is off), retried across concurrent mutation."""
    reg = active_metrics()
    if reg is None:
        return {}
    for _ in range(_SNAPSHOT_TRIES - 1):
        try:
            return reg.snapshot()
        except RuntimeError:
            continue
    return reg.snapshot()


def live_fleet() -> dict:
    """A detached copy of the gathered per-worker snapshots
    (``registry.fleet`` — empty when unarmed or no fleet), retried
    across concurrent mutation like :func:`live_snapshot`."""
    reg = active_metrics()
    if reg is None or not reg.fleet:
        return {}
    for _ in range(_SNAPSHOT_TRIES - 1):
        try:
            return dict(reg.fleet)
        except RuntimeError:
            continue
    return dict(reg.fleet)


def render_metrics() -> str:
    """The full ``/metrics`` body: the local registry's exposition plus
    the federated per-worker families (``worker="wid"`` labels) when
    the coordinator has gathered fleet snapshots.  Fleet HELP/TYPE
    heads are suppressed for families the local section already
    declared — one declaration per family, samples per origin."""
    local = to_prometheus(live_snapshot())
    fleet = live_fleet()
    if not fleet:
        return local
    heads = {
        ln.split()[2]
        for ln in local.splitlines()
        if ln.startswith("# TYPE ")
    }
    return local + fleet_to_prometheus(fleet, skip_heads=heads)


def answer_cmd(cmd: str, status: dict | None = None) -> dict:
    """One telemetry verb → one JSON-ready response record.

    Shared by the socket verbs and (indirectly, shape-wise) the HTTP
    endpoints so both planes answer identically.  Unknown verbs get a
    typed error record, not an exception — a bad verb must not kill the
    connection's reader thread.
    """
    if cmd == "metrics":
        return {"telemetry": "metrics", "metrics": live_snapshot()}
    if cmd == "healthz":
        return {"telemetry": "healthz", "status": dict(status or {"ok": True})}
    if cmd == "trace":
        tracer = active_trace()
        if tracer is None:
            return {
                "telemetry": "trace",
                "error": "trace plane not armed "
                "(--trace-out / SEQALIGN_TRACE)",
            }
        return {"telemetry": "trace", "trace": tracer.export()}
    return {
        "telemetry": cmd,
        "error": f"unknown telemetry cmd {cmd!r} "
        "(expected metrics | healthz | trace)",
    }


class TelemetryServer:
    """Loopback HTTP scrape endpoint over the live observability plane.

    ``start()`` binds 127.0.0.1 and serves from a daemon thread (request
    handling is also daemon-threaded, so a stalled scraper cannot wedge
    shutdown); ``close()`` is idempotent.  The server holds NO serve-loop
    state beyond the injected ``status`` callable — everything else it
    renders comes from the module-global armed planes.
    """

    def __init__(self, port: int, *, status=None):
        self.port = int(port)
        self.status = status
        self._httpd: http.server.ThreadingHTTPServer | None = None

    def start(self) -> int:
        """Bind and serve; returns the bound port (port 0 → assigned)."""
        status = self.status

        class Handler(http.server.BaseHTTPRequestHandler):
            # Scrapers poll; access logs on stderr would swamp the
            # heartbeat stream.
            def log_message(self, fmt, *fmt_args):
                pass

            def _reply(self, code: int, ctype: str, body: str) -> None:
                payload = body.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def _reply_json(self, record: dict, code: int = 200) -> None:
                self._reply(
                    code,
                    "application/json",
                    json.dumps(record, sort_keys=True) + "\n",
                )

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    self._reply(
                        200,
                        "text/plain; version=0.0.4; charset=utf-8",
                        render_metrics(),
                    )
                elif path == "/healthz":
                    self._reply_json(
                        answer_cmd(
                            "healthz",
                            status=status() if status is not None else None,
                        )
                    )
                elif path == "/trace":
                    self._reply_json(answer_cmd("trace"))
                else:
                    self._reply_json(
                        {
                            "error": f"unknown path {path!r} (expected "
                            "/metrics | /healthz | /trace)"
                        },
                        code=404,
                    )

        self._httpd = http.server.ThreadingHTTPServer(
            ("127.0.0.1", self.port), Handler
        )
        self._httpd.daemon_threads = True
        threading.Thread(
            target=self._httpd.serve_forever,
            name="seqalign-telemetry",
            daemon=True,
        ).start()
        return self._httpd.server_address[1]

    def close(self) -> None:
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        try:
            httpd.shutdown()
            httpd.server_close()
        except OSError:  # pragma: no cover - teardown best-effort
            pass
