"""The run-scoped event bus: one sink for everything the resilience
stack used to mutter to stderr.

The bus follows the fault registry's arming pattern
(:mod:`..resilience.faults`): module-global, armed per run by the CLI,
disarmed in its ``finally``, and a **single attribute check** when off —
so library callers and the hot path pay nothing unless observability
was asked for.

Publishers (all rare/failure paths, never per-element work):

=========================  ==============================================
``retry.attempt``          every caught transient failure
                           (:meth:`~..resilience.policy.RetryPolicy.run`)
``retry.backoff``          each nonzero backoff sleep (``delay`` field)
``degrade.transition``     each fall down the backend chain
                           (:meth:`~..resilience.degrade.BackendDegrader.step`)
``watchdog.expiry``        a guarded operation outlived the deadline
``watchdog.guard``         guard arm/disarm (``state`` field)
``drain.request``          the first drain signal of a run
``rescue.beacon_miss``     a worker missed the beacon deadline
``rescue.orphans``         orphaned sequences being rescored (``count``)
``fault.injected``         each deterministically injected fault
``recompile``              a backend compile (``analysis/recompile.py``)
``log``                    every :func:`log_line` diagnostic (``line``)
=========================  ==============================================

Subscribers are synchronous and must not raise; the
:class:`~.metrics.MetricsRegistry` subscribes its
:meth:`~.metrics.MetricsRegistry.record_event` to turn the stream into
counters.  Events are *in addition to* the existing stderr diagnostics,
never instead of them — the chaos suite's goldens assert on those lines.

:func:`log_line` is the blessed default logger for instrumented modules
(seqlint SEQ006 forbids direct ``print(..., file=sys.stderr)`` there):
byte-identical stderr output, but the line also rides the bus so run
reports can count diagnostics.
"""

from __future__ import annotations

import sys


class EventBus:
    """A synchronous fan-out of ``(event, fields)`` to subscribers."""

    __slots__ = ("_subscribers",)

    def __init__(self):
        self._subscribers: list = []

    def subscribe(self, fn) -> None:
        """Register ``fn(event: str, fields: dict)``; called in
        subscription order on every publish."""
        self._subscribers.append(fn)

    def publish(self, event: str, fields: dict) -> None:
        for fn in self._subscribers:
            fn(event, fields)


# The armed bus.  Module-global like the fault registry: the CLI owns
# the run; unit tests arm/disarm their own.
_active: EventBus | None = None


def activate_bus() -> EventBus:
    """Arm a fresh bus for one run; returns it for subscriptions."""
    global _active
    _active = EventBus()
    return _active


def deactivate_bus() -> None:
    global _active
    _active = None


def active_bus() -> EventBus | None:
    return _active


def publish(event: str, **fields) -> None:
    """Instrumentation hook: fan out to the armed bus, else no-op."""
    if _active is not None:
        _active.publish(event, fields)


def log_line(msg: str) -> None:
    """Print ``msg`` to stderr exactly as the old inline defaults did,
    mirroring it onto the armed bus as a ``log`` event first.  The
    default ``log=`` seam for every instrumented module (SEQ006)."""
    if _active is not None:
        _active.publish("log", {"line": msg})
    print(msg, file=sys.stderr)
