"""Observability plane: metrics, spans, events, exports (ISSUE 5).

The package is the single sink for everything the system previously
muttered to stderr: the resilience stack publishes events onto
:mod:`.events`, :mod:`.metrics` folds them into counters, :mod:`.spans`
times the run's phases and per-chunk work, and :mod:`.export` writes
the versioned run report / Prometheus sidecar and the heartbeat line.

Everything is **disabled by default**: until :func:`arm_observability`
runs (the CLI arms per run under ``--metrics``/``--metrics-out``/
``--heartbeat``), every instrumentation hook in the package is a single
attribute check and allocates nothing.
"""

from __future__ import annotations

from . import (  # noqa: F401  (re-exports)
    events,
    export,
    flightrec,
    metrics,
    spans,
    trace,
)


def arm_observability(
    clock=None, span_clock=None, *, with_trace=False, flightrec_depth=0
):
    """Arm the full plane for one run: a fresh registry subscribed to a
    fresh bus, plus a fresh span recorder.  Returns ``(registry,
    recorder)``.  ``with_trace`` additionally arms the Chrome-trace
    recorder (bus + span-close subscriber); ``flightrec_depth > 0``
    arms the flight recorder's ring at that depth.  Also registers the
    backend-compile listener so recompiles land on the bus
    (best-effort: a jax-less install still gets counters and spans)."""
    registry = metrics.activate_metrics(clock)
    bus = events.activate_bus()
    bus.subscribe(registry.record_event)
    recorder = spans.activate_spans(span_clock)
    if with_trace:
        tracer = trace.activate_trace(span_clock)
        bus.subscribe(tracer.record_event)
        recorder.listeners.append(tracer.span_closed)
    if flightrec_depth and flightrec_depth > 0:
        frec = flightrec.activate_flightrec(flightrec_depth, clock)
        bus.subscribe(frec.record_event)
        recorder.listeners.append(frec.span_closed)
    try:
        from ..analysis.recompile import compile_count

        compile_count()  # registering the listener is its side effect
    except Exception:
        # advisory: the recompile listener is observability only —
        # scoring never depends on it being armed.
        pass
    return registry, recorder


def disarm_observability() -> None:
    """Tear the plane down (the CLI's finally; idempotent)."""
    flightrec.deactivate_flightrec()
    trace.deactivate_trace()
    spans.deactivate_spans()
    events.deactivate_bus()
    metrics.deactivate_metrics()
