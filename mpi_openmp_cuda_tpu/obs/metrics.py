"""Process-wide metrics: counters, gauges, histograms, run reports.

:class:`MetricsRegistry` is the run's single numeric sink.  It takes an
**injectable monotonic clock** so tests use a fake clock and stay
byte-deterministic — and so every wall-clock read of the observability
plane lives in this file and :mod:`.spans`, never in the deterministic
``resilience/`` / ``utils/journal.py`` paths (seqlint SEQ005 is scoped
per file; those modules only ever hand us *events*, not times).

Two export formats share one serializer:

* the **versioned JSON run report** (``--metrics-out``), shape
  ``{"schema": ..., "schema_version": N, "kind": ..., ...}`` — the same
  envelope ``bench.py`` wraps its result blob in, so ``BENCH_*.json``
  and run reports validate against the one :func:`validate_report`;
* a **Prometheus text-format** sidecar (``<out>.prom``), counters as
  ``seqalign_<name>_total``, histograms as summaries.

Like the fault registry, the module-global hooks (:func:`inc` /
:func:`gauge` / :func:`observe`) are a single attribute check when no
registry is armed — the hot path pays nothing with metrics off.
"""

from __future__ import annotations

import time

#: The one report envelope (run reports AND bench blobs).
RUN_REPORT_SCHEMA = "mpi_openmp_cuda_tpu.run-report"
RUN_REPORT_VERSION = 1

# The event -> counter mapping (the bus side of the catalogue documented
# in docs/ARCHITECTURE.md §10).  Events not listed here carry their own
# handling in record_event.
_EVENT_COUNTERS = {
    "retry.attempt": "retry_attempts",
    "degrade.transition": "degrade_transitions",
    "watchdog.expiry": "deadline_expiries",
    "drain.request": "drain_requests",
    "fault.injected": "faults_injected",
    "recompile": "recompiles",
    "log": "log_lines",
}


class Histogram(dict):
    """One count/sum/min/max summary, generalised out of the registry so
    any caller (serve latency, backoff delays) shares the exact shape
    :func:`validate_report` checks.  Subclassing ``dict`` keeps snapshots
    and report serialisation plain-JSON for free."""

    __slots__ = ()

    def observe(self, value: float) -> None:
        if not self:
            self["count"] = 1
            self["sum"] = value
            self["min"] = value
            self["max"] = value
            return
        self["count"] += 1
        self["sum"] += value
        self["min"] = min(self["min"], value)
        self["max"] = max(self["max"], value)


class MetricsRegistry:
    """One run's counters/gauges/histograms behind an injectable clock.

    ``clock`` must be monotonic (``time.monotonic`` by default); tests
    pass a fake.  All mutation is plain dict arithmetic under the GIL —
    the only off-thread writer is the watchdog monitor's expiry event,
    for which per-key increments are atomic enough.
    """

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._start = clock()
        self.counters: dict[str, int | float] = {}
        self.gauges: dict[str, int | float | str] = {}
        self.histograms: dict[str, Histogram] = {}
        # Per-host snapshots gathered by the coordinator under
        # --distributed (obs/export.py): process id -> snapshot dict.
        self.fleet: dict[str, dict] = {}

    def inc(self, name: str, n: int | float = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n

    def gauge(self, name: str, value) -> None:
        self.gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        h = self.histograms.get(name)
        if h is None:
            h = self.histograms[name] = Histogram()
        h.observe(value)

    def uptime_s(self) -> float:
        return self._clock() - self._start

    # -- the bus subscriber ------------------------------------------------
    def record_event(self, event: str, fields: dict) -> None:
        """Turn one bus event into counters (subscribed by the CLI)."""
        name = _EVENT_COUNTERS.get(event)
        if name is not None:
            self.inc(name)
            return
        if event == "retry.backoff":
            self.inc("backoff_waits")
            self.observe("backoff_delay_s", float(fields["delay"]))
        elif event == "watchdog.guard":
            self.inc(
                "guard_arms"
                if fields.get("state") == "armed"
                else "guard_disarms"
            )
        elif event == "rescue.beacon_miss":
            self.inc("beacon_misses")
        elif event == "rescue.orphans":
            self.inc("rescued_sequences", int(fields.get("count", 0)))
        elif event == "serve.request.admitted":
            self.inc("serve_requests")
            self.gauge("queue_depth", int(fields.get("depth", 0)))
        elif event == "serve.request.rejected":
            self.inc("serve_rejections")
        elif event == "serve.request.done":
            self.inc("serve_completed")
            self.observe(
                "request_latency_s", float(fields.get("latency_s", 0.0))
            )
        elif event == "serve.batch.dispatch":
            self.inc("serve_batches")
            self.gauge("batch_fill_ratio", float(fields.get("fill", 0.0)))
            self.gauge("queue_depth", int(fields.get("depth", 0)))
        elif event == "serve.request.failed":
            # Deadline misses get their own SLO counter; every other
            # typed failure (poison isolation, ...) shares one.
            if fields.get("error") == "deadline":
                self.inc("serve_deadline_rejections")
            else:
                self.inc("serve_failures")
        elif event == "serve.request.shed":
            self.inc("serve_shed")
        elif event == "serve.shed.state":
            self.inc("serve_shed_transitions")
            self.gauge("shed_state", str(fields.get("state", "")))
        elif event == "serve.queue.wait":
            self.observe("queue_wait_s", float(fields.get("wait_s", 0.0)))
        elif event == "serve.request.abandoned":
            self.inc("serve_abandoned")
        elif event == "serve.request.poisoned":
            self.inc("serve_poisoned")
        elif event == "serve.block.failed":
            self.inc("serve_block_failures")
        elif event == "serve.client.lost":
            self.inc("serve_clients_lost")
        elif event.startswith("breaker."):
            # breaker.open / breaker.half_open / breaker.close -> one
            # counter each, plus the current-state gauge the chaos tier
            # reads back out of the run report.
            what = event.partition(".")[2]
            self.inc(f"breaker_{what}s")
            self.gauge(
                "breaker_state", "closed" if what == "close" else what
            )
        else:
            # Forward-compatible: an unmapped event still leaves a trace.
            self.inc(f"events.{event}")

    # -- snapshots ---------------------------------------------------------
    def record_fleet(self, host, snapshot: dict) -> None:
        self.fleet[str(host)] = snapshot

    def snapshot(self) -> dict:
        """A JSON-ready copy of the registry (no fleet: snapshots are
        what the fleet section is MADE of)."""
        return {
            "uptime_s": round(self.uptime_s(), 6),
            "counters": dict(self.counters),
            "gauges": dict(self.gauges),
            "histograms": {k: dict(v) for k, v in self.histograms.items()},
        }


# The armed registry (same lifecycle as the fault registry).
_active: MetricsRegistry | None = None


def activate_metrics(clock=None) -> MetricsRegistry:
    """Arm a fresh registry for one run; returns it for inspection."""
    global _active
    _active = MetricsRegistry(clock if clock is not None else time.monotonic)
    return _active


def deactivate_metrics() -> None:
    global _active
    _active = None


def active_metrics() -> MetricsRegistry | None:
    return _active


def inc(name: str, n: int | float = 1) -> None:
    """Instrumentation hook: count on the armed registry, else no-op."""
    if _active is not None:
        _active.inc(name, n)


def gauge(name: str, value) -> None:
    if _active is not None:
        _active.gauge(name, value)


def observe(name: str, value: float) -> None:
    if _active is not None:
        _active.observe(name, value)


def drain_snapshot() -> dict | None:
    """The extra payload the journal's ``{"event": "drain"}`` record
    carries when metrics are armed (None otherwise) — the journal itself
    never reads a clock (SEQ005); the uptime inside comes from here."""
    if _active is None:
        return None
    return {"metrics": _active.snapshot()}


# -- the shared report serializer ------------------------------------------


def wrap_report(kind: str, body: dict, *, meta: dict | None = None) -> dict:
    """The one report envelope: ``bench.py`` wraps its blob with
    ``kind="bench"``, the CLI's run report uses ``kind="run"``, and the
    static schedule auditor emits ``kind="schedule-audit"`` — all
    validate against :func:`validate_report`."""
    rec: dict = {
        "schema": RUN_REPORT_SCHEMA,
        "schema_version": RUN_REPORT_VERSION,
        "kind": kind,
    }
    if meta:
        rec["meta"] = dict(meta)
    rec.update(body)
    return rec


def run_report(
    registry: MetricsRegistry,
    *,
    spans=None,
    exit_code: int | None = None,
    meta: dict | None = None,
) -> dict:
    """The ``--metrics-out`` JSON document for one finished run."""
    body = registry.snapshot()
    if spans is not None:
        body["spans"] = {
            "phases": [[name, round(dur, 6)] for name, dur in spans.phases()],
            "totals": {
                path: round(total, 6)
                for path, total in sorted(spans.totals().items())
            },
        }
    if exit_code is not None:
        body["exit_code"] = int(exit_code)
    if registry.fleet:
        body["hosts"] = dict(registry.fleet)
    return wrap_report("run", body, meta=meta)


def validate_report(rec) -> None:
    """Schema gate for any wrapped report (run or bench); raises one
    ValueError naming every problem (``make metrics-smoke`` and the
    chaos tests call this)."""
    problems: list[str] = []
    if not isinstance(rec, dict):
        raise ValueError(f"report must be a JSON object, got {type(rec).__name__}")
    if rec.get("schema") != RUN_REPORT_SCHEMA:
        problems.append(f"schema: want {RUN_REPORT_SCHEMA!r}, got {rec.get('schema')!r}")
    ver = rec.get("schema_version")
    if not isinstance(ver, int) or ver < 1:
        problems.append(f"schema_version: want int >= 1, got {ver!r}")
    kind = rec.get("kind")
    if not isinstance(kind, str) or not kind:
        problems.append(f"kind: want a nonempty string, got {kind!r}")
    if kind == "run":
        for section in ("counters", "gauges", "histograms"):
            if not isinstance(rec.get(section), dict):
                problems.append(f"{section}: want an object, got {rec.get(section)!r}")
        for name, v in (rec.get("counters") or {}).items():
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                problems.append(f"counters[{name!r}]: want a number, got {v!r}")
        for name, h in (rec.get("histograms") or {}).items():
            if not isinstance(h, dict) or set(h) != {"count", "sum", "min", "max"}:
                problems.append(
                    f"histograms[{name!r}]: want count/sum/min/max, got {h!r}"
                )
        if not isinstance(rec.get("uptime_s"), (int, float)):
            problems.append(f"uptime_s: want a number, got {rec.get('uptime_s')!r}")
        if "exit_code" in rec and not isinstance(rec["exit_code"], int):
            problems.append(f"exit_code: want an int, got {rec['exit_code']!r}")
        spans = rec.get("spans")
        if spans is not None:
            if not isinstance(spans, dict) or not isinstance(
                spans.get("phases"), list
            ) or not isinstance(spans.get("totals"), dict):
                problems.append(f"spans: want {{phases: [], totals: {{}}}}, got {spans!r}")
    elif kind == "bench":
        if "metric" not in rec or "value" not in rec:
            problems.append("bench report: want metric and value fields")
    elif kind == "schedule-audit":
        # scripts/schedule_audit.py's cost-sheet + trace-audit report.
        sheet = rec.get("cost_sheet")
        if not isinstance(sheet, dict):
            problems.append(
                f"cost_sheet: want an object, got {sheet!r}"
            )
        else:
            if not isinstance(sheet.get("buckets"), list):
                problems.append("cost_sheet.buckets: want a list")
            totals = sheet.get("totals")
            if totals is not None and (
                not isinstance(totals, dict)
                or not isinstance(totals.get("launches"), int)
                or not isinstance(totals.get("executables"), int)
            ):
                problems.append(
                    "cost_sheet.totals: want launches/executables ints, "
                    f"got {totals!r}"
                )
            pred = sheet.get("predicted_mfu_vs_feed_roofline")
            if pred is not None and not isinstance(pred, (int, float)):
                problems.append(
                    "cost_sheet.predicted_mfu_vs_feed_roofline: want a "
                    f"number or null, got {pred!r}"
                )
        audit = rec.get("trace_audit")
        if not isinstance(audit, dict):
            problems.append(f"trace_audit: want an object, got {audit!r}")
        else:
            if not isinstance(audit.get("buckets"), list):
                problems.append("trace_audit.buckets: want a list")
            don = audit.get("donation")
            if not isinstance(don, dict) or "undonated_large_buffers" not in (
                don or {}
            ):
                problems.append(
                    "trace_audit.donation: want an object with "
                    f"undonated_large_buffers, got {don!r}"
                )
        if not isinstance(rec.get("entry_points"), list):
            problems.append(
                f"entry_points: want a list, got {rec.get('entry_points')!r}"
            )
    elif kind == "aot-manifest":
        # aot/manifest.py's warm-set manifest.
        fp = rec.get("fingerprint")
        if not isinstance(fp, dict) or not isinstance(fp.get("digest"), str):
            problems.append(
                f"fingerprint: want an object with a digest string, got {fp!r}"
            )
        entries = rec.get("entries")
        if not isinstance(entries, list):
            problems.append(f"entries: want a list, got {entries!r}")
        else:
            for i, e in enumerate(entries):
                if not isinstance(e, dict):
                    problems.append(f"entries[{i}]: want an object, got {e!r}")
                    continue
                if not isinstance(e.get("cache_key"), list):
                    problems.append(f"entries[{i}].cache_key: want a list")
                if not isinstance(e.get("fingerprint"), str):
                    problems.append(f"entries[{i}].fingerprint: want a string")
                if not isinstance(e.get("compile_wall_s"), (int, float)):
                    problems.append(
                        f"entries[{i}].compile_wall_s: want a number"
                    )
        if not isinstance(rec.get("stale"), list):
            problems.append(f"stale: want a list, got {rec.get('stale')!r}")
        totals = rec.get("totals")
        if not isinstance(totals, dict) or not isinstance(
            totals.get("entries"), int
        ):
            problems.append(
                f"totals: want an object with an int entry count, got {totals!r}"
            )
    if problems:
        raise ValueError(
            "invalid run report: " + "; ".join(problems)
        )


def _fmt_num(v) -> str:
    return repr(v) if isinstance(v, float) else str(v)


def to_prometheus(snapshot: dict, *, prefix: str = "seqalign") -> str:
    """Prometheus text exposition of one registry snapshot: counters as
    ``_total``, numeric gauges verbatim, string gauges as ``_info``
    labels, histograms as summaries with min/max gauges."""
    lines: list[str] = []
    for name in sorted(snapshot.get("counters", ())):
        m = f"{prefix}_{name.replace('.', '_')}_total"
        lines.append(f"# TYPE {m} counter")
        lines.append(f"{m} {_fmt_num(snapshot['counters'][name])}")
    for name in sorted(snapshot.get("gauges", ())):
        v = snapshot["gauges"][name]
        m = f"{prefix}_{name.replace('.', '_')}"
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {_fmt_num(v)}")
        else:
            lines.append(f"# TYPE {m}_info gauge")
            lines.append(f'{m}_info{{value="{v}"}} 1')
    for name in sorted(snapshot.get("histograms", ())):
        h = snapshot["histograms"][name]
        m = f"{prefix}_{name.replace('.', '_')}"
        lines.append(f"# TYPE {m} summary")
        lines.append(f"{m}_count {_fmt_num(h['count'])}")
        lines.append(f"{m}_sum {_fmt_num(h['sum'])}")
        lines.append(f"# TYPE {m}_min gauge")
        lines.append(f"{m}_min {_fmt_num(h['min'])}")
        lines.append(f"# TYPE {m}_max gauge")
        lines.append(f"{m}_max {_fmt_num(h['max'])}")
    up = snapshot.get("uptime_s")
    if up is not None:
        lines.append(f"# TYPE {prefix}_uptime_seconds gauge")
        lines.append(f"{prefix}_uptime_seconds {_fmt_num(up)}")
    return "\n".join(lines) + "\n"
