"""Process-wide metrics: counters, gauges, histograms, run reports.

:class:`MetricsRegistry` is the run's single numeric sink.  It takes an
**injectable monotonic clock** so tests use a fake clock and stay
byte-deterministic — and so every wall-clock read of the observability
plane lives in this file and :mod:`.spans`, never in the deterministic
``resilience/`` / ``utils/journal.py`` paths (seqlint SEQ005 is scoped
per file; those modules only ever hand us *events*, not times).

Two export formats share one serializer:

* the **versioned JSON run report** (``--metrics-out``), shape
  ``{"schema": ..., "schema_version": N, "kind": ..., ...}`` — the same
  envelope ``bench.py`` wraps its result blob in, so ``BENCH_*.json``
  and run reports validate against the one :func:`validate_report`;
* a **Prometheus text-format** sidecar (``<out>.prom``), counters as
  ``seqalign_<name>_total``, histograms as summaries.

Like the fault registry, the module-global hooks (:func:`inc` /
:func:`gauge` / :func:`observe`) are a single attribute check when no
registry is armed — the hot path pays nothing with metrics off.
"""

from __future__ import annotations

import collections
import math
import time

#: The one report envelope (run reports AND bench blobs).
RUN_REPORT_SCHEMA = "mpi_openmp_cuda_tpu.run-report"
RUN_REPORT_VERSION = 1

# The event -> counter mapping (the bus side of the catalogue documented
# in docs/ARCHITECTURE.md §10).  Events not listed here carry their own
# handling in record_event.
_EVENT_COUNTERS = {
    "retry.attempt": "retry_attempts",
    "degrade.transition": "degrade_transitions",
    "watchdog.expiry": "deadline_expiries",
    "drain.request": "drain_requests",
    "fault.injected": "faults_injected",
    "recompile": "recompiles",
    "log": "log_lines",
}


def percentile(values, q: float) -> float:
    """Nearest-rank percentile over any sized collection (0.0 when
    empty).  THE one percentile in the package: the SLO shed machine's
    internal p90 (``serve/slo.py``) and every histogram's p50/p90/p99
    summary field are this exact function, so report numbers and
    shedding decisions can never disagree on rank arithmetic."""
    if not values:
        return 0.0
    ordered = sorted(values)
    return ordered[min(len(ordered) - 1, int(q * len(ordered)))]


#: Explicit bucket boundaries (seconds) for the latency-shaped
#: histograms.  A histogram created with bounds additionally maintains
#: cumulative ``buckets`` counts and p50/p90/p99 summary fields — the
#: run-report envelope and the Prometheus rendering both follow.
HISTOGRAM_BUCKETS: dict[str, tuple[float, ...]] = {
    "queue_wait_s": (0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0, 60.0),
    "request_latency_s": (0.005, 0.02, 0.1, 0.5, 2.0, 10.0, 60.0, 300.0),
    "backoff_delay_s": (0.01, 0.05, 0.25, 1.0, 5.0, 30.0),
}

#: Recent-observation window the percentile summary fields are computed
#: over (bounded: a serve process observes forever).
_SAMPLE_WINDOW = 512


def _bucket_label(bound: float) -> str:
    return f"{bound:g}"


class Histogram(dict):
    """One count/sum/min/max summary, generalised out of the registry so
    any caller (serve latency, backoff delays) shares the exact shape
    :func:`validate_report` checks.  Subclassing ``dict`` keeps snapshots
    and report serialisation plain-JSON for free.

    With explicit ``bounds`` the histogram additionally keeps cumulative
    per-bucket counts (Prometheus ``le`` semantics, ``+Inf`` included)
    and p50/p90/p99 fields over a bounded window of recent observations.
    """

    __slots__ = ("_bounds", "_samples")

    def __init__(self, bounds=None):
        super().__init__()
        self._bounds = tuple(float(b) for b in bounds) if bounds else ()
        self._samples = (
            collections.deque(maxlen=_SAMPLE_WINDOW) if self._bounds else None
        )

    def observe(self, value: float) -> None:
        if not self:
            self["count"] = 1
            self["sum"] = value
            self["min"] = value
            self["max"] = value
        else:
            self["count"] += 1
            self["sum"] += value
            self["min"] = min(self["min"], value)
            self["max"] = max(self["max"], value)
        if self._bounds:
            buckets = self.get("buckets")
            if buckets is None:
                buckets = self["buckets"] = {
                    _bucket_label(b): 0 for b in self._bounds
                }
                buckets["+Inf"] = 0
            for b in self._bounds:
                if value <= b:
                    buckets[_bucket_label(b)] += 1
            buckets["+Inf"] += 1
            self._samples.append(value)
            self["p50"] = percentile(self._samples, 0.50)
            self["p90"] = percentile(self._samples, 0.90)
            self["p99"] = percentile(self._samples, 0.99)

    def snapshot(self) -> dict:
        """A detached plain-dict copy (nested buckets included) — live
        telemetry scrapes must not alias the mutating registry."""
        out = dict(self)
        if "buckets" in out:
            out["buckets"] = dict(out["buckets"])
        return out


class MetricsRegistry:
    """One run's counters/gauges/histograms behind an injectable clock.

    ``clock`` must be monotonic (``time.monotonic`` by default); tests
    pass a fake.  All mutation is plain dict arithmetic under the GIL —
    the only off-thread writer is the watchdog monitor's expiry event,
    for which per-key increments are atomic enough.
    """

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._start = clock()
        self.counters: dict[str, int | float] = {}
        self.gauges: dict[str, int | float | str] = {}
        self.histograms: dict[str, Histogram] = {}
        # Per-host snapshots gathered by the coordinator under
        # --distributed (obs/export.py): process id -> snapshot dict.
        self.fleet: dict[str, dict] = {}

    def inc(self, name: str, n: int | float = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n

    def gauge(self, name: str, value) -> None:
        self.gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        h = self.histograms.get(name)
        if h is None:
            h = self.histograms[name] = Histogram(
                HISTOGRAM_BUCKETS.get(name)
            )
        h.observe(value)

    def uptime_s(self) -> float:
        return self._clock() - self._start

    # -- the bus subscriber ------------------------------------------------
    def record_event(self, event: str, fields: dict) -> None:
        """Turn one bus event into counters (subscribed by the CLI)."""
        name = _EVENT_COUNTERS.get(event)
        if name is not None:
            self.inc(name)
            return
        if event == "retry.backoff":
            self.inc("backoff_waits")
            self.observe("backoff_delay_s", float(fields["delay"]))
        elif event == "watchdog.guard":
            self.inc(
                "guard_arms"
                if fields.get("state") == "armed"
                else "guard_disarms"
            )
        elif event == "rescue.beacon_miss":
            self.inc("beacon_misses")
        elif event == "rescue.orphans":
            self.inc("rescued_sequences", int(fields.get("count", 0)))
        elif event == "serve.request.admitted":
            self.inc("serve_requests")
            self.gauge("queue_depth", int(fields.get("depth", 0)))
        elif event == "serve.request.rejected":
            self.inc("serve_rejections")
        elif event == "serve.request.done":
            self.inc("serve_completed")
            self.observe(
                "request_latency_s", float(fields.get("latency_s", 0.0))
            )
        elif event == "serve.batch.dispatch":
            self.inc("serve_batches")
            self.gauge("batch_fill_ratio", float(fields.get("fill", 0.0)))
            self.gauge("queue_depth", int(fields.get("depth", 0)))
        elif event == "serve.request.failed":
            # Deadline misses get their own SLO counter; every other
            # typed failure (poison isolation, ...) shares one.
            if fields.get("error") == "deadline":
                self.inc("serve_deadline_rejections")
            else:
                self.inc("serve_failures")
        elif event == "serve.request.shed":
            self.inc("serve_shed")
        elif event == "serve.shed.state":
            self.inc("serve_shed_transitions")
            self.gauge("shed_state", str(fields.get("state", "")))
        elif event == "serve.queue.wait":
            self.observe("queue_wait_s", float(fields.get("wait_s", 0.0)))
        elif event == "serve.request.abandoned":
            self.inc("serve_abandoned")
        elif event == "serve.request.poisoned":
            self.inc("serve_poisoned")
        elif event == "serve.block.failed":
            self.inc("serve_block_failures")
        elif event == "serve.client.lost":
            self.inc("serve_clients_lost")
        elif event == "worker.join":
            self.inc("fleet_joins")
            self.gauge("fleet_workers", int(fields.get("workers", 0)))
        elif event == "worker.dead":
            self.inc("fleet_deaths")
            self.gauge("fleet_workers", int(fields.get("workers", 0)))
        elif event == "lease.expired":
            self.inc("fleet_lease_expiries")
        elif event == "lease.fenced":
            self.inc("fleet_fenced_posts")
        elif event == "fleet.redispatch":
            self.inc("fleet_redispatches")
        elif event == "fleet.deadletter":
            self.inc("fleet_deadletter")
        elif event == "leader.elected":
            self.inc("fleet_elections")
            self.gauge("fleet_leader_epoch", int(fields.get("gen", 0)))
        elif event == "leader.takeover":
            self.inc("fleet_takeovers")
            self.gauge("fleet_leader_epoch", int(fields.get("gen", 0)))
        elif event == "leader.fenced":
            self.inc("fleet_leader_fenced")
        elif event == "leader.deposed":
            self.inc("fleet_depositions")
        elif event == "board.gc":
            self.inc("fleet_gc_swept", int(fields.get("count", 0)))
        elif event == "fleet.score.start":
            self.inc("fleet_scores_started")
        elif event == "fleet.tape.collected":
            self.inc("fleet_tapes_collected")
        elif event == "serve.request.duplicate":
            self.inc("serve_duplicates")
        elif event.startswith("breaker."):
            # breaker.open / breaker.half_open / breaker.close -> one
            # counter each, plus the current-state gauge the chaos tier
            # reads back out of the run report.
            what = event.partition(".")[2]
            self.inc(f"breaker_{what}s")
            self.gauge(
                "breaker_state", "closed" if what == "close" else what
            )
        else:
            # Forward-compatible: an unmapped event still leaves a trace.
            self.inc(f"events.{event}")

    # -- snapshots ---------------------------------------------------------
    def record_fleet(self, host, snapshot: dict) -> None:
        self.fleet[str(host)] = snapshot

    def snapshot(self) -> dict:
        """A JSON-ready copy of the registry (no fleet: snapshots are
        what the fleet section is MADE of)."""
        return {
            "uptime_s": round(self.uptime_s(), 6),
            "counters": dict(self.counters),
            "gauges": dict(self.gauges),
            "histograms": {
                k: v.snapshot() if isinstance(v, Histogram) else dict(v)
                for k, v in self.histograms.items()
            },
        }


# The armed registry (same lifecycle as the fault registry).
_active: MetricsRegistry | None = None


def activate_metrics(clock=None) -> MetricsRegistry:
    """Arm a fresh registry for one run; returns it for inspection."""
    global _active
    _active = MetricsRegistry(clock if clock is not None else time.monotonic)
    return _active


def deactivate_metrics() -> None:
    global _active
    _active = None


def active_metrics() -> MetricsRegistry | None:
    return _active


def inc(name: str, n: int | float = 1) -> None:
    """Instrumentation hook: count on the armed registry, else no-op."""
    if _active is not None:
        _active.inc(name, n)


def gauge(name: str, value) -> None:
    if _active is not None:
        _active.gauge(name, value)


def observe(name: str, value: float) -> None:
    if _active is not None:
        _active.observe(name, value)


def drain_snapshot() -> dict | None:
    """The extra payload the journal's ``{"event": "drain"}`` record
    carries when metrics are armed (None otherwise) — the journal itself
    never reads a clock (SEQ005); the uptime inside comes from here."""
    if _active is None:
        return None
    return {"metrics": _active.snapshot()}


# -- the shared report serializer ------------------------------------------


def wrap_report(kind: str, body: dict, *, meta: dict | None = None) -> dict:
    """The one report envelope: ``bench.py`` wraps its blob with
    ``kind="bench"``, the CLI's run report uses ``kind="run"``, and the
    static schedule auditor emits ``kind="schedule-audit"`` — all
    validate against :func:`validate_report`."""
    rec: dict = {
        "schema": RUN_REPORT_SCHEMA,
        "schema_version": RUN_REPORT_VERSION,
        "kind": kind,
    }
    if meta:
        rec["meta"] = dict(meta)
    rec.update(body)
    return rec


def run_report(
    registry: MetricsRegistry,
    *,
    spans=None,
    exit_code: int | None = None,
    meta: dict | None = None,
    extra: dict | None = None,
) -> dict:
    """The ``--metrics-out`` JSON document for one finished run.
    ``extra`` merges additional top-level sections (the trace plane's
    ``gap_attribution``) into the body."""
    body = registry.snapshot()
    if extra:
        body.update(extra)
    if spans is not None:
        body["spans"] = {
            "phases": [[name, round(dur, 6)] for name, dur in spans.phases()],
            "totals": {
                path: round(total, 6)
                for path, total in sorted(spans.totals().items())
            },
        }
    if exit_code is not None:
        body["exit_code"] = int(exit_code)
    if registry.fleet:
        body["hosts"] = dict(registry.fleet)
    return wrap_report("run", body, meta=meta)


_HISTOGRAM_REQUIRED = ("count", "sum", "min", "max")
_HISTOGRAM_OPTIONAL = ("buckets", "p50", "p90", "p99")


def _is_num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _is_finite_num(v) -> bool:
    return _is_num(v) and math.isfinite(v)


def _histogram_problems(name: str, h) -> list[str]:
    if (
        not isinstance(h, dict)
        or not set(_HISTOGRAM_REQUIRED) <= set(h)
        or not set(h) <= set(_HISTOGRAM_REQUIRED + _HISTOGRAM_OPTIONAL)
    ):
        return [
            f"histograms[{name!r}]: want count/sum/min/max "
            f"(+ optional buckets/p50/p90/p99), got {h!r}"
        ]
    out = []
    for k in ("count", "sum", "min", "max", "p50", "p90", "p99"):
        if k in h and not _is_num(h[k]):
            out.append(
                f"histograms[{name!r}].{k}: want a number, got {h[k]!r}"
            )
    buckets = h.get("buckets")
    if buckets is not None and (
        not isinstance(buckets, dict)
        or "+Inf" not in buckets
        or not all(isinstance(n, int) for n in buckets.values())
    ):
        out.append(
            f"histograms[{name!r}].buckets: want cumulative int counts "
            f"ending in +Inf, got {buckets!r}"
        )
    return out


def validate_report(rec) -> None:
    """Schema gate for any wrapped report (run or bench); raises one
    ValueError naming every problem (``make metrics-smoke`` and the
    chaos tests call this)."""
    problems: list[str] = []
    if not isinstance(rec, dict):
        raise ValueError(f"report must be a JSON object, got {type(rec).__name__}")
    if rec.get("schema") != RUN_REPORT_SCHEMA:
        problems.append(f"schema: want {RUN_REPORT_SCHEMA!r}, got {rec.get('schema')!r}")
    ver = rec.get("schema_version")
    if not isinstance(ver, int) or ver < 1:
        problems.append(f"schema_version: want int >= 1, got {ver!r}")
    kind = rec.get("kind")
    if not isinstance(kind, str) or not kind:
        problems.append(f"kind: want a nonempty string, got {kind!r}")
    if kind == "run":
        for section in ("counters", "gauges", "histograms"):
            if not isinstance(rec.get(section), dict):
                problems.append(f"{section}: want an object, got {rec.get(section)!r}")
        for name, v in (rec.get("counters") or {}).items():
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                problems.append(f"counters[{name!r}]: want a number, got {v!r}")
        for name, h in (rec.get("histograms") or {}).items():
            problems.extend(_histogram_problems(name, h))
        if not isinstance(rec.get("uptime_s"), (int, float)):
            problems.append(f"uptime_s: want a number, got {rec.get('uptime_s')!r}")
        if "exit_code" in rec and not isinstance(rec["exit_code"], int):
            problems.append(f"exit_code: want an int, got {rec['exit_code']!r}")
        spans = rec.get("spans")
        if spans is not None:
            if not isinstance(spans, dict) or not isinstance(
                spans.get("phases"), list
            ) or not isinstance(spans.get("totals"), dict):
                problems.append(f"spans: want {{phases: [], totals: {{}}}}, got {spans!r}")
    elif kind == "bench":
        if "metric" not in rec or "value" not in rec:
            problems.append("bench report: want metric and value fields")
        if rec.get("formulation") == "serve-load":
            # The load harness's official record (load/report.py):
            # goodput + the SLO surface are schema, not convention.
            for field in ("goodput_rps", "offered_rps", "duration_s"):
                if not _is_finite_num(rec.get(field)):
                    problems.append(
                        f"serve-load report: {field}: want a finite "
                        f"number, got {rec.get(field)!r}"
                    )
            reqs = rec.get("requests")
            req_fields = (
                "offered", "done", "rejected", "failed", "missing",
                "reset",
            )
            if not isinstance(reqs, dict) or not all(
                isinstance(reqs.get(k), int) for k in req_fields
            ):
                problems.append(
                    f"serve-load report: requests: want int "
                    f"{'/'.join(req_fields)}, got {reqs!r}"
                )
            for section in ("latency_s", "queue_wait_s"):
                pct = rec.get(section)
                if not isinstance(pct, dict) or not all(
                    _is_finite_num(pct.get(k))
                    for k in ("p50", "p90", "p99")
                ):
                    problems.append(
                        f"serve-load report: {section}: want p50/p90/"
                        f"p99 numbers, got {pct!r}"
                    )
            for field in ("shed_rate", "deadline_miss_rate"):
                v = rec.get(field)
                if not _is_finite_num(v) or not 0.0 <= float(v) <= 1.0:
                    problems.append(
                        f"serve-load report: {field}: want a rate in "
                        f"[0, 1], got {v!r}"
                    )
            arr = rec.get("arrival")
            if not isinstance(arr, dict) or not isinstance(
                arr.get("process"), str
            ) or not _is_finite_num(arr.get("rate_rps")):
                problems.append(
                    f"serve-load report: arrival: want an object with "
                    f"process + rate_rps, got {arr!r}"
                )
    elif kind == "schedule-audit":
        # scripts/schedule_audit.py's cost-sheet + trace-audit report.
        sheet = rec.get("cost_sheet")
        if not isinstance(sheet, dict):
            problems.append(
                f"cost_sheet: want an object, got {sheet!r}"
            )
        else:
            if not isinstance(sheet.get("buckets"), list):
                problems.append("cost_sheet.buckets: want a list")
            totals = sheet.get("totals")
            if totals is not None and (
                not isinstance(totals, dict)
                or not isinstance(totals.get("launches"), int)
                or not isinstance(totals.get("executables"), int)
            ):
                problems.append(
                    "cost_sheet.totals: want launches/executables ints, "
                    f"got {totals!r}"
                )
            pred = sheet.get("predicted_mfu_vs_feed_roofline")
            if pred is not None and not isinstance(pred, (int, float)):
                problems.append(
                    "cost_sheet.predicted_mfu_vs_feed_roofline: want a "
                    f"number or null, got {pred!r}"
                )
        audit = rec.get("trace_audit")
        if not isinstance(audit, dict):
            problems.append(f"trace_audit: want an object, got {audit!r}")
        else:
            if not isinstance(audit.get("buckets"), list):
                problems.append("trace_audit.buckets: want a list")
            don = audit.get("donation")
            if (
                not isinstance(don, dict)
                or "undonated_large_buffers" not in don
                or not isinstance(don.get("pinned_live"), list)
            ):
                problems.append(
                    "trace_audit.donation: want an object with "
                    "undonated_large_buffers and a pinned_live list, "
                    f"got {don!r}"
                )
        if not isinstance(rec.get("entry_points"), list):
            problems.append(
                f"entry_points: want a list, got {rec.get('entry_points')!r}"
            )
    elif kind == "trace":
        # obs/trace.py's Chrome-trace/Perfetto export + gap attribution.
        tev = rec.get("traceEvents")
        if not isinstance(tev, list):
            problems.append(f"traceEvents: want a list, got {tev!r}")
        else:
            for i, ev in enumerate(tev):
                if not isinstance(ev, dict) or "ph" not in ev or "name" not in ev:
                    problems.append(
                        f"traceEvents[{i}]: want objects with ph/name, got {ev!r}"
                    )
                    break
        gap = rec.get("gap_attribution")
        if not isinstance(gap, dict) or not isinstance(
            gap.get("launches"), list
        ):
            problems.append(
                f"gap_attribution: want an object with a launches list, got {gap!r}"
            )
        else:
            for i, row in enumerate(gap["launches"]):
                if (
                    not isinstance(row, dict)
                    or not isinstance(row.get("request_ids"), list)
                    or not _is_finite_num(row.get("measured_s"))
                    or not _is_finite_num(row.get("modelled_s"))
                    or not _is_finite_num(row.get("gap_s"))
                ):
                    problems.append(
                        f"gap_attribution.launches[{i}]: want request_ids "
                        f"plus finite measured_s/modelled_s/gap_s, got {row!r}"
                    )
            for k in ("total_measured_s", "total_modelled_s", "total_gap_s"):
                if not _is_finite_num(gap.get(k)):
                    problems.append(
                        f"gap_attribution.{k}: want a finite number, "
                        f"got {gap.get(k)!r}"
                    )
    elif kind == "flightrec":
        # obs/flightrec.py's incident dump.
        if not isinstance(rec.get("reason"), str) or not rec.get("reason"):
            problems.append(
                f"reason: want a nonempty string, got {rec.get('reason')!r}"
            )
        if not isinstance(rec.get("depth"), int):
            problems.append(f"depth: want an int, got {rec.get('depth')!r}")
        evs = rec.get("events")
        if not isinstance(evs, list):
            problems.append(f"events: want a list, got {evs!r}")
        else:
            for i, e in enumerate(evs):
                if (
                    not isinstance(e, dict)
                    or e.get("kind") not in ("event", "span")
                    or "name" not in e
                ):
                    problems.append(
                        f"events[{i}]: want event/span entries with a name, "
                        f"got {e!r}"
                    )
                    break
    elif kind == "concurrency-audit":
        # scripts/concurrency_audit.py's lock-graph + interleave report.
        lg = rec.get("lockgraph")
        if not isinstance(lg, dict):
            problems.append(f"lockgraph: want an object, got {lg!r}")
        else:
            if not isinstance(lg.get("locks"), list):
                problems.append("lockgraph.locks: want a list of lock ids")
            if not isinstance(lg.get("edges"), list):
                problems.append("lockgraph.edges: want a list")
            if not isinstance(lg.get("findings"), list):
                problems.append("lockgraph.findings: want a list")
            counts = lg.get("counts")
            if not isinstance(counts, dict) or not all(
                isinstance(counts.get(k), int)
                for k in ("locks", "edges", "findings")
            ):
                problems.append(
                    "lockgraph.counts: want locks/edges/findings ints, "
                    f"got {counts!r}"
                )
        il = rec.get("interleave")
        if not isinstance(il, dict):
            problems.append(f"interleave: want an object, got {il!r}")
        else:
            rows = il.get("scenarios")
            if not isinstance(rows, list):
                problems.append(f"interleave.scenarios: want a list, got {rows!r}")
            else:
                for i, row in enumerate(rows):
                    if (
                        not isinstance(row, dict)
                        or not isinstance(row.get("name"), str)
                        or not isinstance(row.get("schedules"), int)
                        or not isinstance(row.get("violations"), list)
                    ):
                        problems.append(
                            f"interleave.scenarios[{i}]: want name plus "
                            f"schedules int plus violations list, got {row!r}"
                        )
            if not isinstance(il.get("total_schedules"), int):
                problems.append(
                    "interleave.total_schedules: want an int, got "
                    f"{il.get('total_schedules')!r}"
                )
    elif kind == "donation-audit":
        # scripts/donation_audit.py's donation-safety dataflow report.
        plan = rec.get("plan")
        if not isinstance(plan, dict) or not isinstance(
            plan.get("entries"), list
        ):
            problems.append(
                f"plan: want an object with an entries list, got {plan!r}"
            )
        else:
            for i, e in enumerate(plan["entries"]):
                if (
                    not isinstance(e, dict)
                    or not isinstance(e.get("wrapper"), str)
                    or not isinstance(e.get("donate"), list)
                    or not isinstance(e.get("pinned"), list)
                ):
                    problems.append(
                        f"plan.entries[{i}]: want wrapper str plus "
                        f"donate/pinned lists, got {e!r}"
                    )
        if not isinstance(rec.get("findings"), list):
            problems.append(
                f"findings: want a list, got {rec.get('findings')!r}"
            )
        if not isinstance(rec.get("restage_paths"), list):
            problems.append(
                "restage_paths: want a list, got "
                f"{rec.get('restage_paths')!r}"
            )
        audit = rec.get("trace_audit")
        if not isinstance(audit, dict):
            problems.append(f"trace_audit: want an object, got {audit!r}")
        else:
            don = audit.get("donation")
            if (
                not isinstance(don, dict)
                or "undonated_large_buffers" not in don
                or not isinstance(don.get("pinned_live"), list)
            ):
                problems.append(
                    "trace_audit.donation: want an object with "
                    "undonated_large_buffers and a pinned_live list, "
                    f"got {don!r}"
                )
    elif kind == "ranges-audit":
        # scripts/ranges_audit.py's value-range certification report.
        consts = rec.get("derived_constants")
        if not isinstance(consts, list) or not consts:
            problems.append(
                f"derived_constants: want a non-empty list, got {consts!r}"
            )
        else:
            for i, c in enumerate(consts):
                if (
                    not isinstance(c, dict)
                    or not isinstance(c.get("name"), str)
                    or not isinstance(c.get("relation"), str)
                    or not isinstance(c.get("ok"), bool)
                ):
                    problems.append(
                        f"derived_constants[{i}]: want name/relation strs "
                        f"plus an ok bool, got {c!r}"
                    )
        entries = rec.get("entries")
        if not isinstance(entries, list) or not entries:
            problems.append(
                f"entries: want a non-empty list, got {entries!r}"
            )
        else:
            for i, e in enumerate(entries):
                if (
                    not isinstance(e, dict)
                    or not isinstance(e.get("entry"), str)
                    or e.get("verdict")
                    not in ("exact", "representable", "unproven")
                    or not isinstance(e.get("findings"), list)
                ):
                    problems.append(
                        f"entries[{i}]: want entry str, verdict in "
                        "exact/representable/unproven, a findings list, "
                        f"got {e!r}"
                    )
        if not isinstance(rec.get("production"), list):
            problems.append(
                f"production: want a list, got {rec.get('production')!r}"
            )
        signed = rec.get("signed_weights")
        if (
            not isinstance(signed, dict)
            or not isinstance(signed.get("entries"), list)
            or not isinstance(signed.get("paths"), list)
        ):
            problems.append(
                "signed_weights: want an object with entries/paths "
                f"lists, got {signed!r}"
            )
        if not isinstance(rec.get("findings"), list):
            problems.append(
                f"findings: want a list, got {rec.get('findings')!r}"
            )
        counts = rec.get("counts")
        if not isinstance(counts, dict) or not all(
            isinstance(counts.get(k), int)
            for k in (
                "constants",
                "constants_ok",
                "entries",
                "entries_exact",
                "production_buckets",
                "signed_survivors",
                "findings",
            )
        ):
            problems.append(
                "counts: want constants/constants_ok/entries/"
                "entries_exact/production_buckets/signed_survivors/"
                f"findings ints, got {counts!r}"
            )
    elif kind == "exitpath-audit":
        # scripts/exitpath_audit.py's exception-flow certification
        # report (analysis/exitflow.py).
        sinks = rec.get("sinks")
        if not isinstance(sinks, dict) or not all(
            isinstance(k, str) and isinstance(v, int)
            for k, v in (sinks or {}).items()
        ):
            problems.append(
                f"sinks: want a str->int sink inventory, got {sinks!r}"
            )
        modules = rec.get("raise_modules")
        if not isinstance(modules, dict) or not all(
            isinstance(k, str) and isinstance(v, int)
            for k, v in (modules or {}).items()
        ):
            problems.append(
                "raise_modules: want a str->int per-module raise map, "
                f"got {modules!r}"
            )
        advisory = rec.get("advisory")
        if not isinstance(advisory, list) or not all(
            isinstance(a, str) for a in advisory or []
        ):
            problems.append(
                f"advisory: want a list of marker strs, got {advisory!r}"
            )
        flush = rec.get("flush")
        if not isinstance(flush, dict):
            problems.append(f"flush: want an object, got {flush!r}")
        else:
            for mod, f in flush.items():
                if (
                    not isinstance(f, dict)
                    or not isinstance(f.get("function"), str)
                    or not isinstance(f.get("flush_try"), list)
                    or not isinstance(f.get("flush_calls"), list)
                    or not isinstance(f.get("protected_returns"), int)
                ):
                    problems.append(
                        f"flush[{mod}]: want function str, flush_try/"
                        "flush_calls lists, protected_returns int, "
                        f"got {f!r}"
                    )
        faults = rec.get("fault_sites")
        if not isinstance(faults, dict) or not all(
            isinstance(faults.get(k), int)
            for k in faults or {}
        ):
            problems.append(
                f"fault_sites: want a str->int summary, got {faults!r}"
            )
        if not isinstance(rec.get("findings"), list):
            problems.append(
                f"findings: want a list, got {rec.get('findings')!r}"
            )
        counts = rec.get("counts")
        if not isinstance(counts, dict) or not all(
            isinstance(counts.get(k), int)
            for k in (
                "raise_sites",
                "production_raises",
                "production_functions",
                "broad_handlers",
                "wire_reply_handlers",
                "advisory_markers",
                "findings",
            )
        ):
            problems.append(
                "counts: want raise_sites/production_raises/"
                "production_functions/broad_handlers/wire_reply_handlers/"
                f"advisory_markers/findings ints, got {counts!r}"
            )
    elif kind == "comms-audit":
        # scripts/comms_audit.py's collective-safety & comms-cost report.
        entries = rec.get("entries")
        if not isinstance(entries, list) or not entries:
            problems.append(
                f"entries: want a non-empty list, got {entries!r}"
            )
        else:
            for i, e in enumerate(entries):
                if (
                    not isinstance(e, dict)
                    or not isinstance(e.get("spec"), str)
                    or not isinstance(e.get("collectives"), list)
                    or not isinstance(e.get("signature"), str)
                    or not isinstance(e.get("consistent"), bool)
                    or not isinstance(e.get("positions"), int)
                ):
                    problems.append(
                        f"entries[{i}]: want spec/signature strs, a "
                        "collectives list, consistent bool, positions "
                        f"int, got {e!r}"
                    )
        if not isinstance(rec.get("findings"), list):
            problems.append(
                f"findings: want a list, got {rec.get('findings')!r}"
            )
        counts = rec.get("counts")
        if not isinstance(counts, dict) or not all(
            isinstance(counts.get(k), int)
            for k in ("entries", "collectives", "payload_bytes", "findings")
        ):
            problems.append(
                "counts: want entries/collectives/payload_bytes/findings "
                f"ints, got {counts!r}"
            )
        comms = rec.get("comms")
        if not isinstance(comms, dict) or not isinstance(
            comms.get("scaling"), list
        ):
            problems.append(
                f"comms: want an object with a scaling list, got {comms!r}"
            )
        else:
            for i, row in enumerate(comms["scaling"]):
                if (
                    not isinstance(row, dict)
                    or not isinstance(row.get("mesh"), int)
                    or not isinstance(row.get("axis"), str)
                    or not _is_finite_num(row.get("comms_wall_us"))
                    or not _is_finite_num(row.get("predicted_wall_us"))
                    or not _is_finite_num(
                        row.get("predicted_scaling_efficiency")
                    )
                ):
                    problems.append(
                        f"comms.scaling[{i}]: want mesh int, axis str, "
                        "finite comms_wall_us/predicted_wall_us/"
                        f"predicted_scaling_efficiency, got {row!r}"
                    )
    elif kind == "aot-manifest":
        # aot/manifest.py's warm-set manifest.
        fp = rec.get("fingerprint")
        if not isinstance(fp, dict) or not isinstance(fp.get("digest"), str):
            problems.append(
                f"fingerprint: want an object with a digest string, got {fp!r}"
            )
        entries = rec.get("entries")
        if not isinstance(entries, list):
            problems.append(f"entries: want a list, got {entries!r}")
        else:
            for i, e in enumerate(entries):
                if not isinstance(e, dict):
                    problems.append(f"entries[{i}]: want an object, got {e!r}")
                    continue
                if not isinstance(e.get("cache_key"), list):
                    problems.append(f"entries[{i}].cache_key: want a list")
                if not isinstance(e.get("fingerprint"), str):
                    problems.append(f"entries[{i}].fingerprint: want a string")
                if not isinstance(e.get("compile_wall_s"), (int, float)):
                    problems.append(
                        f"entries[{i}].compile_wall_s: want a number"
                    )
        if not isinstance(rec.get("stale"), list):
            problems.append(f"stale: want a list, got {rec.get('stale')!r}")
        totals = rec.get("totals")
        if not isinstance(totals, dict) or not isinstance(
            totals.get("entries"), int
        ):
            problems.append(
                f"totals: want an object with an int entry count, got {totals!r}"
            )
    if problems:
        raise ValueError(
            "invalid run report: " + "; ".join(problems)
        )


def _fmt_num(v) -> str:
    return repr(v) if isinstance(v, float) else str(v)


#: HELP text for the metrics worth explaining; everything else gets a
#: mechanical fallback so every family still carries a HELP line.
_METRIC_HELP = {
    "queue_wait_s": "Seconds a request waited in the admission queue",
    "request_latency_s": "Admission-to-done latency of one served request",
    "backoff_delay_s": "Scheduled retry backoff delay",
    "queue_depth": "Requests currently queued for batching",
    "shed_state": "Admission shed state (accept/shed-new/drain-only)",
    "breaker_state": "Circuit breaker state (closed/open/half_open)",
    "batch_fill_ratio": "Real-row fraction of the last dispatched superblock",
    "uptime_seconds": "Seconds since the metrics registry was armed",
}


def _help_line(m: str, name: str, fallback: str) -> str:
    return f"# HELP {m} {_METRIC_HELP.get(name, fallback)}"


def to_prometheus(snapshot: dict, *, prefix: str = "seqalign") -> str:
    """Prometheus text exposition of one registry snapshot: counters as
    ``_total``, numeric gauges verbatim, string gauges as ``_info``
    labels, bucketed histograms as native ``histogram`` families
    (cumulative ``le`` buckets), summary-only histograms as summaries;
    min/max/percentile fields ride as gauges.  Every family carries
    HELP and TYPE lines."""
    lines: list[str] = []
    for name in sorted(snapshot.get("counters", ())):
        m = f"{prefix}_{name.replace('.', '_')}_total"
        lines.append(_help_line(m, name, f"Total {name.replace('_', ' ')}"))
        lines.append(f"# TYPE {m} counter")
        lines.append(f"{m} {_fmt_num(snapshot['counters'][name])}")
    for name in sorted(snapshot.get("gauges", ())):
        v = snapshot["gauges"][name]
        m = f"{prefix}_{name.replace('.', '_')}"
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            lines.append(
                _help_line(m, name, f"Current {name.replace('_', ' ')}")
            )
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {_fmt_num(v)}")
        else:
            lines.append(
                _help_line(
                    f"{m}_info", name, f"Current {name.replace('_', ' ')}"
                )
            )
            lines.append(f"# TYPE {m}_info gauge")
            lines.append(f'{m}_info{{value="{v}"}} 1')
    for name in sorted(snapshot.get("histograms", ())):
        h = snapshot["histograms"][name]
        m = f"{prefix}_{name.replace('.', '_')}"
        buckets = h.get("buckets")
        lines.append(
            _help_line(m, name, f"Distribution of {name.replace('_', ' ')}")
        )
        if buckets:
            lines.append(f"# TYPE {m} histogram")
            for label, n in buckets.items():
                lines.append(f'{m}_bucket{{le="{label}"}} {_fmt_num(n)}')
        else:
            lines.append(f"# TYPE {m} summary")
        lines.append(f"{m}_count {_fmt_num(h['count'])}")
        lines.append(f"{m}_sum {_fmt_num(h['sum'])}")
        for field in ("min", "max", "p50", "p90", "p99"):
            if field in h:
                lines.append(f"# TYPE {m}_{field} gauge")
                lines.append(f"{m}_{field} {_fmt_num(h[field])}")
    up = snapshot.get("uptime_s")
    if up is not None:
        m = f"{prefix}_uptime_seconds"
        lines.append(_help_line(m, "uptime_seconds", "Uptime in seconds"))
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m} {_fmt_num(up)}")
    return "\n".join(lines) + "\n"


def fleet_to_prometheus(
    fleet: dict, *, prefix: str = "seqalign", skip_heads=()
) -> str:
    """Federated exposition of gathered per-worker registry snapshots
    (``registry.fleet``): the same families :func:`to_prometheus`
    renders for the local process, each sample labelled with its
    ``worker="wid"`` origin so one coordinator scrape covers the whole
    fleet.  HELP/TYPE lines are emitted once per family (Prometheus
    rejects duplicates) and suppressed for families in ``skip_heads``
    (the ones the local exposition already declared), samples once per
    worker.  Histograms federate as their count/sum plus
    min/max/percentile gauges — per-worker cumulative buckets would
    multiply the payload for little signal."""
    lines: list[str] = []
    seen: set[str] = set(skip_heads)

    def _head(m: str, name: str, mtype: str, fallback: str) -> None:
        if m not in seen:
            seen.add(m)
            lines.append(_help_line(m, name, fallback))
            lines.append(f"# TYPE {m} {mtype}")

    for wid in sorted(fleet):
        snap = fleet[wid]
        if not isinstance(snap, dict):
            continue
        lab = f'worker="{wid}"'
        counters = snap.get("counters") or {}
        for name in sorted(counters):
            m = f"{prefix}_{name.replace('.', '_')}_total"
            _head(m, name, "counter", f"Total {name.replace('_', ' ')}")
            lines.append(f"{m}{{{lab}}} {_fmt_num(counters[name])}")
        gauges = snap.get("gauges") or {}
        for name in sorted(gauges):
            v = gauges[name]
            m = f"{prefix}_{name.replace('.', '_')}"
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                _head(m, name, "gauge", f"Current {name.replace('_', ' ')}")
                lines.append(f"{m}{{{lab}}} {_fmt_num(v)}")
            else:
                _head(
                    f"{m}_info", name, "gauge",
                    f"Current {name.replace('_', ' ')}",
                )
                lines.append(f'{m}_info{{{lab},value="{v}"}} 1')
        hists = snap.get("histograms") or {}
        for name in sorted(hists):
            h = hists[name]
            if not isinstance(h, dict) or "count" not in h:
                continue
            m = f"{prefix}_{name.replace('.', '_')}"
            _head(
                m, name, "summary",
                f"Distribution of {name.replace('_', ' ')}",
            )
            lines.append(f"{m}_count{{{lab}}} {_fmt_num(h['count'])}")
            lines.append(f"{m}_sum{{{lab}}} {_fmt_num(h.get('sum', 0))}")
            for field in ("min", "max", "p50", "p90", "p99"):
                if field in h:
                    mf = f"{m}_{field}"
                    _head(mf, name, "gauge", f"{field} of {name}")
                    lines.append(f"{mf}{{{lab}}} {_fmt_num(h[field])}")
        up = snap.get("uptime_s")
        if up is not None:
            m = f"{prefix}_uptime_seconds"
            _head(m, "uptime_seconds", "gauge", "Uptime in seconds")
            lines.append(f"{m}{{{lab}}} {_fmt_num(up)}")
    if not lines:
        return ""
    return "\n".join(lines) + "\n"
