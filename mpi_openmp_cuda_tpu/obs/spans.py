"""Nested wall-clock spans: the phase timer generalised.

:class:`SpanRecorder` subsumes the old ``utils/profiling.PhaseTimer``
(now a thin shim over this class): top-level spans ARE the profile
phases (parse / setup / score / print, byte-compatible ``[profile]``
report), and spans opened while another is live record under a dotted
path (``score.chunk_gather``) — the per-chunk dispatch/gather spans
``ops/dispatch.py`` emits nest under whatever phase the CLI has open.

Honest device time: JAX dispatch is asynchronous, so a span around a
dispatch call measures enqueue, not compute.  :func:`fence` calls
``jax.block_until_ready`` on a value *when a recorder is armed* (no-op
otherwise — the hot path must not lose pipelining to an observability
default), so a gather span brackets the actual device wait.

The clock is injectable (``time.perf_counter`` by default) and every
read lives in this file — the deterministic ``resilience/`` and
``utils/journal.py`` paths stay clock-free (seqlint SEQ005).

Module hooks follow the fault-registry pattern: :func:`span` returns a
shared ``nullcontext`` when no recorder is armed (zero allocation on
the per-chunk path), and the CLI arms/disarms per run.
"""

from __future__ import annotations

import contextlib
import sys
import time


class SpanRecorder:
    """Records ``(dotted.path, seconds)`` spans in completion order.

    Single-threaded by construction (the driver thread owns dispatch,
    gather, and all CLI phases — the same argument as the fault
    registry), so one stack suffices.
    """

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self.spans: list[tuple[str, float]] = []
        self._stack: list[str] = []
        # Close listeners: ``fn(path, start, dur)`` per finished span,
        # in the recorder's own clock domain.  The trace and flight-
        # recorder tiers subscribe here; ``spans`` keeps its shape, so
        # phases()/totals()/report() are untouched.
        self.listeners: list = []

    @contextlib.contextmanager
    def span(self, name: str):
        self._stack.append(name)
        path = ".".join(self._stack)
        start = self._clock()
        try:
            yield
        finally:
            dur = self._clock() - start
            self._stack.pop()
            self.spans.append((path, dur))
            for fn in self.listeners:
                try:
                    fn(path, start, dur)
                except Exception:
                    # advisory: a broken observer must never fail the
                    # timed work.
                    pass

    def phases(self) -> list[tuple[str, float]]:
        """Top-level spans in completion order — exactly the old
        ``PhaseTimer.phases`` contract."""
        return [(p, d) for p, d in self.spans if "." not in p]

    def totals(self) -> dict[str, float]:
        """Total seconds per dotted path (repeated spans accumulate —
        per-chunk gather spans sum into one ``score.chunk_gather``)."""
        out: dict[str, float] = {}
        for p, d in self.spans:
            out[p] = out.get(p, 0.0) + d
        return out

    def report(self, out=None) -> None:
        """The byte-compatible ``--profile`` report (top-level phases +
        total), same format the old PhaseTimer printed."""
        out = out or sys.stderr
        phases = self.phases()
        total = sum(d for _, d in phases)
        for name, dur in phases:
            print(f"[profile] {name:>16}: {dur * 1e3:10.2f} ms", file=out)
        print(f"[profile] {'total':>16}: {total * 1e3:10.2f} ms", file=out)


# The armed recorder; one shared nullcontext so a disarmed span() costs
# no allocation (nullcontext enter/exit is stateless and reentrant).
_active: SpanRecorder | None = None
_NULL = contextlib.nullcontext()


def activate_spans(clock=None) -> SpanRecorder:
    """Arm a fresh recorder for one run; returns it (the CLI hands the
    same recorder to the PhaseTimer shim so phases and spans agree)."""
    global _active
    _active = SpanRecorder(clock if clock is not None else time.perf_counter)
    return _active


def deactivate_spans() -> None:
    global _active
    _active = None


def active_spans() -> SpanRecorder | None:
    return _active


def span(name: str):
    """Instrumentation hook: a span on the armed recorder, else the
    shared no-op context."""
    rec = _active
    if rec is None:
        return _NULL
    return rec.span(name)


def fence(tree) -> None:
    """``jax.block_until_ready(tree)`` when a recorder is armed, so the
    enclosing span sees the device wait; no-op (one attribute check)
    otherwise — and a no-op on jax-less installs, where values are
    already host-side."""
    if _active is None:
        return
    try:
        import jax
    except ImportError:
        return
    jax.block_until_ready(tree)
