"""Flight recorder: a bounded ring of the last N bus events + span
closures, dumped atomically on the ways a serve process dies.

Post-mortem story today: a wedged or breaker-tripped server leaves a
heartbeat trail on stderr and (maybe) an exit-time run report — the
*sequence of events* that led to the incident is gone.  The flight
recorder keeps exactly that sequence, cheaply (a deque append per bus
event), and writes it out only when something goes wrong:

* watchdog expiry (``watchdog.expiry`` — published from the monitor
  thread, so recording and dumping are lock-guarded);
* circuit-breaker open (``breaker.open``);
* fatal exit (the CLI dumps on rc 65 in its teardown);
* operator request (SIGUSR2, wired in ``io/cli.py``).

Dumps are ``kind="flightrec"`` envelopes written atomically to
``<cache_home>/flightrec/`` — schema-validated like every other report
artifact, never raising (a failing dump must not turn an incident into
a crash).

Thread contract (seqlint SEQ008 — the module is classified serve-plane
for exactly this rule): ``record_event`` runs on reader threads, the
serve loop, and the watchdog monitor thread; every mutation of the
ring crosses the recorder's own lock, and ``dump`` snapshots under the
lock but writes the file outside it.
"""

from __future__ import annotations

import collections
import json
import os
import tempfile
import threading
import time

from .events import log_line
from .metrics import wrap_report

#: Ring depth when ``SEQALIGN_FLIGHTREC_DEPTH`` is unset (0 disables).
DEFAULT_DEPTH = 256

#: Bus events that trigger an immediate dump, and the dump reason each
#: one stamps into the artifact (and its filename).
DUMP_TRIGGERS = {
    "watchdog.expiry": "watchdog-expiry",
    "breaker.open": "breaker-open",
    "worker.dead": "worker-dead",
    # Failover events: a standby taking over or a deposed leader being
    # fenced is exactly the moment the pre-incident tape matters.
    "leader.takeover": "leader-takeover",
    "leader.fenced": "leader-fenced",
}


class FlightRecorder:
    """Lock-guarded bounded ring of bus events and span closures."""

    def __init__(self, depth: int = DEFAULT_DEPTH, clock=time.monotonic):
        self.depth = int(depth)
        self._clock = clock
        self._lock = threading.Lock()
        self._events: collections.deque = collections.deque(
            maxlen=max(1, self.depth)
        )
        self._seq = 0
        self._dropped = 0
        self._dumps = 0
        self.dump_paths: list[str] = []

    # -- recording ---------------------------------------------------------

    def record_event(self, event: str, fields: dict) -> None:
        """Bus subscriber: append one event; dump when it is a trigger.
        The dump runs OUTSIDE the lock (it re-enters for its snapshot)."""
        t = self._clock()
        with self._lock:
            self._seq += 1
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._events.append({
                "kind": "event",
                "seq": self._seq,
                "t": round(t, 6),
                "name": event,
                "fields": dict(fields),
            })
        reason = DUMP_TRIGGERS.get(event)
        if reason is not None:
            self.dump(reason)

    def span_closed(self, path: str, start: float, dur: float) -> None:
        """Span-recorder listener: append one span closure."""
        t = self._clock()
        with self._lock:
            self._seq += 1
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._events.append({
                "kind": "span",
                "seq": self._seq,
                "t": round(t, 6),
                "name": path,
                "dur_s": round(dur, 9),
            })

    def snapshot_tape(self, limit: int | None = None) -> list[dict]:
        """The newest ``limit`` ring entries, detached — the bounded
        tape a fleet worker posts with its observability snapshot so
        the coordinator can collect it post-mortem."""
        with self._lock:
            tape = list(self._events)
        if limit is not None:
            tape = tape[-int(limit):]
        return [dict(e) for e in tape]

    # -- dumping -----------------------------------------------------------

    def _dump_dir(self) -> str:
        from ..utils.platform import cache_home

        home = cache_home()
        if home is None:
            # Cache plane disabled: a post-mortem is still worth having.
            home = os.path.join(
                tempfile.gettempdir(), "mpi_openmp_cuda_tpu"
            )
        return os.path.join(home, "flightrec")

    def dump(self, reason: str) -> str | None:
        """Write the ring as one ``kind="flightrec"`` envelope.  Returns
        the path, or None on any failure — dumping happens while the
        process is already in trouble and must never add to it."""
        try:
            with self._lock:
                events = list(self._events)
                dropped = self._dropped
                self._dumps += 1
                n = self._dumps
            rec = wrap_report("flightrec", {
                "reason": str(reason),
                "depth": self.depth,
                "dropped": dropped,
                "events": events,
            })
            dump_dir = self._dump_dir()
            os.makedirs(dump_dir, exist_ok=True)
            path = os.path.join(
                dump_dir, f"flightrec-{os.getpid()}-{n}-{reason}.json"
            )
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(json.dumps(rec, indent=2, sort_keys=True) + "\n")
            os.replace(tmp, path)
            with self._lock:
                self.dump_paths.append(path)
            log_line(
                f"mpi_openmp_cuda_tpu: flight recorder dumped "
                f"{len(events)} events to {path} ({reason})"
            )
            return path
        except Exception:
            # advisory: the dump is post-mortem best-effort — failing to
            # write it must not mask the fault that triggered it.
            return None


# -- module plane (mirrors obs.metrics / obs.events arming) ----------------

_active: FlightRecorder | None = None


def activate_flightrec(
    depth: int = DEFAULT_DEPTH, clock=None
) -> FlightRecorder:
    global _active
    _active = FlightRecorder(depth, clock or time.monotonic)
    return _active


def deactivate_flightrec() -> None:
    global _active
    _active = None


def active_flightrec() -> FlightRecorder | None:
    return _active


def dump_active(reason: str) -> str | None:
    """Dump the armed recorder, if any (one attribute check when off)."""
    rec = _active
    if rec is not None:
        return rec.dump(reason)
    return None


def dump_fleet_tape(wid: str, events, reason: str) -> str | None:
    """Write a tape COLLECTED from a fleet worker (its last posted
    observability snapshot) as a ``kind="flightrec"`` envelope in the
    same dump directory — the coordinator calls this when it declares
    the worker dead, so the worker's final seconds survive its own
    inability to dump.  Never raises; returns the path or None."""
    try:
        evs = [
            dict(e) for e in events
            if isinstance(e, dict)
            and e.get("kind") in ("event", "span")
            and e.get("name")
        ]
        rec = FlightRecorder(depth=max(1, len(evs)))
        rec_body = wrap_report("flightrec", {
            "reason": f"{reason}:{wid}",
            "depth": rec.depth,
            "dropped": 0,
            "events": evs,
            "worker": str(wid),
        })
        dump_dir = rec._dump_dir()
        os.makedirs(dump_dir, exist_ok=True)
        path = os.path.join(
            dump_dir, f"fleet-tape-{wid}-{os.getpid()}-{reason}.json"
        )
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(json.dumps(rec_body, indent=2, sort_keys=True) + "\n")
        os.replace(tmp, path)
        log_line(
            f"mpi_openmp_cuda_tpu: collected fleet tape "
            f"({len(evs)} events) from {wid} to {path} ({reason})"
        )
        return path
    except Exception:
        # advisory: post-mortem best-effort, same contract as dump().
        return None
