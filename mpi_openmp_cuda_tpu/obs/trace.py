"""Request-scoped tracing: the third obs tier (counters < spans < traces).

The metrics registry (PR 5) says *how much*; the span recorder says
*where the wall went by phase*; neither can say which REQUEST paid for a
given launch once the batcher coalesces sessions into shared
superblocks.  This module closes that gap:

* trace ids are minted at admission (``serve/queue.py``, from the
  queue's own deterministic sequence counter — no clock, SEQ005) and
  ride the bus fields of every per-request event;
* each pipeline dispatch is recorded as a *launch* carrying the full
  list of linked request ids (many-to-one: one ``pallas_call`` serves
  rows from several concurrent requests);
* every finished launch is priced with the static cost model
  (``analysis/costmodel`` via ``ops/pallas_scorer``), producing a
  parallel *modelled* track and a ``measured - modelled`` gap row — the
  launch-by-launch attribution of the MFU gap the roofline sheet only
  reports in aggregate.

Export is Chrome-trace / Perfetto JSON (``traceEvents``) wrapped in the
versioned run-report envelope as ``kind="trace"``; Perfetto ignores the
extra envelope keys, so the report file loads directly in the UI.

Thread contract: ``record_event`` runs on whatever thread publishes
(reader threads, the watchdog monitor), ``span_closed`` and the launch
hooks on the main loop thread, ``export`` on exit or a telemetry
thread — every mutation crosses the recorder's own lock (SEQ008; the
module is classified serve-plane for exactly that rule).
"""

from __future__ import annotations

import threading
import time

from .metrics import wrap_report

#: Hard cap on buffered trace events: a long-lived server must not grow
#: its trace without bound.  Beyond the cap new events are counted in
#: ``dropped_events`` instead of buffered.
MAX_EVENTS = 200_000

# Perfetto track layout.  Two synthetic "processes": the host plane
# (spans / per-request rows / raw bus events) and the launch plane
# (measured dispatch walls with the cost model's modelled walls as the
# parallel track directly beneath them).
_PID_HOST = 1
_PID_LAUNCH = 2
_TID_SPANS = 1
_TID_REQUESTS = 2
_TID_EVENTS = 3
_TID_MEASURED = 1
_TID_MODELLED = 2

#: Perfetto metadata events naming the tracks (prepended at export).
_METADATA = (
    {"ph": "M", "pid": _PID_HOST, "tid": 0, "name": "process_name",
     "args": {"name": "seqalign-host"}},
    {"ph": "M", "pid": _PID_HOST, "tid": _TID_SPANS, "name": "thread_name",
     "args": {"name": "spans"}},
    {"ph": "M", "pid": _PID_HOST, "tid": _TID_REQUESTS,
     "name": "thread_name", "args": {"name": "requests"}},
    {"ph": "M", "pid": _PID_HOST, "tid": _TID_EVENTS, "name": "thread_name",
     "args": {"name": "events"}},
    {"ph": "M", "pid": _PID_LAUNCH, "tid": 0, "name": "process_name",
     "args": {"name": "seqalign-launches"}},
    {"ph": "M", "pid": _PID_LAUNCH, "tid": _TID_MEASURED,
     "name": "thread_name", "args": {"name": "measured"}},
    {"ph": "M", "pid": _PID_LAUNCH, "tid": _TID_MODELLED,
     "name": "thread_name", "args": {"name": "modelled (cost model)"}},
)

#: Bus events that open / close one request's row on the requests track.
_REQUEST_OPEN = "serve.request.admitted"
_REQUEST_CLOSE = {
    "serve.request.done": "done",
    "serve.request.failed": "failed",
    "serve.request.abandoned": "abandoned",
}

#: First pid handed to merged fleet-worker tracks (the coordinator's own
#: planes own pids 1 and 2; workers get 3, 4, ... in sorted-wid order so
#: the merged export is deterministic for the golden).
_PID_WORKER_BASE = 3

#: Merged worker events keep their within-worker track identity through
#: a (pid, tid) -> merged-tid fold; unknown shapes land on a catch-all.
_WORKER_TID_NAMES = {
    (_PID_HOST, _TID_SPANS): "spans",
    (_PID_HOST, _TID_REQUESTS): "requests",
    (_PID_HOST, _TID_EVENTS): "events",
    (_PID_LAUNCH, _TID_MEASURED): "measured",
    (_PID_LAUNCH, _TID_MODELLED): "modelled (cost model)",
}

#: Bound on buffered board-phase rows (one per fleet-scored superblock;
#: beyond it new rows are counted in ``dropped_events``).
MAX_BOARD_PHASES = 50_000

#: The five board-phase names, offer-posted -> demuxed, in wire order.
#: ``total`` is defined as the SUM of the four intervals, so the smoke
#: gates' totals==sums invariant holds by construction and any clamping
#: of a skewed interval stays visible as a shrunk total.
BOARD_PHASES = (
    "offer_to_claim",
    "claim_to_score",
    "score_to_post",
    "post_to_demux",
    "total",
)

_BLK = 128


def modelled_launch_wall_s(len1: int, lens) -> float:
    """Static-cost-model wall for ONE dispatch of ``len(lens)`` rows.

    Prices the launch exactly the way the schedule auditor prices a
    bucket: build the real Seq2-length histogram (rounded up to lane
    multiples), take the BEST emittable superblock config at the i8
    feed (the serving feed's floor — the same deliberate-lower-bound
    stance as ``serve/slo.py`` admission pricing), and add the fixed
    per-launch overhead.  Returns 0.0 on ANY failure: the gap row must
    stay finite on CPU CI where the calibration sheet may not cover
    every shape, and tracing must never be able to fail a dispatch.
    """
    try:
        from ..analysis.costmodel import LAUNCH_OVERHEAD_S
        from ..ops.pallas_scorer import (
            emittable_superblocks,
            model_constants,
            superblock_model_cost,
        )
        from ..utils.constants import BUF_SIZE_SEQ1, BUF_SIZE_SEQ2

        nbn = max(1, -(-min(int(len1), BUF_SIZE_SEQ1) // _BLK))
        hist: dict[int, int] = {}
        nbi = 1
        for l2 in lens:
            l2 = min(int(l2), BUF_SIZE_SEQ2)
            if l2 <= 0:
                continue
            l2r = -(-l2 // _BLK) * _BLK
            hist[l2r] = hist.get(l2r, 0) + 1
            nbi = max(nbi, l2r // _BLK)
        if not hist:
            return 0.0
        base, per_sb, rate = model_constants("i8")
        lens_hist = tuple(sorted(hist.items()))
        best = 0.0
        for sb in emittable_superblocks(nbn, nbi, "i8"):
            wall = superblock_model_cost(
                nbn, nbi, int(len1), lens_hist, sb,
                base=base, per_sb=per_sb, rate=rate,
            )
            if wall > 0.0 and (best == 0.0 or wall < best):
                best = wall
        return best + LAUNCH_OVERHEAD_S if best > 0.0 else 0.0
    except Exception:
        # advisory: the modelled-wall column is a cost-model estimate —
        # 0.0 drops the column, the measured trace stands on its own.
        return 0.0


class TraceRecorder:
    """Bounded in-memory Chrome-trace builder for one run.

    Subscribes to the event bus (instant events + request rows), to the
    span recorder's close listener (host spans), and to the pipeline's
    launch hooks (measured/modelled launch tracks + gap rows).
    """

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self._lock = threading.Lock()
        self._t0 = clock()
        self._events: list[dict] = []
        self._gaps: list[dict] = []
        self._launches: dict = {}
        self._open_requests: dict = {}
        self._dropped = 0
        # Fleet plane (coordinator side): per-superblock board-phase
        # rows, per-worker clock-offset estimates, and the gathered
        # worker trace snapshots merged into the export as offset-
        # aligned per-worker tracks.
        self._board_phases: list[dict] = []
        self._clock_offsets: dict[str, dict] = {}
        self._worker_tracks: dict[str, tuple[float, list[dict]]] = {}

    def _us(self, t: float) -> float:
        return round((t - self._t0) * 1e6, 3)

    def now_us(self) -> float:
        """The current trace-timeline timestamp (microseconds since this
        recorder armed) — the clock-bridge sample a fleet worker posts
        next to its board-clock reading so the coordinator can map the
        worker's trace timeline onto its own."""
        return self._us(self._clock())

    # -- bus subscriber ----------------------------------------------------

    def record_event(self, event: str, fields: dict) -> None:
        """Every bus event becomes an instant; admitted→done/failed/
        abandoned pairs (matched by trace id) additionally close one
        complete row on the requests track."""
        t = self._clock()
        ev = {
            "name": event,
            "cat": "bus",
            "ph": "i",
            "ts": self._us(t),
            "pid": _PID_HOST,
            "tid": _TID_EVENTS,
            "s": "t",
            "args": dict(fields),
        }
        trace = fields.get("trace")
        outcome = _REQUEST_CLOSE.get(event) if trace is not None else None
        with self._lock:
            if len(self._events) >= MAX_EVENTS:
                self._dropped += 1
                return
            self._events.append(ev)
            if event == _REQUEST_OPEN and trace is not None:
                self._open_requests[trace] = (
                    str(fields.get("id", trace)), t,
                )
            elif outcome is not None:
                opened = self._open_requests.pop(trace, None)
                if opened is not None:
                    rid, t_open = opened
                    self._events.append({
                        "name": rid,
                        "cat": "request",
                        "ph": "X",
                        "ts": self._us(t_open),
                        "dur": round((t - t_open) * 1e6, 3),
                        "pid": _PID_HOST,
                        "tid": _TID_REQUESTS,
                        "args": {"trace": trace, "outcome": outcome},
                    })

    # -- span-recorder listener --------------------------------------------

    def span_closed(self, path: str, start: float, dur: float) -> None:
        ev = {
            "name": path,
            "cat": "span",
            "ph": "X",
            "ts": self._us(start),
            "dur": round(dur * 1e6, 3),
            "pid": _PID_HOST,
            "tid": _TID_SPANS,
            "args": {},
        }
        with self._lock:
            if len(self._events) >= MAX_EVENTS:
                self._dropped += 1
                return
            self._events.append(ev)

    # -- launch hooks (io/pipeline.py) -------------------------------------

    def launch_begin(self, key, *, links=(), len1=0, lens=(), ctx=None) -> None:
        """Arm one dispatch.  ``key`` is any hashable unique while the
        launch is in flight (the pipeline uses ``id(promise)``; the
        entry is popped at ``launch_end``, so id reuse after retirement
        is harmless).  ``links`` is the list of request ids whose rows
        ride this launch.  ``ctx`` (fleet workers only) stamps the
        originating trace ids, worker id, and lease epoch onto the
        launch row and its trace events."""
        entry = (
            tuple(links),
            int(len1),
            tuple(int(x) for x in lens),
            self._clock(),
            dict(ctx) if ctx else None,
        )
        with self._lock:
            self._launches[key] = entry

    def launch_end(self, key) -> None:
        """Close one dispatch: measured wall (dispatch → host rows,
        device-fenced by materialisation itself), modelled wall from
        the cost model, and the gap row.  Unknown keys are ignored —
        a launch that failed mid-flight stays counted as unfinished."""
        t = self._clock()
        with self._lock:
            entry = self._launches.pop(key, None)
        if entry is None:
            return
        links, len1, lens, t_begin, ctx = entry
        measured = t - t_begin
        modelled = modelled_launch_wall_s(len1, lens)
        request_ids = list(links)
        measured_ev = {
            "name": "dispatch",
            "cat": "launch",
            "ph": "X",
            "ts": self._us(t_begin),
            "dur": round(measured * 1e6, 3),
            "pid": _PID_LAUNCH,
            "tid": _TID_MEASURED,
            "args": {
                "request_ids": request_ids,
                "rows": len(lens),
                "len1": len1,
            },
        }
        modelled_ev = {
            "name": "modelled",
            "cat": "model",
            "ph": "X",
            "ts": self._us(t_begin),
            "dur": round(modelled * 1e6, 3),
            "pid": _PID_LAUNCH,
            "tid": _TID_MODELLED,
            "args": {"request_ids": request_ids},
        }
        row = {
            "request_ids": request_ids,
            "rows": len(lens),
            "len1": len1,
            "measured_s": round(measured, 9),
            "modelled_s": round(modelled, 9),
            "gap_s": round(measured - modelled, 9),
        }
        if ctx:
            # Fleet-worker stamp: the propagated admission trace ids,
            # this worker's id, and the claim's lease epoch — absent on
            # local launches so batch/serve rows (and their goldens)
            # stay byte-identical.
            measured_ev["args"].update(ctx)
            row.update(ctx)
        with self._lock:
            if len(self._events) + 2 > MAX_EVENTS:
                self._dropped += 2
            else:
                self._events.append(measured_ev)
                self._events.append(modelled_ev)
            self._gaps.append(row)

    # -- fleet plane (coordinator side) ------------------------------------

    def board_phase(self, row: dict) -> None:
        """Record one fleet-scored superblock's board-phase breakdown
        (serve/fleet.py builds the row: bid, worker, epoch, propagated
        trace ids, clock offset, and the five phase durations)."""
        with self._lock:
            if len(self._board_phases) >= MAX_BOARD_PHASES:
                self._dropped += 1
                return
            self._board_phases.append(dict(row))

    def set_clock_offsets(self, offsets: dict) -> None:
        """Publish the coordinator's current per-worker clock-offset
        estimates (ClockOffsetEstimator.snapshot())."""
        with self._lock:
            self._clock_offsets = dict(offsets)

    def set_worker_track(self, wid: str, events, shift_us: float) -> None:
        """Install (or refresh) one worker's gathered trace snapshot.
        ``events`` is the worker recorder's bounded event list;
        ``shift_us`` maps its timestamps onto THIS recorder's timeline
        (worker-trace -> worker-board -> coordinator-board ->
        coordinator-trace, all deterministic arithmetic).  Snapshots
        overwrite in place: the newest gather wins."""
        evs = [dict(e) for e in events if isinstance(e, dict)]
        with self._lock:
            self._worker_tracks[str(wid)] = (float(shift_us), evs)

    def snapshot_events(self, limit: int = 2000) -> list[dict]:
        """The newest ``limit`` buffered events, detached — the bounded
        payload a fleet worker posts over the board."""
        with self._lock:
            tail = self._events[-int(limit):] if limit else []
        return [dict(e) for e in tail]

    def _merged_worker_events(self) -> list[dict]:
        """Per-worker Perfetto tracks: each gathered worker snapshot on
        its own pid (sorted-wid order from ``_PID_WORKER_BASE``), with
        generated metadata events and timestamps shifted onto this
        recorder's timeline."""
        with self._lock:
            tracks = {
                wid: (shift, list(evs))
                for wid, (shift, evs) in self._worker_tracks.items()
            }
        out: list[dict] = []
        for i, wid in enumerate(sorted(tracks)):
            shift, evs = tracks[wid]
            pid = _PID_WORKER_BASE + i
            out.append({
                "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
                "args": {"name": f"seqalign-worker {wid}"},
            })
            named: set[int] = set()
            for ev in evs:
                old = (ev.get("pid", _PID_HOST), ev.get("tid", _TID_EVENTS))
                tid = old[0] * 4 + old[1]
                if tid not in named:
                    named.add(tid)
                    out.append({
                        "ph": "M", "pid": pid, "tid": tid,
                        "name": "thread_name",
                        "args": {
                            "name": _WORKER_TID_NAMES.get(
                                old, f"p{old[0]}t{old[1]}"
                            )
                        },
                    })
                merged = dict(ev)
                merged["pid"] = pid
                merged["tid"] = tid
                ts = merged.get("ts")
                if isinstance(ts, (int, float)):
                    merged["ts"] = round(float(ts) + shift, 3)
                out.append(merged)
        return out

    # -- export ------------------------------------------------------------

    def gap_attribution(self) -> dict:
        """The per-launch ``measured - modelled`` table plus its totals
        (the run report's ``gap_attribution`` section).  With fleet
        data recorded, the section additionally carries the per-
        superblock ``board_phases`` rows, their per-phase totals, and
        the per-worker ``clock_offsets`` — absent otherwise, so local
        runs' reports are byte-identical to before."""
        with self._lock:
            launches = [dict(g) for g in self._gaps]
            unfinished = len(self._launches)
            phases = [dict(p) for p in self._board_phases]
            offsets = dict(self._clock_offsets)
        total_measured = sum(g["measured_s"] for g in launches)
        total_modelled = sum(g["modelled_s"] for g in launches)
        out = {
            "launches": launches,
            "launch_count": len(launches),
            "unfinished_launches": unfinished,
            "total_measured_s": round(total_measured, 9),
            "total_modelled_s": round(total_modelled, 9),
            "total_gap_s": round(total_measured - total_modelled, 9),
        }
        if phases:
            out["board_phases"] = phases
            out["board_phase_totals"] = {
                name: round(
                    sum(
                        float(p.get("phases", {}).get(name, 0.0))
                        for p in phases
                    ),
                    9,
                )
                for name in BOARD_PHASES
            }
        if offsets:
            out["clock_offsets"] = offsets
        return out

    def export(self, *, exit_code=None, meta=None) -> dict:
        """The full ``kind="trace"`` envelope.  ``traceEvents`` is the
        Chrome-trace payload (Perfetto ignores the sibling keys);
        gathered fleet-worker snapshots ride as additional per-worker
        tracks, offset-aligned to this recorder's timeline."""
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
        body = {
            "traceEvents": (
                list(_METADATA) + events + self._merged_worker_events()
            ),
            "displayTimeUnit": "ms",
            "gap_attribution": self.gap_attribution(),
            "dropped_events": dropped,
        }
        if exit_code is not None:
            body["exit_code"] = int(exit_code)
        return wrap_report("trace", body, meta=meta)


# -- module plane (mirrors obs.metrics / obs.events arming) ----------------

_active: TraceRecorder | None = None


def activate_trace(clock=None) -> TraceRecorder:
    global _active
    _active = TraceRecorder(clock or time.perf_counter)
    return _active


def deactivate_trace() -> None:
    global _active
    _active = None


def active_trace() -> TraceRecorder | None:
    return _active


def trace_launch_begin(key, *, links=(), len1=0, lens=(), ctx=None) -> None:
    """No-op unless the trace plane is armed (one attribute check)."""
    rec = _active
    if rec is not None:
        rec.launch_begin(key, links=links, len1=len1, lens=lens, ctx=ctx)


def trace_launch_end(key) -> None:
    rec = _active
    if rec is not None:
        rec.launch_end(key)


def trace_board_phase(row: dict) -> None:
    """Record one fleet board-phase breakdown row (no-op unarmed)."""
    rec = _active
    if rec is not None:
        rec.board_phase(row)


def trace_clock_offsets(offsets: dict) -> None:
    """Publish per-worker clock-offset estimates (no-op unarmed)."""
    rec = _active
    if rec is not None:
        rec.set_clock_offsets(offsets)


def trace_worker_track(wid: str, events, shift_us: float) -> None:
    """Install a gathered worker trace snapshot (no-op unarmed)."""
    rec = _active
    if rec is not None:
        rec.set_worker_track(wid, events, shift_us)
