"""Report writing, the heartbeat line, and the multi-host metrics plane.

Three consumers of the armed registry:

* :func:`flush_run_report` — the CLI's exit hook: writes the JSON run
  report at ``--metrics-out`` plus a Prometheus text sidecar at
  ``<out>.prom``.  Called from the run's ``finally``, so a failed run
  (exit 65) and a drained run (exit 75) still flush their reports.
* :func:`heartbeat_callback` — the periodic ``[obs] ...`` stderr line
  the watchdog monitor thread emits between operations
  (``--heartbeat`` / ``SEQALIGN_HEARTBEAT_S``).
* :func:`post_host_snapshot` / :func:`gather_fleet` — under
  ``--distributed``, per-host snapshots ride the same board machinery
  as the lost-shard rescue (:mod:`..resilience.rescue`): each worker
  posts its snapshot next to its rows, the coordinator folds them into
  the ``hosts`` section of one merged fleet report.  A worker that died
  simply has no snapshot key — absence over negotiation, exactly the
  beacon contract.
"""

from __future__ import annotations

import json
import os

from . import metrics as _metrics
from .events import log_line


def flush_run_report(
    registry,
    spans,
    path: str | None,
    *,
    exit_code: int | None = None,
    meta: dict | None = None,
    extra: dict | None = None,
) -> dict | None:
    """Write the run report (and ``.prom`` sidecar) for one finished
    run; no-op without a path or registry.  Returns the report dict.
    ``extra`` merges additional top-level body sections (the trace
    plane's ``gap_attribution``) into the report.

    Writes are tmp-file + rename so a preemption mid-flush leaves the
    previous report intact, never a torn JSON document (the journal's
    torn-tail lesson applied to reports)."""
    if registry is None or path is None:
        return None
    rec = _metrics.run_report(
        registry, spans=spans, exit_code=exit_code, meta=meta, extra=extra
    )
    _atomic_write(path, json.dumps(rec, indent=2, sort_keys=True) + "\n")
    _atomic_write(path + ".prom", _metrics.to_prometheus(registry.snapshot()))
    return rec


def _atomic_write(path: str, text: str) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


def flush_trace(
    tracer,
    path: str | None,
    *,
    exit_code: int | None = None,
    meta: dict | None = None,
) -> dict | None:
    """Write the Perfetto/Chrome-trace envelope for one finished run
    (``--trace-out`` / ``SEQALIGN_TRACE``); no-op without a path or an
    armed tracer.  Same atomic-write stance as the run report — and the
    same every-exit-path contract: a crashed run's trace is often the
    only timeline of what wedged."""
    if tracer is None or path is None:
        return None
    rec = tracer.export(exit_code=exit_code, meta=meta)
    _atomic_write(path, json.dumps(rec, indent=2, sort_keys=True) + "\n")
    return rec


# -- heartbeat -------------------------------------------------------------


def heartbeat_line(snapshot: dict) -> str:
    """One ``[obs]`` status line from a registry snapshot (the format in
    the README's observability walkthrough)."""
    c = snapshot.get("counters", {})
    g = snapshot.get("gauges", {})
    total = g.get("chunks_total", "?")
    degraded = "yes" if c.get("degrade_transitions") else "no"
    line = (
        f"[obs] chunk {c.get('chunks_dispatched', 0)}/{total} "
        f"retries={c.get('retry_attempts', 0)} degraded={degraded}"
    )
    if "queue_depth" in g:
        # Serve mode only (the gauge exists only there): the batch-mode
        # heartbeat golden stays byte-identical.
        line += f" queue={g['queue_depth']}"
    if "shed_state" in g:
        line += f" shed={g['shed_state']}"
    if "breaker_state" in g:
        line += f" breaker={g['breaker_state']}"
    if "fleet_workers" in g:
        # Fleet coordinator only (the gauge exists only under
        # --fleet-board): batch AND plain-serve heartbeats unchanged.
        line += f" fleet={g['fleet_workers']}"
    return line


def heartbeat_callback(log=None):
    """The zero-argument emitter the watchdog's monitor thread calls on
    each quiet heartbeat interval."""
    emit = log or log_line

    def beat() -> None:
        reg = _metrics.active_metrics()
        if reg is not None:
            emit(heartbeat_line(reg.snapshot()))

    return beat


# -- the multi-host metrics plane ------------------------------------------


def _metrics_key(run_tag: str, pid: int) -> str:
    return f"seqalign/{run_tag}/metrics/{int(pid)}"


def post_host_snapshot(board, run_tag: str, pid: int) -> None:
    """Worker side: post this host's snapshot to the board (no-op with
    metrics off — a run where only some hosts enabled metrics still
    completes; the coordinator just reports the posters)."""
    reg = _metrics.active_metrics()
    if reg is None:
        return
    board.post(_metrics_key(run_tag, pid), json.dumps(reg.snapshot()))


def gather_fleet(
    board,
    run_tag: str,
    num_processes: int,
    *,
    skip=(),
    timeout_s: float | None = None,
) -> None:
    """Coordinator side: fold every posted host snapshot into the armed
    registry's fleet section.  ``skip`` lists workers already known lost
    (no point waiting out their timeout twice); a missing or torn
    snapshot is simply omitted, mirroring :func:`..resilience.rescue.
    fetch_shard`'s absence-over-negotiation contract."""
    reg = _metrics.active_metrics()
    if reg is None:
        return
    for w in range(int(num_processes)):
        if w in skip:
            continue
        raw = board.get(_metrics_key(run_tag, w), timeout_s)
        if raw is None:
            continue
        try:
            snap = json.loads(raw)
        except json.JSONDecodeError:
            continue
        if isinstance(snap, dict):
            reg.record_fleet(w, snap)


# -- the fleet observability plane (serve/fleet.py) ------------------------


def post_worker_snapshot(
    board, wid: str, t_board: float, *, beat: int = 0, trace_limit: int = 2000
) -> None:
    """Fleet-worker side of the serve-fleet obs plane: post ONE bounded
    observability snapshot to ``obs_snapshot_key(wid)``, overwritten in
    place each cadence (the board holds only the newest).  The payload
    bundles the registry snapshot (metrics federation), the newest
    trace events (timeline merge), the flight-recorder tape (post-
    mortem collection when this worker is declared dead), and the
    clock-bridge pair: ``t_board`` (the worker's ServeClock reading,
    sampled by the caller immediately before this call) next to
    ``t_trace_us`` (its trace clock, sampled here back-to-back) — the
    coordinator subtracts the pair to map trace timestamps onto board
    time, then its offset estimate maps board time across processes.

    Same absence-over-negotiation stance as :func:`post_host_snapshot`:
    planes that are not armed simply leave their key out."""
    from ..resilience.membership import obs_snapshot_key
    from .flightrec import active_flightrec
    from .trace import active_trace

    snap: dict = {
        "wid": str(wid),
        "pid": os.getpid(),
        "beat": int(beat),
        "t_board": float(t_board),
    }
    reg = _metrics.active_metrics()
    if reg is not None:
        snap["metrics"] = reg.snapshot()
    tracer = active_trace()
    if tracer is not None:
        snap["t_trace_us"] = tracer.now_us()
        snap["trace"] = {"events": tracer.snapshot_events(trace_limit)}
    rec = active_flightrec()
    if rec is not None:
        snap["tape"] = rec.snapshot_tape()
    board.post(obs_snapshot_key(str(wid)), json.dumps(snap))


def collect_worker_snapshot(board, wid: str) -> dict | None:
    """Coordinator side: the newest snapshot a worker posted, or None
    when missing/torn/alien (absence over negotiation — a worker that
    never armed its obs plane, or died before its first post, simply
    contributes nothing)."""
    from ..resilience.membership import read_obs_snapshot

    return read_obs_snapshot(board, str(wid))
