"""Report writing, the heartbeat line, and the multi-host metrics plane.

Three consumers of the armed registry:

* :func:`flush_run_report` — the CLI's exit hook: writes the JSON run
  report at ``--metrics-out`` plus a Prometheus text sidecar at
  ``<out>.prom``.  Called from the run's ``finally``, so a failed run
  (exit 65) and a drained run (exit 75) still flush their reports.
* :func:`heartbeat_callback` — the periodic ``[obs] ...`` stderr line
  the watchdog monitor thread emits between operations
  (``--heartbeat`` / ``SEQALIGN_HEARTBEAT_S``).
* :func:`post_host_snapshot` / :func:`gather_fleet` — under
  ``--distributed``, per-host snapshots ride the same board machinery
  as the lost-shard rescue (:mod:`..resilience.rescue`): each worker
  posts its snapshot next to its rows, the coordinator folds them into
  the ``hosts`` section of one merged fleet report.  A worker that died
  simply has no snapshot key — absence over negotiation, exactly the
  beacon contract.
"""

from __future__ import annotations

import json
import os

from . import metrics as _metrics
from .events import log_line


def flush_run_report(
    registry,
    spans,
    path: str | None,
    *,
    exit_code: int | None = None,
    meta: dict | None = None,
    extra: dict | None = None,
) -> dict | None:
    """Write the run report (and ``.prom`` sidecar) for one finished
    run; no-op without a path or registry.  Returns the report dict.
    ``extra`` merges additional top-level body sections (the trace
    plane's ``gap_attribution``) into the report.

    Writes are tmp-file + rename so a preemption mid-flush leaves the
    previous report intact, never a torn JSON document (the journal's
    torn-tail lesson applied to reports)."""
    if registry is None or path is None:
        return None
    rec = _metrics.run_report(
        registry, spans=spans, exit_code=exit_code, meta=meta, extra=extra
    )
    _atomic_write(path, json.dumps(rec, indent=2, sort_keys=True) + "\n")
    _atomic_write(path + ".prom", _metrics.to_prometheus(registry.snapshot()))
    return rec


def _atomic_write(path: str, text: str) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


def flush_trace(
    tracer,
    path: str | None,
    *,
    exit_code: int | None = None,
    meta: dict | None = None,
) -> dict | None:
    """Write the Perfetto/Chrome-trace envelope for one finished run
    (``--trace-out`` / ``SEQALIGN_TRACE``); no-op without a path or an
    armed tracer.  Same atomic-write stance as the run report — and the
    same every-exit-path contract: a crashed run's trace is often the
    only timeline of what wedged."""
    if tracer is None or path is None:
        return None
    rec = tracer.export(exit_code=exit_code, meta=meta)
    _atomic_write(path, json.dumps(rec, indent=2, sort_keys=True) + "\n")
    return rec


# -- heartbeat -------------------------------------------------------------


def heartbeat_line(snapshot: dict) -> str:
    """One ``[obs]`` status line from a registry snapshot (the format in
    the README's observability walkthrough)."""
    c = snapshot.get("counters", {})
    g = snapshot.get("gauges", {})
    total = g.get("chunks_total", "?")
    degraded = "yes" if c.get("degrade_transitions") else "no"
    line = (
        f"[obs] chunk {c.get('chunks_dispatched', 0)}/{total} "
        f"retries={c.get('retry_attempts', 0)} degraded={degraded}"
    )
    if "queue_depth" in g:
        # Serve mode only (the gauge exists only there): the batch-mode
        # heartbeat golden stays byte-identical.
        line += f" queue={g['queue_depth']}"
    if "shed_state" in g:
        line += f" shed={g['shed_state']}"
    if "breaker_state" in g:
        line += f" breaker={g['breaker_state']}"
    if "fleet_workers" in g:
        # Fleet coordinator only (the gauge exists only under
        # --fleet-board): batch AND plain-serve heartbeats unchanged.
        line += f" fleet={g['fleet_workers']}"
    return line


def heartbeat_callback(log=None):
    """The zero-argument emitter the watchdog's monitor thread calls on
    each quiet heartbeat interval."""
    emit = log or log_line

    def beat() -> None:
        reg = _metrics.active_metrics()
        if reg is not None:
            emit(heartbeat_line(reg.snapshot()))

    return beat


# -- the multi-host metrics plane ------------------------------------------


def _metrics_key(run_tag: str, pid: int) -> str:
    return f"seqalign/{run_tag}/metrics/{int(pid)}"


def post_host_snapshot(board, run_tag: str, pid: int) -> None:
    """Worker side: post this host's snapshot to the board (no-op with
    metrics off — a run where only some hosts enabled metrics still
    completes; the coordinator just reports the posters)."""
    reg = _metrics.active_metrics()
    if reg is None:
        return
    board.post(_metrics_key(run_tag, pid), json.dumps(reg.snapshot()))


def gather_fleet(
    board,
    run_tag: str,
    num_processes: int,
    *,
    skip=(),
    timeout_s: float | None = None,
) -> None:
    """Coordinator side: fold every posted host snapshot into the armed
    registry's fleet section.  ``skip`` lists workers already known lost
    (no point waiting out their timeout twice); a missing or torn
    snapshot is simply omitted, mirroring :func:`..resilience.rescue.
    fetch_shard`'s absence-over-negotiation contract."""
    reg = _metrics.active_metrics()
    if reg is None:
        return
    for w in range(int(num_processes)):
        if w in skip:
            continue
        raw = board.get(_metrics_key(run_tag, w), timeout_s)
        if raw is None:
            continue
        try:
            snap = json.loads(raw)
        except json.JSONDecodeError:
            continue
        if isinstance(snap, dict):
            reg.record_fleet(w, snap)
