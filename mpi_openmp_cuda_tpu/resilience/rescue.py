"""Lost-shard rescue: beacons, the shard ledger, orphan rescoring.

The reference's distribution tier is ``MPI_Scatter`` + ``MPI_Gatherv``
(main.c:174-197): rank 0 owns the index ledger implicitly, and a dead
rank kills the job inside the gather.  The TPU-native rescue tier
(driven by :func:`parallel.distributed.scatter_gather_rescue`) keeps
the scatter semantics but makes the gather survivable:

* :func:`shard_index_sets` — the coordinator-side **ledger**: the same
  deterministic contiguous split on every process, so "which index-set
  did the missing worker own" is a pure function, not a negotiation.
* A **board** — a tiny key-value bulletin each process posts its
  liveness beacon and result rows to.  :class:`CoordinationBoard` backs
  it with jax.distributed's coordination-service KV store (the one
  multi-host channel that still works when a *peer* is dead — a
  collective would hang); :class:`MemoryBoard` is the in-process
  equivalent for single-process runs and simulated-loss tests, where a
  missing key IS a missed deadline (deterministic, no clock);
  :class:`FileBoard` is the multi-process single-machine form (atomic
  directory posts, no jax.distributed) that backs the elastic serve
  fleet (serve/fleet.py + resilience/membership.py).
* :func:`fetch_shard` — the per-worker gather: beacon first, rows
  second, timeout (``SEQALIGN_BEACON_S``) identifying the lost worker.
  All timing lives in the board's blocking get (the monitoring
  boundary); nothing here reads a clock (seqlint SEQ005).
* :func:`rescue_orphans` — coordinator-side rescoring of the orphaned
  indices on a LOCAL scorer through the PR 1 degradation chain
  (xla -> xla-gather), so the run completes with byte-identical output
  minus the dead worker's speedup.
"""

from __future__ import annotations

import errno
import json
import os
import threading

import numpy as np

from ..obs.events import log_line, publish
from .degrade import BackendDegrader, run_degrading
from .faults import scheduled as _fault_scheduled


def shard_index_sets(total: int, parts: int) -> list[list[int]]:
    """The scatter ledger: a contiguous, balanced split of ``total``
    sequence indices over ``parts`` workers (MPI_Scatter parity,
    main.c:174 — earlier workers take the remainder).  Deterministic on
    every process, so ledger agreement needs no communication."""
    if parts < 1:
        raise ValueError(f"shard ledger needs >= 1 worker, got {parts}")
    base, extra = divmod(int(total), parts)
    out, start = [], 0
    for p in range(parts):
        n = base + (1 if p < extra else 0)
        out.append(list(range(start, start + n)))
        start += n
    return out


class MemoryBoard:
    """In-process bulletin board.

    Used by single-process runs and by the simulated-lost-worker tests:
    a worker that never posted simply has no key, and ``get`` returns
    None immediately — absence is the deterministic analogue of a
    missed wall-clock deadline.

    All boards share the torn-post guarantee: a post that did not land
    whole (here: an empty value, the in-memory stand-in for a writer
    killed before its bytes hit the board) reads as MISSING, never as
    data.  The fleet tier (resilience/membership.py) leans on three
    extra verbs every board grows here: ``claim`` (atomic post-if-absent
    — the lease race's single-winner primitive), ``delete``, and
    ``keys`` (prefix scan, the worker's offer discovery).
    """

    def __init__(self):
        self._kv: dict[str, str] = {}
        # Single dict reads/writes are GIL-atomic; `claim` is a
        # check-THEN-set, which is not — two threads racing one lease
        # key could both pass the check and both report victory.  The
        # lock restores the single-winner contract FileBoard gets from
        # os.link (the concurrent-claimers test races N threads on it).
        self._claim_lock = threading.Lock()

    def post(self, key: str, value: str) -> None:
        self._kv[key] = value

    def get(self, key: str, timeout_s: float | None = None) -> str | None:
        value = self._kv.get(key)
        return value if value else None  # zero-length post reads as missing

    def claim(self, key: str, value: str) -> bool:
        with self._claim_lock:
            if key in self._kv:
                return False
            self._kv[key] = value
            return True

    def delete(self, key: str) -> None:
        self._kv.pop(key, None)

    def keys(self, prefix: str) -> list[str]:
        return sorted(k for k in self._kv if k.startswith(prefix))


class FileBoard:
    """Directory-backed bulletin board for multi-process single-machine
    fleets (serve/fleet.py) — no jax.distributed required.

    Key ``a/b/c`` is the file ``root/a/b/c``.  Every ``post`` is atomic
    (tmp file + fsync + ``os.replace``), so a reader can never observe a
    half-written value under the final name; a writer killed mid-post
    leaves only a ``.tmp.`` orphan, which readers and ``keys`` skip.
    ``claim`` is ``os.link`` onto the final name: the filesystem makes
    exactly one linker win, so two workers racing one lease resolve
    without any coordination service.  Defensively, ``get`` still treats
    unreadable or zero-length files as missing — the chaos tier posts
    deliberately torn values through ``post`` to prove readers survive
    a board that DID tear (e.g. a non-atomic network filesystem).
    """

    _TMP = ".tmp."

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key: str) -> str:
        parts = [p for p in key.split("/") if p and p not in (".", "..")]
        if not parts:
            raise ValueError(f"empty board key: {key!r}")
        return os.path.join(self.root, *parts)

    def _write_tmp(self, path: str, value: str) -> str:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # pid alone is not unique enough: in-process worker THREADS
        # (the fleet tests, the serve readers) racing one key would
        # share one tmp file, and a claim could link the other racer's
        # bytes under its own victory.  pid + thread id makes every
        # concurrent writer's staging file its own.
        tmp = os.path.join(
            os.path.dirname(path),
            f"{self._TMP}{os.path.basename(path)}"
            f".{os.getpid()}.{threading.get_ident()}",
        )
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                if _fault_scheduled("board:enospc"):
                    # Modelled disk-full: half the bytes land, then the
                    # write fails — the worst torn-tmp shape.  The final
                    # key must still read as missing (the tmp never
                    # reaches os.replace/os.link) and the orphan must
                    # not leak.
                    fh.write(value[: len(value) // 2])
                    fh.flush()
                    raise OSError(
                        errno.ENOSPC, "injected: no space left on device"
                    )
                fh.write(value)
                fh.flush()
                os.fsync(fh.fileno())
        except OSError:
            # A failed staging write (ENOSPC, quota, I/O error) must not
            # leak the tmp orphan: the caller sees the post as never
            # having happened, and the board directory stays clean.
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return tmp

    def post(self, key: str, value: str) -> None:
        path = self._path(key)
        os.replace(self._write_tmp(path, value), path)

    def get(self, key: str, timeout_s: float | None = None) -> str | None:
        try:
            with open(self._path(key), encoding="utf-8") as fh:
                value = fh.read()
        except OSError:
            return None
        return value if value else None  # zero-length post reads as missing

    def claim(self, key: str, value: str) -> bool:
        path = self._path(key)
        tmp = self._write_tmp(path, value)
        try:
            os.link(tmp, path)  # atomic: exactly one claimer wins
            return True
        except FileExistsError:
            return False
        except OSError:
            return False  # unclaimable board == lost race, caller re-polls
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def delete(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except OSError:
            pass

    def keys(self, prefix: str) -> list[str]:
        out = []
        for dirpath, _dirs, files in os.walk(self.root):
            rel = os.path.relpath(dirpath, self.root)
            base = "" if rel == "." else rel.replace(os.sep, "/") + "/"
            for name in files:
                if name.startswith(self._TMP):
                    continue  # a dead writer's orphan, not a post
                key = base + name
                if key.startswith(prefix):
                    out.append(key)
        return sorted(out)

    def sweep_orphans(self) -> int:
        """Unlink every ``.tmp.`` orphan under the board root — the debris
        a writer killed mid-post leaves behind.  Readers already skip
        these, so this is pure hygiene (the fleet-chaos no-stale-keys
        gate).  Racing a LIVE writer is safe: its ``os.replace`` on an
        unlinked tmp raises OSError, which every board writer absorbs
        and retries as a lost post."""
        swept = 0
        for dirpath, _dirs, files in os.walk(self.root):
            for name in files:
                if not name.startswith(self._TMP):
                    continue
                try:
                    os.unlink(os.path.join(dirpath, name))
                    swept += 1
                except OSError:
                    pass
        return swept


class CoordinationBoard:
    """jax.distributed coordination-service KV board (multi-host).

    The coordination service is process 0's sidecar server, so it
    outlives any dead *worker* — exactly the channel a lost-shard gather
    needs.  ``get`` blocks up to the beacon deadline inside the service
    client (the monitoring boundary; no clock reads here) and returns
    None on timeout, which the caller treats as "worker lost".
    """

    def __init__(self, timeout_s: float):
        if timeout_s <= 0:
            raise ValueError(
                f"beacon deadline must be > 0 seconds, got {timeout_s}"
            )
        self.timeout_s = float(timeout_s)

    @staticmethod
    def _client():
        from jax._src import distributed as jax_distributed

        client = jax_distributed.global_state.client
        if client is None:
            raise RuntimeError(
                "no jax.distributed coordination service: the beacon board "
                "needs --distributed (or use MemoryBoard single-process)"
            )
        return client

    def post(self, key: str, value: str) -> None:
        self._client().key_value_set(key, value)

    def get(self, key: str, timeout_s: float | None = None) -> str | None:
        timeout = self.timeout_s if timeout_s is None else float(timeout_s)
        try:
            value = self._client().blocking_key_value_get(
                key, int(timeout * 1000)
            )
        except Exception:
            # advisory: timeout == lost worker; the ledger names it.
            return None
        return value if value else None  # zero-length post reads as missing

    def claim(self, key: str, value: str) -> bool:
        # The coordination service rejects a duplicate key_value_set, so
        # "set succeeded" IS the single-winner claim.  Best-effort: the
        # fleet's tested multi-process path is FileBoard; this keeps the
        # board verbs uniform for an eventual multi-host fleet.
        try:
            self._client().key_value_set(key, value)
            return True
        except Exception:
            # advisory: a rejected set IS the lost claim — False tells
            # the caller another worker won.
            return False

    def delete(self, key: str) -> None:
        try:
            self._client().key_value_delete(key)
        except Exception:
            pass  # advisory: best-effort — a stale key is fenced by epoch

    def keys(self, prefix: str) -> list[str]:
        try:
            pairs = self._client().key_value_dir_get(prefix)
        except Exception:
            # advisory: an unreadable dir reads as empty — the scan
            # simply retries on the next tick.
            return []
        return sorted(k for k, _v in pairs)


def _beacon_key(run_tag: str, pid: int) -> str:
    return f"seqalign/{run_tag}/beacon/{int(pid)}"


def _rows_key(run_tag: str, pid: int) -> str:
    return f"seqalign/{run_tag}/rows/{int(pid)}"


def post_shard(board, run_tag: str, pid: int, rows) -> None:
    """Worker side: liveness beacon first (cheap, lands even if the rows
    post is what the worker dies inside), then the scored rows."""
    board.post(_beacon_key(run_tag, pid), "scored")
    rows = np.asarray(rows, dtype=np.int32)
    board.post(_rows_key(run_tag, pid), json.dumps(rows.tolist()))


def fetch_shard(
    board, run_tag: str, pid: int, expect_n: int, timeout_s: float | None = None
) -> np.ndarray | None:
    """Coordinator side: gather one worker's shard under the beacon
    deadline.  Returns the [expect_n, 3] rows, or None when the worker
    is lost (no beacon, no rows, or rows of the wrong shape — a torn
    post is rescored, never trusted)."""
    rows = _fetch_shard(board, run_tag, pid, expect_n, timeout_s)
    if rows is None:
        publish("rescue.beacon_miss", worker=pid)
    return rows


def _fetch_shard(board, run_tag, pid, expect_n, timeout_s):
    if board.get(_beacon_key(run_tag, pid), timeout_s) is None:
        return None
    raw = board.get(_rows_key(run_tag, pid), timeout_s)
    if raw is None:
        return None
    try:
        rows = np.asarray(json.loads(raw), dtype=np.int32)
    except (json.JSONDecodeError, ValueError):
        return None
    if rows.shape != (int(expect_n), 3):
        return None
    return rows


def rescue_orphans(
    seq1_codes,
    orphan_codes,
    weights,
    *,
    policy,
    backend: str = "xla",
    log=None,
):
    """Rescore a lost worker's orphaned sequences on a LOCAL scorer.

    Runs through the degradation chain starting at ``backend`` (the
    local XLA backend by default — the rescue path must not depend on
    the same kernel runtime that may have taken the worker down), under
    the run's retry policy.  Returns [len(orphan_codes), 3] int32 rows.
    """
    from ..ops.dispatch import AlignmentScorer

    log = log or log_line
    publish("rescue.orphans", count=len(orphan_codes))
    start = "xla" if backend in ("pallas", "auto") else backend
    deg = BackendDegrader(
        AlignmentScorer(backend=start),
        lambda b: AlignmentScorer(backend=b),
        enabled=True,
        log=log,
    )
    return run_degrading(
        policy,
        deg,
        lambda: deg.scorer.score_codes(seq1_codes, orphan_codes, weights),
        lambda sc: sc.score_codes(seq1_codes, orphan_codes, weights),
        "orphan rescue",
        budget=policy.new_budget(),
    )
