"""Deterministic fault injection (chaos testing as a first-class tier).

The repo's retry/abort/resume machinery predates this module but was
only testable by monkeypatching scorer internals per test.  Here every
resilience-relevant code path is *instrumented*: it calls
:func:`fire(site)` with a stable site name, and an activated registry
decides — from a counted, fully deterministic schedule — whether that
invocation raises an injected error.  A chaos run is then an exact
reproducible test: same spec + same input => same faults at the same
points, every time, on every host (the counters depend only on the
program's own call sequence, which the lockstep SPMD schedule already
keeps identical across hosts).

Spec grammar (``SEQALIGN_FAULTS`` env var or ``--faults``)::

    spec    ::= entry (';' entry)*
    entry   ::= site ':' kv (',' kv)*
    kv      ::= 'fail=' N        # inject N consecutive faults
              | 'after=' M      # ... starting at invocation M (default 0)
              | 'kind=' transient|fatal

    SEQALIGN_FAULTS="chunk_scoring:fail=2;journal_append:fail=1"

``kind=transient`` (default) raises :class:`InjectedFaultError`
(retried by :class:`~.policy.RetryPolicy`); ``kind=fatal`` raises
:class:`InjectedFatalFaultError`, a ValueError — the policy's fatal
class — so the never-retry contract is testable too.

The registry is **armed per run**: the CLI activates it at entry and
deactivates in a finally, so library callers and unit tests that drive
the scorer directly never see ambient faults.  When inactive,
:func:`fire` is a single attribute check.

Instrumented sites:

========================  ====================================================
``chunk_dispatch``        ``AlignmentScorer.score_codes_async`` entry
``chunk_scoring``         result materialisation (``PendingResult.result`` /
                          ``BucketedPending.result``)
``device_transfer``       the prefetched device->host copy
                          (``PendingResult.prefetch``)
``journal_append``        every journal record write (``utils/journal.py``)
``broadcast_problem``     each coordinator broadcast
``broadcast_chunk``       (``parallel/distributed.py``)
``broadcast_index_set``
``broadcast_stream_meta``
========================  ====================================================

Survival-layer sites (PR 4) piggyback on those fire points but model a
*different* failure shape — the operation never returns, or the process
dies — instead of a raised error:

========================  ====================================================
``hang:dispatch``         ``chunk_dispatch`` never returns: blocks until the
                          armed watchdog deadline, then surfaces the transient
                          :class:`~.watchdog.DeadlineExpiredError`
``hang:gather``           same at ``chunk_scoring`` (result materialisation —
                          the ``block_until_ready`` / gather boundary)
``hang:broadcast``        same at every ``broadcast_*`` coordinator collective
``kill:journal-append``   SIGKILL this process at the scheduled
                          ``journal_append`` — a deterministic mid-batch
                          preemption for the kill-resume chaos tier
``kill:serve-tick``       SIGKILL at the scheduled serve-loop tick boundary
                          (``serve_tick`` fire point) — the serve-mode
                          kill-resume tier: the live journal must make the
                          rerun's ``--resume`` lose and double nothing
``kill:fleet-worker``     SIGKILL a fleet scoring worker at its scheduled
                          ``fleet_score`` fire point — after the lease claim,
                          before any result lands (mid-superblock)
``kill:fleet-coordinator``  SIGKILL the fleet *coordinator* at its scheduled
                          ``fleet_pump`` fire point (the pump-tick boundary,
                          after the previous tick's board checkpoint) — the
                          standby-takeover chaos tier: a ``--fleet-standby``
                          process must win the next leader generation and
                          answer every unanswered request exactly once
========================  ====================================================

Hang sites require an armed watchdog (``--deadline`` /
``SEQALIGN_DEADLINE_S``); firing one without it is a fatal chaos-spec
error (:class:`~.watchdog.HangWithoutDeadlineError`) — the alternative
is a run that blocks forever.  ``kind=`` is meaningless for hang/kill
sites and rejected.

Serve-plane sites (PR 9) are **marker** sites: they are consulted via
the non-raising :func:`scheduled` probe and the serve plane itself
shapes the failure — nothing raises at the probe point, so ``kind=`` is
rejected for them too:

==========================  ==================================================
``slow-client``             this ``Responder.send`` behaves like a client
                            whose socket buffer never drains — the record is
                            dropped and the responder marked dead (the
                            write-timeout armor's classification)
``dead-socket-midstream``   the client vanished between records: this send
                            finds the socket dead
``poison-session``          the session built from this request is poisoned —
                            every superblock containing it fails fatally
                            until quarantine bisection isolates it
``overload-burst``          this request arrives as part of a modelled burst
                            that exhausts the admission bucket on its own
                            (a typed ``overloaded`` rejection)
``burst:overload``          sustained open-loop overload: this request is
                            priced at 5x its modelled wall — the saturation
                            regime the load harness (``load/``) drives for
                            real, injectable here so the fleet-chaos tier can
                            hold 5x while murdering workers
==========================  ==================================================

Fleet marker sites (serve/fleet.py) shape worker-side failures the same
way — probed with :func:`scheduled`, the fleet machinery does the rest:

==========================  ==================================================
``zombie:fleet-worker``     after scoring, this worker freezes its heartbeat
                            until declared dead and its lease epoch fenced,
                            THEN posts the stale result — which must be
                            counted as fenced, never demuxed
``board:torn-post``         this result post lands half-written (a writer
                            dying mid-post on a non-atomic board); readers
                            must treat it as missing, so the lease expires
                            and the superblock re-dispatches
``lease:stall``             this worker claims the offer and never scores it
                            — the pure lease-expiry path, no death involved
``zombie:fleet-leader``     the *coordinator* freezes its leader beat at this
                            pump tick while continuing to serve — it must be
                            deposed by a standby and its late board posts
                            fenced by generation, never double-answered
``board:enospc``            this board post's tmp write fails mid-write
                            (disk full): the key must read as missing — never
                            as a torn post — and no ``.tmp.`` file may leak
==========================  ==================================================
"""

from __future__ import annotations

from dataclasses import dataclass

from ..obs.events import publish

# Serve-plane marker sites: consulted with scheduled(), never fire().
SERVE_SITES = frozenset(
    {
        "slow-client",
        "dead-socket-midstream",
        "poison-session",
        "overload-burst",
        # Colon-joined like the fleet sites: "burst" rides the grammar
        # re-partition in parse_spec.
        "burst:overload",
    }
)

# Fleet marker sites (serve/fleet.py): same scheduled() contract; the
# colon-joined names ride the same grammar re-partition as hang:/kill:.
FLEET_SITES = frozenset(
    {
        "zombie:fleet-worker",
        "zombie:fleet-leader",
        "board:torn-post",
        "board:enospc",
        "lease:stall",
    }
)

KNOWN_SITES = (
    frozenset(
        {
            "chunk_dispatch",
            "chunk_scoring",
            "device_transfer",
            "journal_append",
            "broadcast_problem",
            "broadcast_chunk",
            "broadcast_index_set",
            "broadcast_stream_meta",
            "hang:dispatch",
            "hang:gather",
            "hang:broadcast",
            "kill:journal-append",
            "kill:serve-tick",
            "kill:fleet-worker",
            "kill:fleet-coordinator",
        }
    )
    | SERVE_SITES
    | FLEET_SITES
)

# Survival-site aliases: which *fire point* each hang/kill site rides.
# The underlying site's fire() consults the alias schedule with the
# alias's OWN invocation counter, so "hang:broadcast:fail=1,after=2"
# means "the third broadcast of any kind hangs".
_HANG_SITES = {
    "chunk_dispatch": "hang:dispatch",
    "chunk_scoring": "hang:gather",
    "broadcast_problem": "hang:broadcast",
    "broadcast_chunk": "hang:broadcast",
    "broadcast_index_set": "hang:broadcast",
    "broadcast_stream_meta": "hang:broadcast",
}
_KILL_SITES = {
    "journal_append": "kill:journal-append",
    "serve_tick": "kill:serve-tick",
    "fleet_score": "kill:fleet-worker",
    "fleet_pump": "kill:fleet-coordinator",
}


class InjectedFaultError(RuntimeError):
    """A deterministic injected *transient* fault (retried by policy)."""


class InjectedFatalFaultError(ValueError):
    """A deterministic injected *fatal* fault (never retried — ValueError
    is the policy's fatal classification)."""


@dataclass(frozen=True)
class SiteFaults:
    """One site's schedule: invocations [after, after+fail) raise."""

    fail: int
    after: int = 0
    kind: str = "transient"


def parse_spec(spec: str) -> dict[str, SiteFaults]:
    """Parse the ``site:fail=N[,after=M][,kind=K]`` grammar; fail fast on
    unknown sites/keys so a typo'd chaos spec cannot silently test
    nothing."""
    sites: dict[str, SiteFaults] = {}
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        site, sep, body = entry.partition(":")
        site = site.strip()
        if site in ("hang", "kill", "zombie", "board", "lease", "burst"):
            # Survival/fleet sites carry a colon in the NAME
            # (hang:dispatch, zombie:fleet-worker): re-partition so the
            # first body segment joins the site.
            sub, sep2, rest = body.partition(":")
            site, sep, body = f"{site}:{sub.strip()}", sep2, rest
        if not sep or not body.strip():
            raise ValueError(
                f"bad --faults entry {entry!r}: want site:fail=N[,after=M]"
                "[,kind=transient|fatal]"
            )
        if site not in KNOWN_SITES:
            raise ValueError(
                f"bad --faults site {site!r}: known sites are "
                f"{', '.join(sorted(KNOWN_SITES))}"
            )
        kv = {}
        for part in body.split(","):
            key, sep, val = part.partition("=")
            key = key.strip()
            val = val.strip()
            if not sep or key not in ("fail", "after", "kind"):
                raise ValueError(
                    f"bad --faults key {part.strip()!r} for site {site!r}: "
                    "want fail=N, after=M, or kind=transient|fatal"
                )
            if key == "kind":
                if val not in ("transient", "fatal"):
                    raise ValueError(
                        f"bad --faults kind {val!r}: want transient or fatal"
                    )
                kv[key] = val
            else:
                try:
                    n = int(val)
                except ValueError:
                    raise ValueError(
                        f"bad --faults value {val!r} for {site}:{key}"
                    ) from None
                if n < 0:
                    raise ValueError(f"--faults {site}:{key} must be >= 0")
                kv[key] = n
        if "fail" not in kv:
            raise ValueError(f"--faults entry for {site!r} needs fail=N")
        if "kind" in kv and (
            site.partition(":")[0] in ("hang", "kill")
            or site in SERVE_SITES
            or site in FLEET_SITES
        ):
            raise ValueError(
                f"--faults site {site!r} does not take kind= (the failure "
                "shape is the site's own, not a raised error class)"
            )
        if site in sites:
            raise ValueError(f"duplicate --faults site {site!r}")
        sites[site] = SiteFaults(**kv)
    return sites


class FaultRegistry:
    """Per-run fault state: invocation counters + the parsed schedule."""

    def __init__(self, spec: str | dict[str, SiteFaults]):
        if isinstance(spec, str):
            self.sites = parse_spec(spec)
        else:
            unknown = sorted(set(spec) - KNOWN_SITES)
            if unknown:
                # Pre-built dict specs get the same unknown-site guard as
                # the string grammar — a typo'd site must not silently
                # test nothing.
                raise ValueError(
                    f"bad --faults site {unknown[0]!r}: known sites are "
                    f"{', '.join(sorted(KNOWN_SITES))}"
                )
            self.sites = dict(spec)
        self.counts: dict[str, int] = {}
        self.injected = 0

    def _scheduled(self, site: str) -> bool:
        """Bump ``site``'s invocation counter; True when this invocation
        falls inside its scheduled [after, after+fail) window."""
        n = self.counts.get(site, 0)
        self.counts[site] = n + 1
        sf = self.sites.get(site)
        return sf is not None and sf.after <= n < sf.after + sf.fail

    def scheduled(self, site: str) -> bool:
        """Marker-site probe: bump the counter and report (never raise)
        whether this invocation is scheduled — the serve plane shapes
        the failure itself (a deadened responder, a poisoned session, an
        inflated admission price)."""
        if self._scheduled(site):
            self.injected += 1
            publish("fault.injected", site=site, kind="marker")
            return True
        return False

    def fire(self, site: str) -> None:
        n = self.counts.get(site, 0)
        sf = self.sites.get(site)
        if self._scheduled(site):
            self.injected += 1
            publish("fault.injected", site=site, kind=sf.kind)
            cls = (
                InjectedFatalFaultError
                if sf.kind == "fatal"
                else InjectedFaultError
            )
            raise cls(
                f"injected {sf.kind} fault at site {site!r} (invocation {n})"
            )
        # Survival-site aliases ride this fire point with their OWN
        # counters (counted only while armed, so schedules stay exact).
        hang = _HANG_SITES.get(site)
        if hang is not None and hang in self.sites and self._scheduled(hang):
            self.injected += 1
            publish("fault.injected", site=hang, kind="hang")
            from . import watchdog

            # Blocks until the armed watchdog's deadline, then raises the
            # transient DeadlineExpiredError (fatal if no watchdog armed).
            watchdog.hang_until_deadline(hang)
        kill = _KILL_SITES.get(site)
        if kill is not None and kill in self.sites and self._scheduled(kill):
            self.injected += 1
            publish("fault.injected", site=kill, kind="kill")
            import os
            import signal

            # A deterministic preemption: SIGKILL is uncatchable, exactly
            # like the scheduler's escalation.  Flushed journal chunks
            # are already fsync'd; the in-flight chunk is lost by design.
            os.kill(os.getpid(), signal.SIGKILL)


# The armed registry.  Module-global, single-threaded by construction:
# the instrumented sites all run on the driver thread.
_active: FaultRegistry | None = None


def activate_faults(spec) -> FaultRegistry | None:
    """Arm a fresh registry for one run (counters reset); ``spec`` may be
    None/empty (no-op — fire() stays a cheap check).  Returns the
    registry so callers can inspect ``injected`` afterwards."""
    global _active
    _active = FaultRegistry(spec) if spec else None
    return _active


def deactivate_faults() -> None:
    global _active
    _active = None


def fire(site: str) -> None:
    """Instrumentation hook: raises per the armed schedule, else no-op."""
    if _active is not None:
        _active.fire(site)


def scheduled(site: str) -> bool:
    """Non-raising marker probe (serve chaos sites): True when the armed
    schedule marks this invocation; a single attribute check when no
    registry is armed."""
    return _active is not None and _active.scheduled(site)
