"""Graceful preemption drain: SIGTERM/SIGINT -> flush -> resumable exit.

The reference dies mid-batch on any signal, losing every scored result
(fail-stop, `main.c` has no handlers).  On preemptible fleets SIGTERM is
not an error — it is a *deadline*: the scheduler will follow with
SIGKILL shortly, and the only useful response is to stop starting new
work, flush what finished, and exit with a code the orchestrator can
distinguish from failure.

Mechanics:

* :class:`drain_guard` installs SIGTERM/SIGINT handlers for the span of
  one CLI run (main thread only; previous handlers are restored on
  exit, so in-process callers — the test suite — never leak handlers).
* The first signal sets a module flag; :func:`drain_requested` is
  checked at every **chunk boundary** (the batch journal loop in
  ``utils/journal.py`` and the ``--stream`` submit loop in
  ``io/cli.py``) — never mid-collective, so multi-host schedules cannot
  desynchronise.  ``SEQALIGN_DRAIN=1`` pre-arms the flag (deterministic
  testing of the drain path without signals).
* The boundary raises :class:`DrainInterrupt` after in-flight results
  are flushed + fsync'd and a resumable-exit record is appended to the
  journal; the CLI maps it to exit code 75 (``EX_TEMPFAIL``:
  "temporary; rerun with ``--resume``") versus 65 for fatal errors.
* A **second** signal during the drain force-exits immediately
  (``os._exit(128 + signum)``): the operator escalated, obey.
* The rerun side: a drained process leaves both a resumable journal AND
  a populated persistent compile cache + AOT manifest behind, so
  ``--resume --prewarm`` rejoins **warm** — the restarted process
  replays its predecessor's executables (``aot/prewarm``) instead of
  re-paying the multi-second first-compile tax on top of the preemption
  it just survived.

:class:`DrainInterrupt` derives from ``BaseException`` deliberately —
the retry policy's transient net (``except Exception``) must not catch
and retry a preemption, and the degradation chain must not "absorb" it.
"""

from __future__ import annotations

import os
import signal
import threading

from ..obs.events import log_line, publish


class DrainInterrupt(BaseException):
    """A drain request reached a chunk boundary: stop cleanly, exit 75.

    BaseException (like KeyboardInterrupt): preemption must sail through
    the retry/degrade machinery untouched.
    """


# One flag per process, like the fault registry: the CLI owns the run.
_requested = False
_signals = 0


def drain_requested() -> bool:
    """The chunk-boundary check (no clock, no syscall: one global read —
    the decision input is an external signal, never time)."""
    return _requested


def request_drain(why: str, log=None) -> None:
    """Set the drain flag (idempotent); logged once on the transition."""
    global _requested
    if not _requested:
        _requested = True
        publish("drain.request", why=why)
        (log or log_line)(
            f"mpi_openmp_cuda_tpu: drain requested ({why}); finishing "
            "in-flight chunks, flushing the journal, then exiting 75 "
            "(resumable) — a second signal force-exits"
        )


class drain_guard:
    """Context manager installing the drain handlers for one run.

    ``prearm=None`` reads ``SEQALIGN_DRAIN`` (typed env registry): a
    pre-armed run drains at its first chunk boundary, which makes the
    whole drain -> flush -> 75 -> ``--resume`` path an ordinary
    deterministic test.  Handlers install only on the main thread
    (CPython restriction) and the previous handlers are restored on
    exit; the flag is reset on both entry and exit so consecutive
    in-process runs never inherit a stale drain.
    """

    def __init__(self, *, prearm: bool | None = None, log=None):
        self._prearm = prearm
        self._log = log or log_line
        self._saved: list[tuple[int, object]] = []

    def __enter__(self):
        global _requested, _signals
        prearm = self._prearm
        if prearm is None:
            from ..utils.platform import env_flag

            prearm = env_flag("SEQALIGN_DRAIN")
        _requested = bool(prearm)
        _signals = 0
        if threading.current_thread() is threading.main_thread():
            for sig in (signal.SIGTERM, signal.SIGINT):
                try:
                    self._saved.append((sig, signal.signal(sig, self._on_signal)))
                except (ValueError, OSError):  # pragma: no cover - exotic hosts
                    continue
        return self

    def __exit__(self, *exc):
        global _requested, _signals
        saved, self._saved = self._saved, []
        for sig, old in saved:
            try:
                signal.signal(sig, old)
            except (ValueError, OSError):  # pragma: no cover
                continue
        _requested = False
        _signals = 0
        return False

    def _on_signal(self, signum, frame) -> None:
        global _signals
        _signals += 1
        if _signals > 1:
            # Second signal during the drain: the operator (or the
            # scheduler's escalation) means NOW.  os._exit skips every
            # finally/atexit — flushed journal chunks are already
            # fsync'd, so nothing durable is lost.
            os._exit(128 + signum)
        try:
            name = signal.Signals(signum).name
        except ValueError:  # pragma: no cover
            name = f"signal {signum}"
        request_drain(name, self._log)
