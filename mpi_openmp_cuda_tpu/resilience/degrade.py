"""Backend degradation chain: survive a persistently-broken backend.

Retry absorbs *transient* faults; a backend that fails the same chunk
past its whole budget is effectively broken (a wedged kernel runtime, a
poisoned compile cache, a sick device).  Under ``--degrade`` that no
longer kills the run: the driver falls down the backend chain

    pallas -> xla -> xla-gather

rescoring the failed chunk (and serving every later chunk) on the next
backend, with a logged warning.  The first successfully degraded chunk
is re-verified against the host oracle (``ops/oracle.py``) before its
rows are trusted — a backend that *silently corrupts* instead of
failing must not be degraded onto; a mismatch raises
:class:`DegradedBackendMismatchError` (a ValueError: fatal, never
retried).

Degradation is a **single-process** feature: under ``--distributed``
the backend choice IS the SPMD program, and a lone host degrading would
desynchronise the collective schedules (a hang, not an error) — the CLI
statically rejects ``--degrade --distributed``, the same stance as
``resolve_auto_backend``'s multi-host pallas-import failure.
"""

from __future__ import annotations

from ..obs.events import log_line, publish
from .policy import RetryExhaustedError, RetryPolicy

# The fallback order.  'xla' is the MXU matmul formulation (with its own
# exactness fallback); 'xla-gather' forces the always-exact int32 gather
# formulation — the most conservative accelerated path, so the chain
# ends there (the host oracle stays a *verifier*, not a serving tier).
DEGRADE_CHAIN = {"pallas": "xla", "xla": "xla-gather"}

# Sequences of the first degraded chunk re-verified against the oracle
# (a sample bounds the host-side cost on huge chunks).
VERIFY_CAP = 32


class DegradedBackendMismatchError(ValueError):
    """A degraded backend disagreed with the host oracle (fatal)."""


class MaterialisedRows:
    """Pending-compatible wrapper for rows a degraded backend already
    scored synchronously (so the streaming pipeline's promise contract
    survives a dispatch-stage degradation)."""

    def __init__(self, rows):
        self._rows = rows

    def prefetch(self) -> None:
        pass

    def result(self):
        return self._rows


class BackendDegrader:
    """Chain state for one run: the live scorer + how far it has fallen.

    ``make_scorer(backend)`` builds the replacement scorer (same
    sharding/chunk budget as the original); ``enabled=False`` turns the
    whole object into a pass-through so call sites stay uniform.
    """

    def __init__(self, scorer, make_scorer, *, enabled: bool = False, log=None):
        self.scorer = scorer
        self._make = make_scorer
        self.enabled = enabled
        self.verified = False  # first degraded chunk oracle-checked yet?
        self._log = log or log_line
        self._original = scorer
        self._built: dict[str, object] = {}  # degraded scorers, by backend

    def step(self) -> str | None:
        """Fall one link down the chain; returns the new backend name, or
        None when the chain is exhausted (caller re-raises)."""
        nxt = DEGRADE_CHAIN.get(self.scorer.backend)
        if nxt is None:
            return None
        publish("degrade.transition", frm=self.scorer.backend, to=nxt)
        self._log(
            f"mpi_openmp_cuda_tpu: warning: backend {self.scorer.backend!r} "
            f"exhausted its retry budget; degrading to {nxt!r} (the first "
            "degraded chunk is re-verified against the host oracle)"
        )
        self.scorer = self._scorer_for(nxt)
        return nxt

    def can_degrade(self) -> bool:
        """True when the chain has somewhere to fall from the ORIGINAL
        backend (the circuit breaker's precondition for opening)."""
        return DEGRADE_CHAIN.get(self._original.backend) is not None

    def pin(self) -> str | None:
        """Circuit-breaker open: ensure the live scorer is a degraded
        backend and return its name.  Already-degraded chains stay where
        they fell; from the primary this is one :meth:`step` down."""
        if self.scorer.backend != self._original.backend:
            return self.scorer.backend
        return self.step()

    def reset(self) -> None:
        """Circuit-breaker half-open probe: restore the primary scorer.
        The ``verified`` flag deliberately survives — oracle
        re-verification is once per run, not once per pin cycle, and the
        degraded scorers stay cached in ``_built`` with their jit caches
        warm for the next open."""
        self.scorer = self._original

    def _scorer_for(self, backend: str):
        scorer = self._built.get(backend)
        if scorer is None:
            scorer = self._built[backend] = self._make(backend)
        return scorer


def verify_rows_against_oracle(seq1_codes, seq2_codes, weights, rows) -> None:
    """Compare up to :data:`VERIFY_CAP` rows against ``ops/oracle.py``;
    raise :class:`DegradedBackendMismatchError` on any divergence."""
    from ..ops.oracle import score_batch_oracle

    k = min(len(seq2_codes), VERIFY_CAP)
    if k == 0:
        return
    want = score_batch_oracle(seq1_codes, list(seq2_codes)[:k], weights)
    got = [tuple(int(x) for x in row) for row in list(rows)[:k]]
    if got != [tuple(int(x) for x in w) for w in want]:
        raise DegradedBackendMismatchError(
            "degraded backend disagrees with the host oracle on the first "
            f"{k} sequences of the degraded chunk; refusing to continue"
        )


def run_degrading(
    policy: RetryPolicy,
    degrader: BackendDegrader | None,
    attempt,
    rescore,
    describe: str,
    *,
    budget=None,
    verify=None,
    wrap=None,
):
    """``policy.run(attempt)``, falling down the degradation chain on
    transient budget exhaustion.

    ``rescore(scorer)`` rescores the same work on a (degraded) scorer
    under a FRESH budget per chain link.  ``verify(rows)`` runs once on
    the first degraded result (oracle re-verification); ``wrap(rows)``
    adapts a degraded synchronous result to the caller's return contract
    (the streaming dispatch stage wraps rows in
    :class:`MaterialisedRows`).  With ``degrader`` disabled/None this is
    exactly ``policy.run``.
    """
    try:
        return policy.run(attempt, describe, budget=budget)
    except RetryExhaustedError as exhausted:
        if degrader is None or not degrader.enabled:
            raise
        last = exhausted
        while True:
            backend = degrader.step()
            if backend is None:
                raise last
            try:
                rows = policy.run(
                    lambda: rescore(degrader.scorer),
                    f"{describe} [degraded:{backend}]",
                    budget=policy.new_budget(),
                )
            except RetryExhaustedError as e:
                last = e
                continue
            if verify is not None and not degrader.verified:
                verify(rows)
                degrader.verified = True
            return wrap(rows) if wrap is not None else rows
