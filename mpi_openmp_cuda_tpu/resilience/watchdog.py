"""Wall-clock deadlines around device work and coordinator collectives.

The reference's failure model assumes an operation either returns or
raises (`cudaFunctions.cu:15-33`).  On real fleets there is a third
outcome: it never comes back — a wedged device runtime blocking in
``block_until_ready``, a collective whose peer was preempted.  The PR 1
retry/degrade machinery only sees *raised* errors, so a hang starves it.

This module closes that gap with a single monitor thread per run
(``--deadline`` / ``SEQALIGN_DEADLINE_S``):

* Each blocking boundary — result materialisation (the
  ``block_until_ready`` analogue in ``ops/dispatch.py`` /
  ``parallel/sharding.py``) and each coordinator broadcast in
  ``parallel/distributed.py`` — arms a :meth:`Watchdog.guard` before
  entering and disarms on exit.
* The monitor waits on a ``threading.Condition`` with a timeout — note
  no wall-clock *reads* anywhere: like ``time.sleep``, a condition
  timeout delays, it does not decide, so the deterministic-path lint
  (seqlint SEQ005) holds structurally and all timing stays at this one
  monitoring boundary.
* Expiry is classified **transient**: :class:`DeadlineExpiredError` is a
  ``RuntimeError``, so the existing :class:`~.policy.RetryPolicy`
  retries it and the :class:`~.degrade.BackendDegrader` chain absorbs a
  persistently-hanging backend, exactly like a raised fault.

Honesty note: Python cannot unwind a C call that genuinely never
returns.  For *injected* hangs (the ``hang:*`` fault sites in
:mod:`.faults`) the hang itself waits on the armed guard's expiry event
and then raises, which makes the whole deadline -> retry -> degrade
path deterministically chaos-testable; for a *real* hang the monitor
logs a loud warning naming the stuck operation so an orchestrator (or
the drain handler, :mod:`.drain`) can act on it.
"""

from __future__ import annotations

import contextlib
import threading

from ..obs.events import log_line, publish

#: The monitor thread's name: tests assert no thread with this name
#: survives a clean CLI exit (the joined-on-stop contract).
THREAD_NAME = "seqalign-watchdog"


class DeadlineExpiredError(RuntimeError):
    """A guarded operation outlived the watchdog deadline.  RuntimeError
    == transient: the retry policy absorbs it and the degradation chain
    sits behind that, the same path as any raised device fault."""


class HangWithoutDeadlineError(ValueError):
    """A ``hang:*`` fault site fired with no watchdog armed.  ValueError
    == fatal (never retried): a chaos spec that injects hangs without
    ``--deadline`` would hang the run forever, which is a configuration
    error, not a fault to absorb."""


class _Arm:
    """One armed guard: the operation description plus the event the
    monitor sets at expiry (injected hangs block on it)."""

    __slots__ = ("describe", "expired")

    def __init__(self, describe: str):
        self.describe = describe
        self.expired = threading.Event()


class Watchdog:
    """One monitor thread watching one armed operation at a time.

    The instrumented boundaries are all on the driver thread (the same
    single-threaded-by-construction argument as the fault registry), so
    a single arm slot suffices; nested guards no-op under the outer
    deadline.  ``stop()`` joins the thread — a run must not leave a
    dangling monitor behind (asserted by the test suite).
    """

    def __init__(
        self,
        deadline_s: float | None,
        *,
        log=None,
        heartbeat_s: float | None = None,
        heartbeat=None,
    ):
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(
                f"watchdog deadline must be > 0 seconds, got {deadline_s}"
            )
        if deadline_s is None and heartbeat_s is None:
            raise ValueError(
                "watchdog needs a deadline, a heartbeat interval, or both"
            )
        if heartbeat_s is not None and heartbeat_s <= 0:
            raise ValueError(
                f"heartbeat interval must be > 0 seconds, got {heartbeat_s}"
            )
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        self.heartbeat_s = None if heartbeat_s is None else float(heartbeat_s)
        self._heartbeat = heartbeat
        self.expiries = 0
        self._log = log or log_line
        self._cond = threading.Condition()
        self._arm: _Arm | None = None
        self._stopped = False
        self._thread: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is None:
            self._stopped = False
            self._thread = threading.Thread(
                target=self._monitor, name=THREAD_NAME, daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        """Stop and JOIN the monitor (idempotent)."""
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join()

    def _beat(self) -> None:
        """Emit one heartbeat line (the obs plane's periodic status).
        No clock reads: cadence comes from condition-wait timeouts, the
        same delay-not-decide stance as the deadline itself."""
        if self._heartbeat is not None:
            self._heartbeat()

    def _monitor(self) -> None:
        # The bus fan-out (publish / log_line / the heartbeat callback)
        # happens OUTSIDE the condition: every subscriber sits behind its
        # own lock and the flight recorder's trigger events do file I/O,
        # so emitting under `_cond` would stall every `guard()`/`stop()`
        # caller behind a disk write (analysis/lockgraph.py rule b —
        # obs recorder locks must never nest under a subsystem lock).
        hb = self.heartbeat_s
        while True:
            beat = False
            expired = None
            with self._cond:
                if self._stopped:
                    return
                if self._arm is None or self.deadline_s is None:
                    # Idle (or heartbeat-only mode, where armed guards
                    # carry no deadline): sleep a heartbeat interval —
                    # forever when none is configured — and emit the
                    # status line on each quiet timeout.
                    notified = self._cond.wait(timeout=hb)
                    beat = not notified and not self._stopped
                else:
                    cur = self._arm
                    disarmed = self._cond.wait_for(
                        lambda: self._stopped or self._arm is not cur,
                        timeout=self.deadline_s,
                    )
                    if not disarmed:
                        # Deadline hit while cur is still armed: signal
                        # expiry (an injected hang blocked on
                        # cur.expired now raises a transient
                        # DeadlineExpiredError into the retry policy).
                        self.expiries += 1
                        cur.expired.set()
                        expired = cur
            if beat:
                self._beat()
            if expired is not None:
                publish("watchdog.expiry", site=expired.describe)
                self._log(
                    f"mpi_openmp_cuda_tpu: warning: {expired.describe} "
                    f"exceeded the {self.deadline_s:g}s watchdog deadline; "
                    "if it never returns the process must be preempted "
                    "externally (SIGTERM drains with journalled progress; "
                    "see --resume)"
                )
                with self._cond:
                    self._cond.wait_for(
                        lambda: self._stopped or self._arm is not expired
                    )

    # -- arming ------------------------------------------------------------
    @contextlib.contextmanager
    def guard(self, describe: str):
        """Arm the monitor around one blocking operation.  Nested guards
        are no-ops: the outermost deadline already covers them."""
        with self._cond:
            nested = self._arm is not None
            if not nested:
                token = _Arm(describe)
                self._arm = token
                self._cond.notify_all()
        if not nested:
            publish("watchdog.guard", state="armed", site=describe)
        try:
            yield
        finally:
            if not nested:
                with self._cond:
                    self._arm = None
                    self._cond.notify_all()
                publish("watchdog.guard", state="disarmed", site=describe)

    def hang_until_expiry(self, site: str) -> None:
        """The injected-hang behaviour (``hang:*`` fault sites): block on
        the armed guard's expiry event, then surface the hang as the
        transient :class:`DeadlineExpiredError` the retry policy absorbs.
        With no guard armed the hang would block forever — fail fast."""
        with self._cond:
            token = self._arm
        if token is None or self.deadline_s is None:
            raise HangWithoutDeadlineError(
                f"injected hang at {site!r} outside any deadline-armed "
                "watchdog guard; refusing to block forever (this is a "
                "chaos-spec bug — a heartbeat-only watchdog enforces no "
                "deadline)"
            )
        token.expired.wait()
        raise DeadlineExpiredError(
            f"injected hang at {site!r}: {token.describe} exceeded the "
            f"{self.deadline_s:g}s watchdog deadline"
        )


# The armed watchdog.  Module-global like the fault registry: armed per
# run by the CLI, cleared in its finally, so library callers never see
# an ambient deadline.
_active: Watchdog | None = None


def activate_watchdog(
    deadline_s: float | None,
    *,
    log=None,
    heartbeat_s: float | None = None,
    heartbeat=None,
) -> Watchdog:
    """Arm (and start) a fresh watchdog for one run; returns it so the
    caller can inspect ``expiries`` afterwards.  ``deadline_s=None``
    with a heartbeat runs the monitor in heartbeat-only mode (status
    lines, no deadline enforcement)."""
    global _active
    deactivate_watchdog()
    _active = Watchdog(
        deadline_s, log=log, heartbeat_s=heartbeat_s, heartbeat=heartbeat
    )
    _active.start()
    return _active


def deactivate_watchdog() -> None:
    """Stop + join the run's watchdog (no-op when none armed)."""
    global _active
    wd, _active = _active, None
    if wd is not None:
        wd.stop()


def active_watchdog() -> Watchdog | None:
    return _active


def guard(describe: str):
    """Instrumentation hook for the blocking boundaries: a context
    manager arming the run's watchdog, or a no-op when none is armed."""
    wd = _active
    if wd is None:
        return contextlib.nullcontext()
    return wd.guard(describe)


def hang_until_deadline(site: str) -> None:
    """Entry point for the ``hang:*`` fault sites (see :mod:`.faults`)."""
    wd = _active
    if wd is None:
        raise HangWithoutDeadlineError(
            f"injected hang at {site!r} with no watchdog armed; hang "
            "faults need --deadline (or SEQALIGN_DEADLINE_S) so the run "
            "can classify the hang instead of blocking forever"
        )
    wd.hang_until_expiry(site)
