"""Unified retry/backoff policy (SURVEY §5: absorb transient faults).

One :class:`RetryPolicy` instance per run owns the three decisions the
old per-call-site helpers (``io/cli.py`` ``_retrying`` /
``_materialise_retrying``) each re-derived:

* **classification** — (ValueError, TypeError) are shape/programming
  errors and always propagate; anything else is transient and retried.
  :data:`FATAL_ERROR_TYPES` is the single source.
* **attempt budget** — ``retries`` extra attempts per budget.  A budget
  is a mutable one-element list so several stages can SHARE one: the
  streaming pipeline passes the same counter to a chunk's dispatch and
  materialise stages, so the chunk gets N retries total, matching the
  batch path's N+1-attempt contract.
* **backoff** — exponential with deterministic seeded jitter.  The
  jitter derives from ``(seed, describe, attempt)`` only — never from
  time, pid, or host identity — so under ``--distributed`` every host
  computes the IDENTICAL sleep sequence for a job-wide transient
  failure and re-enters the sharded collectives in lockstep (the
  cross-host contract documented at the CLI's ``--retries`` help; a
  per-host random jitter would skew the schedules toward the
  coordination-timeout teardown it exists to avoid).

Budget exhaustion on a transient error raises
:class:`RetryExhaustedError` chaining the last cause — the typed signal
the degradation chain (:mod:`.degrade`) keys on.
"""

from __future__ import annotations

import random
import time

from ..obs.events import log_line, publish

# The single source of the transient-vs-fatal classification (previously
# a docstring contract in io/cli.py:_retrying).
FATAL_ERROR_TYPES = (ValueError, TypeError)

# Backoff defaults: first retry waits ~BASE seconds, doubling per attempt
# up to CAP.  SEQALIGN_BACKOFF_BASE overrides (0 disables sleeping —
# the chaos suite uses a near-zero base to keep injected-fault runs fast).
_DEFAULT_BACKOFF_BASE = 0.05
_DEFAULT_BACKOFF_FACTOR = 2.0
_DEFAULT_BACKOFF_CAP = 2.0


class RetryExhaustedError(RuntimeError):
    """A transient failure outlived its retry budget (the policy's
    exhaustion error: nonzero exit unless a degradation chain absorbs
    it).  ``__cause__`` carries the last underlying error."""


class RetryPolicy:
    """Attempt budget + backoff + classification for one run.

    ``sleep`` / ``log`` are injectable for tests; ``seed`` feeds the
    deterministic jitter (same seed + site + attempt => same delay on
    every host).
    """

    def __init__(
        self,
        retries: int = 0,
        *,
        backoff_base: float | None = None,
        backoff_factor: float = _DEFAULT_BACKOFF_FACTOR,
        backoff_cap: float = _DEFAULT_BACKOFF_CAP,
        seed: int = 0,
        sleep=time.sleep,
        log=None,
    ):
        if retries < 0:
            raise ValueError(f"retries must be >= 0, got {retries}")
        self.retries = int(retries)
        if backoff_base is None:
            from ..utils.platform import env_float

            backoff_base = env_float(
                "SEQALIGN_BACKOFF_BASE", _DEFAULT_BACKOFF_BASE
            )
        self.backoff_base = float(backoff_base)
        self.backoff_factor = float(backoff_factor)
        self.backoff_cap = float(backoff_cap)
        self.seed = int(seed)
        self._sleep = sleep
        self._log = log or log_line

    # -- pieces ------------------------------------------------------------
    @staticmethod
    def is_fatal(exc: BaseException) -> bool:
        return isinstance(exc, FATAL_ERROR_TYPES)

    def new_budget(self) -> list[int]:
        """A fresh shared attempt counter (see module docstring)."""
        return [0]

    def backoff_delay(self, attempt: int, describe: str) -> float:
        """Deterministic delay before retry ``attempt`` (1-based) at site
        ``describe``: exponential, capped, jittered in [0.5x, 1.5x) by a
        PRNG seeded from (seed, describe, attempt) alone — identical on
        every host of a lockstep SPMD job."""
        if self.backoff_base <= 0:
            return 0.0
        raw = min(
            self.backoff_cap,
            self.backoff_base * self.backoff_factor ** (attempt - 1),
        )
        jitter = 0.5 + random.Random(
            f"{self.seed}:{describe}:{attempt}"
        ).random()
        return raw * jitter

    # -- the retry loop ----------------------------------------------------
    def run(self, fn, describe: str, budget: list[int] | None = None):
        """Run ``fn()`` absorbing up to ``retries`` transient failures.

        ``budget`` shares one attempt counter across several ``run``
        calls (stream mode: one chunk's dispatch + materialise).  Fatal
        errors (:data:`FATAL_ERROR_TYPES`) always propagate untouched;
        a transient error past the budget raises
        :class:`RetryExhaustedError` chaining it.
        """
        used = self.new_budget() if budget is None else budget
        while True:
            try:
                return fn()
            except FATAL_ERROR_TYPES:
                raise
            except Exception as e:
                used[0] += 1
                # Every caught transient failure is one retry attempt —
                # including the one that exhausts the budget, so a
                # fail=N fault spec reports retry_attempts == N exactly.
                publish("retry.attempt", site=describe, attempt=used[0])
                if used[0] > self.retries:
                    raise RetryExhaustedError(
                        f"{describe}: retry budget exhausted after "
                        f"{used[0]} attempts ({e})"
                    ) from e
                delay = self.backoff_delay(used[0], describe)
                suffix = f" in {delay:.2f}s" if delay > 0 else ""
                self._log(
                    f"mpi_openmp_cuda_tpu: {describe} attempt {used[0]} "
                    f"failed ({e}); retrying{suffix}"
                )
                if delay > 0:
                    publish("retry.backoff", site=describe, delay=delay)
                    self._sleep(delay)

    def materialise(self, promise, rescore, describe: str, budget):
        """Materialise an async dispatch under the shared budget.

        The first attempt forces ``promise``; every retry calls
        ``rescore()`` (a synchronous rescore of the same chunk).  The
        coordinator's chunk finish and the worker stream loop BOTH go
        through this method, so a job-wide transient failure sees every
        host take the same attempt sequence and re-enter the same
        sharded collectives in lockstep — two diverging copies of this
        pattern would turn such a failure into a coordination-timeout
        teardown (ADVICE r3).
        """
        first = [promise]

        def attempt():
            if first:
                return first.pop().result()
            return rescore()

        return self.run(attempt, describe, budget=budget)
