"""Circuit breaker over the backend degrade chain (serve-plane SLO armor).

The PR-1 degrade chain reacts per request: every exhausted retry budget
walks pallas→xla→xla-gather and re-verifies the degraded backend against
the oracle before trusting it.  That is the right shape for a one-shot
batch run, but a persistent server facing a *systemic* primary-backend
failure (driver wedge, bad build, device loss) would pay the full
retry-then-degrade-then-verify cost on every superblock forever.  The
breaker watches the dispatch path's transient failures and, after
``threshold`` of them inside a ``window_ticks`` window, OPENS: the
degraded backend is pinned fleet-wide via
:meth:`~..resilience.degrade.BackendDegrader.pin` and dispatch bypasses
the primary entirely (and, because the degrader's ``verified`` flag is
sticky, oracle re-verification is not repeated per request).  After
``cooldown_ticks`` the breaker goes HALF-OPEN and lets exactly one
probe through on the restored primary: success closes the breaker,
failure re-opens it for another cooldown.

Determinism contract (seqlint SEQ005, role ``deterministic``): windows
and cooldowns count serve-loop *ticks*, never wall clock.  The serve
loop calls :meth:`CircuitBreaker.tick` once per iteration; given the
same failure sequence at the same ticks, the breaker transitions
identically on every run — which is what makes the serve chaos tier's
open→half-open→close cycle reproducible.

State machine::

    closed --(threshold transient failures in window)--> open
    open   --(cooldown_ticks elapsed)-----------------> half_open
    half_open --(probe succeeds)----------------------> closed
    half_open --(probe fails)-------------------------> open

Every transition publishes a ``breaker.open`` / ``breaker.half_open`` /
``breaker.close`` bus event (obs/metrics.py folds them into the
``breaker_*`` counters and the ``breaker_state`` gauge).
"""

from __future__ import annotations

import collections

from ..obs.events import log_line, publish

STATE_CLOSED = "closed"
STATE_OPEN = "open"
STATE_HALF_OPEN = "half_open"

#: Transient dispatch failures inside the window that open the breaker.
DEFAULT_THRESHOLD = 3
#: Failure-memory horizon, in serve-loop ticks.
DEFAULT_WINDOW_TICKS = 16
#: Ticks an open breaker waits before probing half-open.
DEFAULT_COOLDOWN_TICKS = 8


class CircuitBreaker:
    """Tick-counted breaker pinning the degrade chain while open.

    Owned and ticked by the serve loop's main thread only — no locking,
    by design: ``record_failure``/``record_success`` are invoked from
    the dispatch path, which runs on the same thread as ``tick``.
    """

    def __init__(
        self,
        degrader,
        *,
        threshold: int = DEFAULT_THRESHOLD,
        window_ticks: int = DEFAULT_WINDOW_TICKS,
        cooldown_ticks: int = DEFAULT_COOLDOWN_TICKS,
        log=log_line,
    ):
        if threshold < 1:
            raise ValueError(f"breaker threshold must be >= 1, got {threshold}")
        if window_ticks < 1:
            raise ValueError(
                f"breaker window must be >= 1 tick, got {window_ticks}"
            )
        if cooldown_ticks < 1:
            raise ValueError(
                f"breaker cooldown must be >= 1 tick, got {cooldown_ticks}"
            )
        self.degrader = degrader
        self.threshold = int(threshold)
        self.window_ticks = int(window_ticks)
        self.cooldown_ticks = int(cooldown_ticks)
        self.state = STATE_CLOSED
        self.opens = 0
        self._log = log
        self._ticks = 0
        self._opened_at = 0
        self._failures: collections.deque[int] = collections.deque()

    def tick(self) -> None:
        """One serve-loop iteration: age the failure window; an open
        breaker whose cooldown has elapsed moves to half-open and
        restores the primary backend for the probe dispatch."""
        self._ticks += 1
        self._trim()
        if (
            self.state == STATE_OPEN
            and self._ticks - self._opened_at >= self.cooldown_ticks
        ):
            self._half_open()

    def bypass_primary(self) -> bool:
        """True while open: dispatch goes straight to the pinned
        degraded backend, skipping the primary attempt + retry ladder
        (and the per-request oracle re-verification with it)."""
        return self.state == STATE_OPEN

    def record_failure(self) -> None:
        """A transient (retryable) failure on the primary dispatch
        path.  Fatal errors never reach here — they are not a backend
        health signal (io/pipeline.py filters on FATAL_ERROR_TYPES)."""
        if self.state == STATE_OPEN:
            return
        if self.state == STATE_HALF_OPEN:
            self._open(reason="probe-failed")
            return
        if not (self.degrader.enabled and self.degrader.can_degrade()):
            # Nothing to pin: without --degrade (or with the chain
            # exhausted) an open breaker could only bypass onto the
            # same failing backend.
            return
        self._failures.append(self._ticks)
        self._trim()
        if len(self._failures) >= self.threshold:
            self._open(reason="threshold")

    def record_success(self) -> None:
        """A primary dispatch completed: a half-open probe that
        succeeds closes the breaker (closed-state successes are not
        state transitions — the window forgets on its own)."""
        if self.state == STATE_HALF_OPEN:
            self._close()

    def _trim(self) -> None:
        horizon = self._ticks - self.window_ticks
        while self._failures and self._failures[0] < horizon:
            self._failures.popleft()

    def _open(self, reason: str) -> None:
        pinned = self.degrader.pin() or self.degrader.scorer.backend
        self.state = STATE_OPEN
        self.opens += 1
        self._opened_at = self._ticks
        self._failures.clear()
        publish("breaker.open", backend=pinned, reason=reason, tick=self._ticks)
        self._log(
            f"mpi_openmp_cuda_tpu: breaker OPEN ({reason}): backend "
            f"{pinned!r} pinned fleet-wide; probing primary in "
            f"{self.cooldown_ticks} tick(s)"
        )

    def _half_open(self) -> None:
        self.state = STATE_HALF_OPEN
        self.degrader.reset()
        publish(
            "breaker.half_open",
            backend=self.degrader.scorer.backend,
            tick=self._ticks,
        )
        self._log(
            "mpi_openmp_cuda_tpu: breaker HALF-OPEN: probing primary "
            f"backend {self.degrader.scorer.backend!r}"
        )

    def _close(self) -> None:
        self.state = STATE_CLOSED
        self._failures.clear()
        publish(
            "breaker.close",
            backend=self.degrader.scorer.backend,
            tick=self._ticks,
        )
        self._log(
            "mpi_openmp_cuda_tpu: breaker CLOSED: primary backend healthy"
        )
