"""Fleet membership: worker registry, heartbeats, epoch-fenced leases.

ROADMAP item 2 names the PR-4 rescue board as the membership layer for
an *elastic* serve fleet — workers joining and leaving mid-serve.  This
module is that layer: the pure bookkeeping the fleet coordinator
(serve/fleet.py) drives once per board poll.  It owns three things:

* the **board key schema** under ``seqalign/fleet/`` — registrations,
  heartbeats, superblock offers, lease claims, epoch-stamped results;
* :class:`Membership` — who is alive, decided from heartbeat *change*
  under a tick-counted deadline;
* :class:`LeaseTable` — which worker owns which offered superblock, at
  which fencing epoch, and when a lease has expired.

Two invariants, both inherited from the PR-4 board pattern:

* **Torn posts read as missing, never as data.**  Every structured
  record crossing the board goes through :func:`board_read_json`: a
  post that is absent, zero-length, unparsable (a writer killed
  mid-write on a non-atomic board, or the chaos tier's deliberately
  torn ``board:torn-post``), or not a JSON object is indistinguishable
  from no post at all.  The lease deadline then re-dispatches the work
  — a torn result can delay an answer, never corrupt one.
* **Decisions are tick-counted, never wall-clock (SEQ005).**  The
  caller hands ``observe``/``expired`` its own monotonically increasing
  poll-tick number.  A worker is dead when its heartbeat value has not
  *changed* for ``deadline_ticks`` observed ticks; a lease is expired
  ``lease_ticks`` after issue or claim.  Wall time only paces the
  caller's polls, through the injectable serve clock, where tests
  substitute a fake.

**Epoch fencing** is how a zombie — a worker declared dead whose
process is still running — is kept from double-answering a request:
every re-dispatch bumps the lease epoch, claim and result keys embed
the epoch, and :meth:`LeaseTable.admits` is the one acceptance
predicate.  A result posted under any previous epoch lands on the
board, is counted (``lease.fenced``), and is never demuxed.  Death is
terminal: a worker whose heartbeat resumes after the verdict stays
dead — its leases were already re-dispatched — and a restarted process
registers under a new (pid-derived) worker id instead.

**Leader leases** (PR 16) apply the same three disciplines one layer
up, to the coordinator itself.  The fleet **generation** is the
coordinator-level fencing epoch: every coordinator that ever leads this
board wins exactly one generation by claiming ``leader/g<gen>`` through
the board's single-winner ``claim`` primitive, renews a beat value on
every pump tick, and stamps its generation into every block id it
offers.  A ``--fleet-standby`` process watches the newest generation's
beat exactly the way :class:`Membership` watches worker heartbeats —
value *change* under a tick-counted deadline — and on a stale verdict
races ``claim`` on the NEXT generation; the winner replays the dead
leader's board checkpoint (:func:`read_checkpoint`) and every key the
dead leader ever posted is now a fenced lower generation, swept by the
new leader's board GC.  Death is terminal here too: a deposed leader
(one that observes a higher generation claim) must stop answering —
:class:`~..serve.fleet.FleetCoordinator` raises on the next pump.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass

from ..obs.events import publish

#: Board key namespace.  One fleet per board: for FileBoard fleets the
#: board *directory* is the run scope, so no run tag is needed here.
_ROOT = "seqalign/fleet"
FLEET_PREFIX = f"{_ROOT}/"  # everything the board GC may ever sweep
WORKER_PREFIX = f"{_ROOT}/worker/"
OFFER_PREFIX = f"{_ROOT}/offer/"


def worker_key(wid: str) -> str:
    return f"{WORKER_PREFIX}{wid}"


def heartbeat_key(wid: str) -> str:
    return f"{_ROOT}/hb/{wid}"


def offer_key(bid: str) -> str:
    return f"{OFFER_PREFIX}{bid}"


def claim_key(bid: str, epoch: int) -> str:
    return f"{_ROOT}/claim/{bid}/e{int(epoch)}"


def result_key(bid: str, epoch: int) -> str:
    return f"{_ROOT}/result/{bid}/e{int(epoch)}"


def shutdown_key() -> str:
    return f"{_ROOT}/shutdown"


def obs_snapshot_key(wid: str) -> str:
    """One bounded observability snapshot per worker (metrics + recent
    trace events + the flight-recorder tape), overwritten in place —
    the coordinator's federation/merge source and the post-mortem tape
    it collects when the worker is declared dead."""
    return f"{_ROOT}/obssnap/{wid}"


#: Leader-lease key namespace: one claim key per generation (the
#: single-winner record), one beat key per generation (liveness), one
#: checkpoint key per generation (the takeover's replay state).
LEADER_PREFIX = f"{_ROOT}/leader/"


def leader_claim_key(gen: int) -> str:
    return f"{LEADER_PREFIX}g{int(gen)}"


def leader_beat_key(gen: int) -> str:
    return f"{_ROOT}/leaderhb/g{int(gen)}"


def ckpt_key(gen: int) -> str:
    return f"{_ROOT}/ckpt/g{int(gen)}"


def current_generation(board) -> int:
    """The newest leader generation ever claimed on this board (-1 on a
    board no coordinator has led yet).  A scan, not a counter post: the
    claim keys themselves are the authoritative monotonic record, so
    there is no torn-counter state to reconcile after a crash."""
    best = -1
    for key in board.keys(LEADER_PREFIX):
        name = key[len(LEADER_PREFIX):]
        if not name.startswith("g"):
            continue
        try:
            best = max(best, int(name[1:]))
        except ValueError:
            continue
    return best


def board_read_json(board, key: str) -> dict | None:
    """One JSON-object read with the torn-post guarantee: a missing,
    zero-length, unparsable, or non-object post reads as None."""
    raw = board.get(key)
    if raw is None or not raw.strip():
        return None
    try:
        obj = json.loads(raw)
    except (json.JSONDecodeError, ValueError):
        return None
    return obj if isinstance(obj, dict) else None


@dataclass
class WorkerView:
    """Coordinator-side view of one registered worker."""

    wid: str
    beat: int = -1  # last heartbeat VALUE read off the board
    seen_tick: int = 0  # tick that value last changed
    alive: bool = True


class Membership:
    """The worker registry: registrations plus heartbeat staleness.

    ``observe(tick)`` is the whole protocol: scan registration keys (a
    new one is a join), re-read each live worker's heartbeat (a changed
    value proves liveness at this tick; a value frozen for
    ``deadline_ticks`` ticks is a death verdict).  Publishes
    ``worker.join`` / ``worker.dead`` and returns the joined/died ids.
    """

    def __init__(self, board, deadline_ticks: int):
        if deadline_ticks < 1:
            raise ValueError(
                f"membership deadline must be >= 1 tick, got {deadline_ticks}"
            )
        self.board = board
        self.deadline_ticks = int(deadline_ticks)
        self.workers: dict[str, WorkerView] = {}

    def observe(self, tick: int) -> tuple[list[str], list[str]]:
        tick = int(tick)
        joined: list[str] = []
        died: list[str] = []
        for key in self.board.keys(WORKER_PREFIX):
            wid = key[len(WORKER_PREFIX):]
            if not wid or wid in self.workers:
                continue
            if board_read_json(self.board, key) is None:
                continue  # torn registration: not a member (yet)
            self.workers[wid] = WorkerView(wid, seen_tick=tick)
            joined.append(wid)
            publish("worker.join", worker=wid, workers=self.live_count())
        for view in self.workers.values():
            if not view.alive:
                continue
            beat = self._read_beat(view.wid)
            if beat is not None and beat != view.beat:
                view.beat = beat
                view.seen_tick = tick
            elif tick - view.seen_tick >= self.deadline_ticks:
                view.alive = False
                died.append(view.wid)
        for wid in died:
            publish("worker.dead", worker=wid, workers=self.live_count())
        return joined, died

    def _read_beat(self, wid: str) -> int | None:
        raw = self.board.get(heartbeat_key(wid))
        if raw is None or not raw.strip():
            return None
        try:
            return int(raw)
        except ValueError:
            return None  # torn heartbeat reads as missing

    def live(self) -> list[str]:
        return [w.wid for w in self.workers.values() if w.alive]

    def live_count(self) -> int:
        return sum(1 for w in self.workers.values() if w.alive)

    def is_live(self, wid: str) -> bool:
        view = self.workers.get(wid)
        return view is not None and view.alive


@dataclass
class Lease:
    """One superblock's lease: fencing epoch, holder, and the tick its
    expiry clock last (re)started — at issue, claim, or bump."""

    bid: str
    epoch: int = 0
    holder: str | None = None
    since: int = 0


class LeaseTable:
    """Epoch-fenced leases with tick-counted expiry.

    The epoch is the fencing token: every re-dispatch bumps it, claim
    and result posts embed it, and :meth:`admits` — the one acceptance
    predicate — only passes the CURRENT epoch.  A zombie holding epoch
    N cannot double-answer after the coordinator moved to N+1.
    """

    def __init__(self, lease_ticks: int):
        if lease_ticks < 1:
            raise ValueError(
                f"lease must be >= 1 tick, got {lease_ticks}"
            )
        self.lease_ticks = int(lease_ticks)
        self._leases: dict[str, Lease] = {}

    def issue(self, bid: str, tick: int) -> Lease:
        if bid in self._leases:
            raise ValueError(f"lease for block {bid!r} already issued")
        lease = Lease(bid, since=int(tick))
        self._leases[bid] = lease
        return lease

    def get(self, bid: str) -> Lease:
        return self._leases[bid]

    def note_claim(self, bid: str, wid: str, tick: int) -> None:
        lease = self._leases[bid]
        lease.holder = str(wid)
        lease.since = int(tick)  # the expiry clock restarts at the claim

    def bump(self, bid: str, tick: int) -> int:
        """Fence + re-arm: next epoch, no holder, expiry clock reset."""
        lease = self._leases[bid]
        lease.epoch += 1
        lease.holder = None
        lease.since = int(tick)
        return lease.epoch

    def admits(self, bid: str, epoch: int) -> bool:
        """The fencing predicate: does a result carrying ``epoch``
        answer the CURRENT lease?  Retired/unknown blocks admit
        nothing."""
        lease = self._leases.get(bid)
        return lease is not None and int(epoch) == lease.epoch

    def retire(self, bid: str) -> None:
        self._leases.pop(bid, None)

    def expired(self, tick: int) -> list[Lease]:
        tick = int(tick)
        return [
            lease
            for lease in self._leases.values()
            if tick - lease.since >= self.lease_ticks
        ]

    def held_by(self, wid: str) -> list[Lease]:
        return [
            lease for lease in self._leases.values()
            if lease.holder == str(wid)
        ]


class LeaderLease:
    """The coordinator-level lease: exactly one leader per generation.

    Leader side: :meth:`acquire` wins the next free generation (board
    ``claim`` — the same ``os.link`` single-winner primitive worker
    leases ride), :meth:`renew` posts the beat every pump tick, and
    :meth:`deposed` detects a successor (any higher-generation claim).

    Standby side: :meth:`observe` is one watch tick — the same
    change-under-a-tick-counted-deadline liveness rule as worker
    heartbeats (SEQ005: the caller supplies the tick number; wall time
    never decides).  A leader whose beat value has not changed for
    ``deadline_ticks`` observed ticks — including one that died before
    its first beat ever landed — earns a dead verdict, and the standby
    races :meth:`try_acquire` on the NEXT generation.  Losing that race
    is not an error: a rival standby won, and the watch simply restarts
    against the new leader's beat.
    """

    def __init__(self, board, lid: str, deadline_ticks: int):
        if deadline_ticks < 1:
            raise ValueError(
                f"leader deadline must be >= 1 tick, got {deadline_ticks}"
            )
        self.board = board
        self.lid = str(lid)
        self.deadline_ticks = int(deadline_ticks)
        self.gen: int | None = None  # the generation this lease holds
        self._beat = 0
        # Standby watch state: the generation under watch, the last beat
        # value read, and the tick that value last changed.
        self._watch_gen: int | None = None
        self._watch_beat: str | None = None
        self._watch_tick = 0

    # -- leader side -------------------------------------------------------

    def try_acquire(self, gen: int) -> bool:
        """One claim attempt on one specific generation — the standby
        race's unit.  Exactly one claimer wins; the loser keeps
        watching."""
        won = self.board.claim(
            leader_claim_key(gen),
            json.dumps({"lid": self.lid, "gen": int(gen)}),
        )
        if won:
            self.gen = int(gen)
            self.renew()
            publish("leader.elected", leader=self.lid, gen=int(gen))
        return won

    def acquire(self) -> int:
        """Startup acquisition: claim the next free generation.  Bounded
        retries cover the startup race where several coordinators scan
        the same maximum — each retry re-scans, so the loop terminates
        as soon as this process stops losing."""
        for _ in range(64):
            if self.try_acquire(current_generation(self.board) + 1):
                return self.gen
        raise RuntimeError(
            "could not win a fleet leader generation after 64 claim "
            "attempts (a claim storm this deep means the board is sick)"
        )

    def renew(self) -> None:
        """Post the next beat value (leader liveness).  Best-effort on a
        sick board: one missed beat is indistinguishable from a slow
        tick; a board that stays unwritable earns this leader the same
        dead verdict a crash would."""
        self._beat += 1
        try:
            self.board.post(leader_beat_key(self.gen), str(self._beat))
        except OSError:
            pass

    def deposed(self) -> bool:
        """Has any successor generation been claimed?  The deposed
        leader must stop answering — its late posts are fenced by
        generation exactly as a zombie worker's are by epoch."""
        return self.gen is not None and current_generation(self.board) > self.gen

    # -- standby side ------------------------------------------------------

    def watched_gen(self) -> int | None:
        """The generation currently under watch (None before any leader
        has claimed)."""
        return self._watch_gen

    def observe(self, tick: int) -> bool:
        """One standby watch tick; True when the watched leader's beat
        has been frozen (or absent) for ``deadline_ticks`` ticks.  A new
        claim — even mid-countdown — restarts the watch against the new
        generation: the verdict always names the NEWEST leader."""
        tick = int(tick)
        gen = current_generation(self.board)
        if gen < 0:
            # No leader has ever claimed: nothing to succeed.  A standby
            # is a coordinator-in-WAITING; it never seizes a virgin board.
            self._watch_gen = None
            return False
        raw = self.board.get(leader_beat_key(gen))
        beat = raw.strip() if raw is not None and raw.strip() else None
        if gen != self._watch_gen:
            self._watch_gen = gen
            self._watch_beat = beat
            self._watch_tick = tick
            return False
        if beat is not None and beat != self._watch_beat:
            self._watch_beat = beat
            self._watch_tick = tick
            return False
        return tick - self._watch_tick >= self.deadline_ticks


def read_obs_snapshot(board, wid: str) -> dict | None:
    """Read one worker's observability snapshot with the torn-post
    guarantee plus identity validation: a snapshot that is absent,
    torn, or stamped with a DIFFERENT worker id (an alien post — a key
    collision or a confused writer) reads as missing.  Observability is
    best-effort by construction: missing is never fatal."""
    post = board_read_json(board, obs_snapshot_key(wid))
    if post is None:
        return None
    if post.get("wid") != wid:
        return None
    return post


class ClockOffsetEstimator:
    """Deterministic per-worker clock-offset estimates from offer/claim
    echo pairs.

    The coordinator stamps each offer with its own clock (``t_post``),
    the claiming worker echoes its clock (``t_echo``) in the claim
    payload, and the coordinator reads the claim at ``t_seen``.  One
    such pair bounds the worker clock against the coordinator clock the
    way one NTP exchange does: the echo happened somewhere inside
    ``[t_post, t_seen]``, so the midpoint estimate

        ``offset = t_echo - (t_post + t_seen) / 2``

    is wrong by at most half the round trip.  The estimator keeps the
    minimum-RTT pair per worker — the tightest bound seen — which makes
    the estimate a deterministic function of the observed pairs (same
    pairs, same verdict: the change-under-tick discipline of the rest
    of this module, applied to clock alignment).  No clock is read
    here (SEQ005); every timestamp is caller-supplied.
    """

    def __init__(self):
        # wid -> (rtt_s, offset_s) of the best (minimum-RTT) pair.
        self._best: dict[str, tuple[float, float]] = {}

    def observe(self, wid: str, t_post, t_echo, t_seen) -> None:
        """Fold one echo pair in.  Non-numeric or causally impossible
        pairs (``t_seen < t_post``) are dropped — a torn claim must not
        corrupt the estimate."""
        try:
            t_post = float(t_post)
            t_echo = float(t_echo)
            t_seen = float(t_seen)
        except (TypeError, ValueError):
            return
        if not (math.isfinite(t_post) and math.isfinite(t_echo)
                and math.isfinite(t_seen)):
            return
        rtt = t_seen - t_post
        if rtt < 0.0:
            return
        offset = t_echo - (t_post + t_seen) / 2.0
        best = self._best.get(str(wid))
        if best is None or rtt < best[0]:
            self._best[str(wid)] = (rtt, offset)

    def offset(self, wid: str) -> float | None:
        """Worker-minus-coordinator clock offset (seconds), or None
        before any echo pair has been observed for ``wid``."""
        best = self._best.get(str(wid))
        return best[1] if best is not None else None

    def uncertainty(self, wid: str) -> float | None:
        """Half the best pair's round trip: the estimate's error bound."""
        best = self._best.get(str(wid))
        return best[0] / 2.0 if best is not None else None

    def to_coordinator(self, wid: str, t_worker) -> float | None:
        """Map one worker-clock timestamp onto the coordinator clock
        (None while the worker's offset is still unknown)."""
        off = self.offset(wid)
        if off is None:
            return None
        try:
            return float(t_worker) - off
        except (TypeError, ValueError):
            return None

    def snapshot(self) -> dict[str, dict]:
        """JSON-ready per-worker estimates (the run report / bench
        table's ``clock_offsets`` rows)."""
        return {
            wid: {
                "offset_s": round(offset, 9),
                "rtt_s": round(rtt, 9),
            }
            for wid, (rtt, offset) in sorted(self._best.items())
        }


def write_checkpoint(board, gen: int, state: dict) -> None:
    """Post one coordinator state checkpoint (atomic board post).  The
    caller (FleetCoordinator) owns change-detection; OSError is the
    caller's to absorb — a leader that cannot checkpoint keeps serving
    and keeps its --journal authoritative."""
    board.post(ckpt_key(gen), json.dumps(state))


def read_checkpoint(board, gen: int) -> dict | None:
    """Read generation ``gen``'s coordinator checkpoint with the full
    torn-post guarantee plus shape validation: anything that is not a
    JSON object carrying list-valued ``requests``/``answered`` reads as
    missing — a takeover replays nothing rather than garbage."""
    post = board_read_json(board, ckpt_key(gen))
    if post is None:
        return None
    if not isinstance(post.get("requests"), list):
        return None
    if not isinstance(post.get("answered"), list):
        return None
    return post
