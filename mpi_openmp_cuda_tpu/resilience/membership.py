"""Fleet membership: worker registry, heartbeats, epoch-fenced leases.

ROADMAP item 2 names the PR-4 rescue board as the membership layer for
an *elastic* serve fleet — workers joining and leaving mid-serve.  This
module is that layer: the pure bookkeeping the fleet coordinator
(serve/fleet.py) drives once per board poll.  It owns three things:

* the **board key schema** under ``seqalign/fleet/`` — registrations,
  heartbeats, superblock offers, lease claims, epoch-stamped results;
* :class:`Membership` — who is alive, decided from heartbeat *change*
  under a tick-counted deadline;
* :class:`LeaseTable` — which worker owns which offered superblock, at
  which fencing epoch, and when a lease has expired.

Two invariants, both inherited from the PR-4 board pattern:

* **Torn posts read as missing, never as data.**  Every structured
  record crossing the board goes through :func:`board_read_json`: a
  post that is absent, zero-length, unparsable (a writer killed
  mid-write on a non-atomic board, or the chaos tier's deliberately
  torn ``board:torn-post``), or not a JSON object is indistinguishable
  from no post at all.  The lease deadline then re-dispatches the work
  — a torn result can delay an answer, never corrupt one.
* **Decisions are tick-counted, never wall-clock (SEQ005).**  The
  caller hands ``observe``/``expired`` its own monotonically increasing
  poll-tick number.  A worker is dead when its heartbeat value has not
  *changed* for ``deadline_ticks`` observed ticks; a lease is expired
  ``lease_ticks`` after issue or claim.  Wall time only paces the
  caller's polls, through the injectable serve clock, where tests
  substitute a fake.

**Epoch fencing** is how a zombie — a worker declared dead whose
process is still running — is kept from double-answering a request:
every re-dispatch bumps the lease epoch, claim and result keys embed
the epoch, and :meth:`LeaseTable.admits` is the one acceptance
predicate.  A result posted under any previous epoch lands on the
board, is counted (``lease.fenced``), and is never demuxed.  Death is
terminal: a worker whose heartbeat resumes after the verdict stays
dead — its leases were already re-dispatched — and a restarted process
registers under a new (pid-derived) worker id instead.
"""

from __future__ import annotations

import json
from dataclasses import dataclass

from ..obs.events import publish

#: Board key namespace.  One fleet per board: for FileBoard fleets the
#: board *directory* is the run scope, so no run tag is needed here.
_ROOT = "seqalign/fleet"
WORKER_PREFIX = f"{_ROOT}/worker/"
OFFER_PREFIX = f"{_ROOT}/offer/"


def worker_key(wid: str) -> str:
    return f"{WORKER_PREFIX}{wid}"


def heartbeat_key(wid: str) -> str:
    return f"{_ROOT}/hb/{wid}"


def offer_key(bid: str) -> str:
    return f"{OFFER_PREFIX}{bid}"


def claim_key(bid: str, epoch: int) -> str:
    return f"{_ROOT}/claim/{bid}/e{int(epoch)}"


def result_key(bid: str, epoch: int) -> str:
    return f"{_ROOT}/result/{bid}/e{int(epoch)}"


def shutdown_key() -> str:
    return f"{_ROOT}/shutdown"


def board_read_json(board, key: str) -> dict | None:
    """One JSON-object read with the torn-post guarantee: a missing,
    zero-length, unparsable, or non-object post reads as None."""
    raw = board.get(key)
    if raw is None or not raw.strip():
        return None
    try:
        obj = json.loads(raw)
    except (json.JSONDecodeError, ValueError):
        return None
    return obj if isinstance(obj, dict) else None


@dataclass
class WorkerView:
    """Coordinator-side view of one registered worker."""

    wid: str
    beat: int = -1  # last heartbeat VALUE read off the board
    seen_tick: int = 0  # tick that value last changed
    alive: bool = True


class Membership:
    """The worker registry: registrations plus heartbeat staleness.

    ``observe(tick)`` is the whole protocol: scan registration keys (a
    new one is a join), re-read each live worker's heartbeat (a changed
    value proves liveness at this tick; a value frozen for
    ``deadline_ticks`` ticks is a death verdict).  Publishes
    ``worker.join`` / ``worker.dead`` and returns the joined/died ids.
    """

    def __init__(self, board, deadline_ticks: int):
        if deadline_ticks < 1:
            raise ValueError(
                f"membership deadline must be >= 1 tick, got {deadline_ticks}"
            )
        self.board = board
        self.deadline_ticks = int(deadline_ticks)
        self.workers: dict[str, WorkerView] = {}

    def observe(self, tick: int) -> tuple[list[str], list[str]]:
        tick = int(tick)
        joined: list[str] = []
        died: list[str] = []
        for key in self.board.keys(WORKER_PREFIX):
            wid = key[len(WORKER_PREFIX):]
            if not wid or wid in self.workers:
                continue
            if board_read_json(self.board, key) is None:
                continue  # torn registration: not a member (yet)
            self.workers[wid] = WorkerView(wid, seen_tick=tick)
            joined.append(wid)
            publish("worker.join", worker=wid, workers=self.live_count())
        for view in self.workers.values():
            if not view.alive:
                continue
            beat = self._read_beat(view.wid)
            if beat is not None and beat != view.beat:
                view.beat = beat
                view.seen_tick = tick
            elif tick - view.seen_tick >= self.deadline_ticks:
                view.alive = False
                died.append(view.wid)
        for wid in died:
            publish("worker.dead", worker=wid, workers=self.live_count())
        return joined, died

    def _read_beat(self, wid: str) -> int | None:
        raw = self.board.get(heartbeat_key(wid))
        if raw is None or not raw.strip():
            return None
        try:
            return int(raw)
        except ValueError:
            return None  # torn heartbeat reads as missing

    def live(self) -> list[str]:
        return [w.wid for w in self.workers.values() if w.alive]

    def live_count(self) -> int:
        return sum(1 for w in self.workers.values() if w.alive)

    def is_live(self, wid: str) -> bool:
        view = self.workers.get(wid)
        return view is not None and view.alive


@dataclass
class Lease:
    """One superblock's lease: fencing epoch, holder, and the tick its
    expiry clock last (re)started — at issue, claim, or bump."""

    bid: str
    epoch: int = 0
    holder: str | None = None
    since: int = 0


class LeaseTable:
    """Epoch-fenced leases with tick-counted expiry.

    The epoch is the fencing token: every re-dispatch bumps it, claim
    and result posts embed it, and :meth:`admits` — the one acceptance
    predicate — only passes the CURRENT epoch.  A zombie holding epoch
    N cannot double-answer after the coordinator moved to N+1.
    """

    def __init__(self, lease_ticks: int):
        if lease_ticks < 1:
            raise ValueError(
                f"lease must be >= 1 tick, got {lease_ticks}"
            )
        self.lease_ticks = int(lease_ticks)
        self._leases: dict[str, Lease] = {}

    def issue(self, bid: str, tick: int) -> Lease:
        if bid in self._leases:
            raise ValueError(f"lease for block {bid!r} already issued")
        lease = Lease(bid, since=int(tick))
        self._leases[bid] = lease
        return lease

    def get(self, bid: str) -> Lease:
        return self._leases[bid]

    def note_claim(self, bid: str, wid: str, tick: int) -> None:
        lease = self._leases[bid]
        lease.holder = str(wid)
        lease.since = int(tick)  # the expiry clock restarts at the claim

    def bump(self, bid: str, tick: int) -> int:
        """Fence + re-arm: next epoch, no holder, expiry clock reset."""
        lease = self._leases[bid]
        lease.epoch += 1
        lease.holder = None
        lease.since = int(tick)
        return lease.epoch

    def admits(self, bid: str, epoch: int) -> bool:
        """The fencing predicate: does a result carrying ``epoch``
        answer the CURRENT lease?  Retired/unknown blocks admit
        nothing."""
        lease = self._leases.get(bid)
        return lease is not None and int(epoch) == lease.epoch

    def retire(self, bid: str) -> None:
        self._leases.pop(bid, None)

    def expired(self, tick: int) -> list[Lease]:
        tick = int(tick)
        return [
            lease
            for lease in self._leases.values()
            if tick - lease.since >= self.lease_ticks
        ]

    def held_by(self, wid: str) -> list[Lease]:
        return [
            lease for lease in self._leases.values()
            if lease.holder == str(wid)
        ]
