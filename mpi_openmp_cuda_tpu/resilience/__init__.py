"""Resilience runtime: retry policy, fault injection, degradation.

The reference program's failure model is pure fail-stop
(``cudaFunctions.cu:15-33``, SURVEY §5).  A production service absorbing
transient device/link faults needs the opposite default for *transient*
errors, and — critically — needs every retry/abort/resume path to be
reproducibly testable.  This package is the single policy layer the
scattered per-call-site handling migrated into:

* :mod:`.policy` — :class:`~.policy.RetryPolicy`: one attempt budget,
  exponential backoff with deterministic seeded jitter, and the
  transient-vs-fatal error classification (previously duplicated in
  ``io/cli.py``'s ``_retrying`` / ``_materialise_retrying``).
* :mod:`.faults` — deterministic fault injection: named sites at chunk
  dispatch/materialise, device transfer, journal append, and each
  coordinator broadcast fire injected errors on a counted schedule
  driven by a spec string (``SEQALIGN_FAULTS`` / ``--faults``), so chaos
  runs are exact reproducible tests instead of a hope.
* :mod:`.degrade` — graceful degradation: when a backend exhausts its
  retry budget on the same chunk, fall down the backend chain
  (pallas -> xla -> xla-gather) with a logged warning, re-verifying the
  first degraded chunk against the host oracle (``--degrade``).
* :mod:`.watchdog` — wall-clock deadlines (``--deadline`` /
  ``SEQALIGN_DEADLINE_S``) around device work and coordinator
  collectives: a monitor thread arms before each blocking boundary and
  an expiry surfaces as the *transient*
  :class:`~.watchdog.DeadlineExpiredError`, feeding the same
  retry -> degrade chain as any raised fault.
* :mod:`.drain` — graceful preemption: SIGTERM/SIGINT (or
  ``SEQALIGN_DRAIN``) sets a drain flag checked at chunk boundaries;
  in-flight results are flushed to the journal and the run exits 75
  (EX_TEMPFAIL, resumable with ``--resume``).  A second signal
  force-exits.
* :mod:`.rescue` — lost-shard recovery for ``--distributed`` batch
  runs (``SEQALIGN_BEACON_S``): per-process liveness beacons + result
  posts on the coordination-service board, a deterministic shard
  ledger naming the missing worker's index-set, and coordinator-side
  rescoring of the orphans through the degradation chain.

Everything here is pure stdlib + numpy-free at import time, so the
instrumented modules (``ops``, ``io``, ``utils``, ``parallel``) can
import the ``fire`` hook without cost or cycles.
"""

from .faults import (
    FaultRegistry,
    InjectedFatalFaultError,
    InjectedFaultError,
    activate_faults,
    deactivate_faults,
    fire,
)
from .drain import (
    DrainInterrupt,
    drain_guard,
    drain_requested,
    request_drain,
)
from .policy import FATAL_ERROR_TYPES, RetryExhaustedError, RetryPolicy
from .watchdog import (
    DeadlineExpiredError,
    HangWithoutDeadlineError,
    Watchdog,
    activate_watchdog,
    active_watchdog,
    deactivate_watchdog,
)

__all__ = [
    "FATAL_ERROR_TYPES",
    "DeadlineExpiredError",
    "DrainInterrupt",
    "FaultRegistry",
    "HangWithoutDeadlineError",
    "InjectedFatalFaultError",
    "InjectedFaultError",
    "RetryExhaustedError",
    "RetryPolicy",
    "Watchdog",
    "activate_faults",
    "activate_watchdog",
    "active_watchdog",
    "deactivate_faults",
    "deactivate_watchdog",
    "drain_guard",
    "drain_requested",
    "request_drain",
]
