"""Entry point: ``python -m mpi_openmp_cuda_tpu < input.txt``."""

from .io.cli import main

if __name__ == "__main__":
    main()
