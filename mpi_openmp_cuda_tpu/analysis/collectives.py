"""Collective-safety & comms-cost pass (the sixth analysis tier).

The ``parallel/`` layer was the only tier of the system with zero
static verification: no pass walked a sharded jaxpr, no model priced a
byte moved over ICI, and a mis-sequenced collective would surface only
as a multi-host hang in production.  Following the MPI-rical
observation that distributed-parallelism errors are statically
detectable from source (PAPERS.md, arXiv:2305.09438), this pass lowers
every sharded entry point — each ``parallel/specs.py`` mesh-spec form
at a representative bucket shape, through the SAME
``BatchSharding._prepare`` / ``RingSharding._prepare`` derivations the
production dispatch runs — on the forced multi-device CPU backend and
proves four properties per program:

1. **Collective inventory** (:func:`collective_inventory`): a recursive
   jaxpr walk collects every ``psum`` / ``all_gather`` / ``ppermute`` /
   ``all_to_all`` (and reduce-scatter variants) with its axis names,
   operand shape, dtype, and payload bytes.  Collectives inside a
   static-length ``scan`` carry the trip count; the inventory is the
   per-device collective *sequence* in program order.
2. **Ordering consistency**: every collective axis name must resolve to
   a registered mesh axis (an unregistered axis is a typed finding),
   and the per-position sequence must be provably identical across all
   mesh positions.  Position-dependence is tracked per mesh axis — a
   value is *varying* over the axes it was sharded in by
   ``shard_map``'s ``in_names`` or derived from ``axis_index``; a
   ``psum``/``all_gather`` over an axis makes its output uniform over
   that axis again.  A collective under a ``cond`` whose predicate is
   varying, or under any ``while_loop`` (dynamic trip count — equal
   per-position sequence lengths cannot be proven), is the static
   signature of a multi-host deadlock and **fails closed** as a
   ``divergent-sequence`` finding.
3. **Resharding hygiene**: the optimized post-partitioning HLO is
   diffed against the explicit jaxpr inventory — an HLO collective kind
   with a >= :data:`LARGE_RESHARD_BYTES` payload and no explicit
   counterpart is an implicit all-gather/reshard the SPMD partitioner
   inserted behind the program's back (``implicit-reshard``), and any
   large operand entering a sharded program as a bare host array (no
   committed ``jax.Array`` placement — a spec that "skipped" the
   operand) is an ``unsharded-operand`` finding.
4. **Ring-plan cross-check**: the ring entries' lowered ``ppermute``
   count must equal ``ring_plan``'s analytic ``R`` — the same number
   the ICI comms model (``analysis/costmodel.py``) prices, so the
   modelled ``predicted_scaling_efficiency`` rows and the lowered
   programs cannot drift apart.

``scripts/comms_audit.py`` (``make comms-audit``) wraps the report in
the run-report envelope and diffs inventory, ordering signatures, and
the modelled comms fields against ``tests/golden/comms_audit.json``.
CPU-only, zero real devices, a few seconds.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import json
import re

import numpy as np

from . import CollectiveAuditError
from .traceaudit import LARGE_BUFFER_BYTES

#: Hygiene threshold: an un-annotated intermediate crossing the mesh at
#: or above this size is a finding.  Deliberately the same bound the
#: trace-audit donation gate uses for "large" buffers — one notion of
#: large across the trace tier.
LARGE_RESHARD_BYTES = LARGE_BUFFER_BYTES

#: jaxpr primitive names that move bytes across the mesh.
COLLECTIVE_PRIMS = frozenset(
    {
        "psum",
        "pmax",
        "pmin",
        "ppermute",
        "pshuffle",
        "all_gather",
        "all_to_all",
        "reduce_scatter",
        "psum_scatter",
    }
)

#: Collectives whose output is *uniform* over the reduced/gathered axes
#: (every member holds the same value afterwards) — the varying-axes
#: tracking subtracts these axes; a ppermute/all_to_all output stays
#: position-dependent.
_UNIFORMIZING_PRIMS = frozenset(
    {"psum", "pmax", "pmin", "all_gather"}
)

#: jaxpr primitive -> optimized-HLO instruction family, for the
#: pre/post-partitioning hygiene diff.
HLO_OF_PRIM = {
    "psum": "all-reduce",
    "pmax": "all-reduce",
    "pmin": "all-reduce",
    "ppermute": "collective-permute",
    "pshuffle": "collective-permute",
    "all_gather": "all-gather",
    "all_to_all": "all-to-all",
    "reduce_scatter": "reduce-scatter",
    "psum_scatter": "reduce-scatter",
}

#: Optimized-HLO collective matcher: result dtype + dims + op family.
#: Matches both sync ops and their ``-start`` async halves (``-done``
#: carries no second collective).  The canonical parser — the test
#: harness's ``conftest.collective_ops`` delegates here.
_HLO_COLLECTIVE_RE = re.compile(
    r"=\s*\(?\s*([a-z0-9]+)\[([\d,]*)\][^=]*?\s"
    r"(all-gather|all-reduce|collective-permute|all-to-all|"
    r"reduce-scatter|collective-broadcast)(-start)?\("
)

_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2,
    "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
    "f64": 8,
}


@dataclasses.dataclass(frozen=True)
class CollectiveOp:
    """One collective in a lowered program's per-device sequence."""

    op: str  # jaxpr primitive name
    axes: tuple[str, ...]  # mesh axis names it communicates over
    shape: tuple[int, ...]  # first operand's (per-device) shape
    dtype: str
    payload_bytes: int  # summed over array operands, per invocation
    count: int  # invocations (enclosing static scan lengths)

    def row(self) -> dict:
        return {
            "op": self.op,
            "axes": list(self.axes),
            "shape": list(self.shape),
            "dtype": self.dtype,
            "payload_bytes": self.payload_bytes,
            "count": self.count,
        }

    def describe(self) -> str:
        axes = ",".join(self.axes) or "-"
        return (
            f"{self.op:<12s} axes={axes:<10s} "
            f"{self.dtype}{list(self.shape)} "
            f"payload={self.payload_bytes}B x{self.count}"
        )


def hlo_collectives(hlo_text: str) -> list[dict]:
    """Every cross-device collective of an optimized-HLO dump:
    ``{"op", "dtype", "elements", "bytes"}`` rows — the statically
    auditable collective set of a compiled SPMD program, the TPU
    analogue of reading the MPI calls off the reference's main.c."""
    rows = []
    for m in _HLO_COLLECTIVE_RE.finditer(hlo_text):
        dims = [int(d) for d in m.group(2).split(",") if d]
        elements = int(np.prod(dims)) if dims else 1
        itemsize = _HLO_DTYPE_BYTES.get(m.group(1), 4)
        rows.append(
            {
                "op": m.group(3),
                "dtype": m.group(1),
                "elements": elements,
                "bytes": elements * itemsize,
            }
        )
    return rows


# -- the jaxpr walk ---------------------------------------------------------


def _unwrap_jaxpr(val):
    """The raw ``Jaxpr`` under a ClosedJaxpr/param value, else None."""
    seen = 0
    while hasattr(val, "jaxpr") and seen < 4:
        val = val.jaxpr
        seen += 1
    return val if hasattr(val, "eqns") else None


def _iter_sub_jaxprs(params: dict):
    """Every raw sub-jaxpr reachable from an eqn's params (the
    traceaudit recursion idiom, shared here)."""
    for val in params.values():
        items = val if isinstance(val, (tuple, list)) else (val,)
        for item in items:
            sub = _unwrap_jaxpr(item)
            if sub is not None:
                yield sub


def _contains_collective(jaxpr) -> bool:
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in COLLECTIVE_PRIMS:
            return True
        for sub in _iter_sub_jaxprs(eqn.params):
            if _contains_collective(sub):
                return True
    return False


def _uses_axis_index(jaxpr) -> bool:
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "axis_index":
            return True
        for sub in _iter_sub_jaxprs(eqn.params):
            if _uses_axis_index(sub):
                return True
    return False


def _collective_axes(params: dict) -> tuple[str, ...]:
    """Mesh axis names a collective eqn communicates over.  ``psum``
    spells them ``axes``, the rest ``axis_name``; positional (int)
    vmap axes are not mesh axes and are skipped."""
    axes = params.get("axes", params.get("axis_name", ()))
    if axes is None:
        axes = ()
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(str(a) for a in axes if not isinstance(a, int))


def _names_union(names) -> frozenset:
    """Union of the axis names in one shard_map ``in_names``/
    ``out_names`` dict ({dim: (axis, ...)})."""
    out: set[str] = set()
    for axes in (names or {}).values():
        axes = axes if isinstance(axes, (tuple, list)) else (axes,)
        out.update(str(a) for a in axes)
    return frozenset(out)


class _Walker:
    """One program's inventory walk with per-axis position-dependence
    tracking.  ``varying`` maps ``id(var)`` to the frozenset of mesh
    axes the value differs over; uniform values are simply absent."""

    def __init__(self, entry: str, registered: frozenset):
        self.entry = entry
        self.registered = registered
        self.ops: list[CollectiveOp] = []
        self.findings: list[dict] = []

    def _finding(self, kind: str, detail: str):
        self.findings.append(
            {"kind": kind, "entry": self.entry, "detail": detail}
        )

    @staticmethod
    def _ax(varying: dict, v) -> frozenset:
        if hasattr(v, "val"):  # Literal: a host constant, uniform
            return frozenset()
        return varying.get(id(v), frozenset())

    def _record(self, eqn, repeat: int):
        axes = _collective_axes(eqn.params)
        for a in axes:
            if a not in self.registered:
                self._finding(
                    "unregistered-axis",
                    f"{eqn.primitive.name} over axis {a!r}, which is not "
                    f"a registered mesh axis "
                    f"({sorted(self.registered)}): the collective would "
                    "fail to resolve (or silently bind a different mesh) "
                    "at dispatch",
                )
        shape: tuple[int, ...] = ()
        dtype = "?"
        payload = 0
        for v in eqn.invars:
            aval = getattr(v, "aval", None)
            if aval is None or not getattr(aval, "shape", None) and not (
                hasattr(aval, "dtype")
            ):
                continue
            nbytes = int(np.prod(aval.shape, dtype=np.int64)) * int(
                np.dtype(aval.dtype).itemsize
            )
            if not shape and not payload:
                shape = tuple(int(d) for d in aval.shape)
                dtype = str(np.dtype(aval.dtype))
            payload += nbytes
        self.ops.append(
            CollectiveOp(
                op=eqn.primitive.name,
                axes=axes,
                shape=shape,
                dtype=dtype,
                payload_bytes=payload,
                count=repeat,
            )
        )

    def walk(self, jaxpr, varying: dict, repeat: int = 1) -> list:
        """Walk one raw jaxpr; returns the varying-axes sets of its
        outvars.  ``varying`` seeds the invars (keyed by ``id``)."""
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            in_ax: frozenset = frozenset()
            for v in eqn.invars:
                in_ax |= self._ax(varying, v)

            if name == "axis_index":
                out_ax = in_ax | {str(eqn.params.get("axis_name"))}
            elif name in COLLECTIVE_PRIMS:
                self._record(eqn, repeat)
                axes = frozenset(_collective_axes(eqn.params))
                if name in _UNIFORMIZING_PRIMS:
                    out_ax = in_ax - axes
                else:
                    out_ax = in_ax | axes
            elif name == "cond":
                out_ax = self._walk_cond(eqn, varying, repeat, in_ax)
            elif name == "while":
                out_ax = self._walk_while(eqn, varying, repeat, in_ax)
            elif name == "scan":
                out_ax = self._walk_scan(eqn, varying, repeat, in_ax)
            elif name == "shard_map":
                out_ax = self._walk_shard_map(eqn, varying, repeat)
            else:
                out_ax = self._walk_generic(eqn, varying, repeat, in_ax)

            for v in eqn.outvars:
                if out_ax:
                    varying[id(v)] = out_ax
        return [self._ax(varying, v) for v in jaxpr.outvars]

    def _seed(self, sub, eqn_invars, varying, in_ax) -> dict:
        """Seed a sub-jaxpr's invars: positional when the arities line
        up, else conservatively the union of the caller's axes."""
        inner: dict = {}
        if len(sub.invars) == len(eqn_invars):
            for iv, ov in zip(sub.invars, eqn_invars):
                ax = self._ax(varying, ov)
                if ax:
                    inner[id(iv)] = ax
        else:
            for iv in sub.invars:
                if in_ax:
                    inner[id(iv)] = in_ax
        return inner

    def _walk_cond(self, eqn, varying, repeat, in_ax) -> frozenset:
        branches = eqn.params.get("branches") or ()
        subs = [_unwrap_jaxpr(b) for b in branches]
        subs = [s for s in subs if s is not None]
        pred_ax = self._ax(varying, eqn.invars[0])
        if any(_uses_axis_index(s) for s in subs):
            pred_ax = pred_ax  # predicate divergence is what matters
        has_coll = any(_contains_collective(s) for s in subs)
        if has_coll and pred_ax:
            self._finding(
                "divergent-sequence",
                "collective inside a cond whose predicate varies over "
                f"mesh axes {sorted(pred_ax)}: mesh positions would "
                "take different branches and issue DIFFERENT collective "
                "sequences — the static signature of a multi-host "
                "deadlock (fail closed)",
            )
        out_ax = in_ax
        for sub in subs:
            inner = self._seed(sub, eqn.invars[1:], varying, in_ax)
            for ax in self.walk(sub, inner, repeat):
                out_ax |= ax
        return out_ax | pred_ax

    def _walk_while(self, eqn, varying, repeat, in_ax) -> frozenset:
        subs = list(_iter_sub_jaxprs(eqn.params))
        if any(_contains_collective(s) for s in subs):
            self._finding(
                "divergent-sequence",
                "collective inside a while_loop: the trip count is "
                "dynamic, so equal per-position collective-sequence "
                "lengths cannot be proven statically (fail closed); "
                "use a static-length scan or hoist the collective",
            )
        out_ax = in_ax
        for sub in subs:
            inner = {}
            for iv in sub.invars:
                if in_ax:
                    inner[id(iv)] = in_ax
            for ax in self.walk(sub, inner, repeat):
                out_ax |= ax
        return out_ax

    def _walk_scan(self, eqn, varying, repeat, in_ax) -> frozenset:
        length = eqn.params.get("length") or 1
        out_ax = in_ax
        for sub in _iter_sub_jaxprs(eqn.params):
            inner = {}
            for iv in sub.invars:
                if in_ax:
                    inner[id(iv)] = in_ax
            for ax in self.walk(sub, inner, repeat * int(length)):
                out_ax |= ax
        return out_ax

    def _walk_shard_map(self, eqn, varying, repeat) -> frozenset:
        sub = _unwrap_jaxpr(eqn.params.get("jaxpr"))
        in_names = eqn.params.get("in_names") or ()
        out_names = eqn.params.get("out_names") or ()
        if sub is None:
            return frozenset()
        inner: dict = {}
        body_invars = sub.invars[-len(in_names):] if in_names else sub.invars
        for iv, names in zip(body_invars, in_names):
            ax = _names_union(names) | self._ax(varying, iv)
            if ax:
                inner[id(iv)] = ax
        self.walk(sub, inner, repeat)
        out_ax: frozenset = frozenset()
        for names in out_names:
            out_ax |= _names_union(names)
        return out_ax

    def _walk_generic(self, eqn, varying, repeat, in_ax) -> frozenset:
        subs = list(_iter_sub_jaxprs(eqn.params))
        if not subs:
            return in_ax
        out_ax = in_ax
        for sub in subs:
            inner = self._seed(sub, eqn.invars, varying, in_ax)
            for ax in self.walk(sub, inner, repeat):
                out_ax |= ax
        return out_ax


def collective_inventory(
    fn, args, registered_axes, entry: str = "program"
) -> tuple[list[CollectiveOp], list[dict]]:
    """Trace ``fn(*args)`` and walk the jaxpr: the per-device collective
    sequence in program order, plus the ordering findings (unregistered
    axes, divergent branches — see the module docstring).  ``fn`` may be
    a jitted wrapper; the walk recurses through pjit/shard_map/control-
    flow sub-jaxprs."""
    import jax

    closed = jax.make_jaxpr(fn)(*args)
    walker = _Walker(entry, frozenset(str(a) for a in registered_axes))
    walker.walk(closed.jaxpr, {})
    return walker.ops, walker.findings


def ordering_signature(ops: list[CollectiveOp]) -> str:
    """Stable digest of one per-device collective sequence: op, axes,
    shape, dtype, payload, count — in program order."""
    blob = json.dumps([op.row() for op in ops], sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def mesh_positions(mesh) -> list[tuple[int, ...]]:
    """Every coordinate of the mesh, in axis order."""
    sizes = [int(mesh.shape[a]) for a in mesh.axis_names]
    return list(itertools.product(*[range(s) for s in sizes]))


def operand_placement(
    entry: str, args, threshold: int = LARGE_RESHARD_BYTES
) -> list[dict]:
    """Hygiene gate on a sharded program's operands: every array at or
    above ``threshold`` must enter as a committed ``jax.Array`` (a
    ``NamedSharding`` placement from ``_put_global``) — a bare host
    array is an operand the spec *skipped*, which the partitioner will
    reshard implicitly on every dispatch."""
    import jax

    findings = []
    for i, a in enumerate(args):
        nbytes = int(getattr(a, "nbytes", 0) or 0)
        if nbytes < threshold:
            continue
        if not isinstance(a, jax.Array):
            findings.append(
                {
                    "kind": "unsharded-operand",
                    "entry": entry,
                    "detail": (
                        f"operand {i} ({type(a).__name__}, {nbytes} B) "
                        "enters the sharded program as a bare host "
                        "array — the sharding spec skipped it, so the "
                        "partitioner reshards it implicitly on every "
                        "dispatch; place it with _put_global / "
                        "jax.device_put(NamedSharding(...))"
                    ),
                }
            )
    return findings


def reshard_hygiene(
    entry: str,
    hlo_text: str,
    ops: list[CollectiveOp],
    threshold: int = LARGE_RESHARD_BYTES,
) -> tuple[list[dict], list[dict]]:
    """Diff the post-partitioning HLO collectives against the explicit
    jaxpr inventory.  Returns ``(hlo_rows, findings)``: an HLO
    collective *kind* with a >= ``threshold`` payload and no explicit
    jaxpr counterpart is an implicit reshard the partitioner inserted
    (an un-annotated intermediate crossing the mesh).  Counts are not
    compared — async splitting and fusion legitimately reshape them;
    the kind set plus the large-payload gate is the stable contract."""
    explicit_kinds = {HLO_OF_PRIM.get(op.op) for op in ops}
    rows = hlo_collectives(hlo_text)
    findings = []
    for row in rows:
        if row["bytes"] >= threshold and row["op"] not in explicit_kinds:
            findings.append(
                {
                    "kind": "implicit-reshard",
                    "entry": entry,
                    "detail": (
                        f"partitioner inserted a {row['op']} of "
                        f"{row['bytes']} B ({row['dtype']}, "
                        f"{row['elements']} elements) with no explicit "
                        "collective in the program — an un-annotated "
                        "intermediate is crossing the mesh; annotate "
                        "the sharding (in_specs/out_specs) or move the "
                        "exchange into an explicit parallel/ collective"
                    ),
                }
            )
    return rows, findings


# -- the entry-point audit --------------------------------------------------

#: Every mesh-spec grammar form (parallel/specs.py), audited through
#: the real strategy ``_prepare`` derivations at the representative
#: bucket shape below.  Values: devices the spec needs.
AUDIT_SPECS: dict[str, int] = {
    "2": 2,
    "batch:2": 2,
    "seq:4": 4,
    "2x2": 4,
}

#: Representative bucket shape: Seq1 of 150 chars (l1p = 256 after the
#: 128-lane round-up, so the ring path takes R >= 2 neighbour
#: exchanges) and six Seq2 rows topping out at 100 (l2p = 128).
_REP_LEN1 = 150
_REP_LEN2S = (100, 60, 40, 100, 25, 7)
_REP_WEIGHTS = (2, 2, 1, 10)


def _representative_batch():
    from ..ops.dispatch import pad_problem
    from ..ops.values import value_table

    rng = np.random.default_rng(14)
    seq1 = rng.integers(1, 27, size=_REP_LEN1).astype(np.int32)
    seq2s = [
        rng.integers(1, 27, size=n).astype(np.int32) for n in _REP_LEN2S
    ]
    batch = pad_problem(seq1, seq2s)
    val_flat = value_table(_REP_WEIGHTS).astype(np.int32).reshape(-1)
    return batch, val_flat


def audit_program(
    entry: str, fn, args, mesh, *, compile_hlo: bool = True
) -> tuple[dict, list[dict]]:
    """Audit one prepared sharded program: inventory + ordering +
    hygiene.  Returns ``(entry_row, findings)``."""
    registered = tuple(str(a) for a in mesh.axis_names)
    ops, findings = collective_inventory(
        fn, args, registered, entry=entry
    )
    findings = list(findings)
    findings.extend(operand_placement(entry, args))
    hlo_rows: list[dict] = []
    if compile_hlo:
        hlo_text = fn.lower(*args).compile().as_text()
        hlo_rows, hygiene = reshard_hygiene(entry, hlo_text, ops)
        findings.extend(hygiene)
    divergent = any(f["kind"] == "divergent-sequence" for f in findings)
    sig = ordering_signature(ops)
    positions = mesh_positions(mesh)
    row = {
        "entry": entry,
        "mesh_axes": {
            str(a): int(mesh.shape[a]) for a in mesh.axis_names
        },
        "collectives": [op.row() for op in ops],
        "payload_bytes": sum(op.payload_bytes * op.count for op in ops),
        "signature": sig,
        "positions": len(positions),
        "per_position": [
            {"position": list(p), "signature": sig} for p in positions
        ],
        "consistent": not divergent,
        "hlo_collectives": [
            {"op": r["op"], "bytes": r["bytes"]} for r in hlo_rows
        ],
    }
    return row, findings


def audit_spec_entries(
    *, compile_hlo: bool = True, max_devices: int | None = None
) -> tuple[list[dict], list[dict]]:
    """Lower every ``AUDIT_SPECS`` mesh form through the production
    ``_prepare`` derivations at the representative bucket shape and
    audit each program.  ``max_devices`` skips the specs this process
    cannot mesh (bench on a single real chip); the driver paths force
    8 virtual CPU devices and cover all of them."""
    import jax

    from ..parallel.specs import build_sharding

    avail = len(jax.devices())
    if max_devices is not None:
        avail = min(avail, max_devices)
    batch, val_flat = _representative_batch()
    entries: list[dict] = []
    findings: list[dict] = []
    for spec, need in AUDIT_SPECS.items():
        if need > avail:
            continue
        strategy = build_sharding(spec)
        fn, args, _ = strategy._prepare(batch, val_flat, backend="xla")
        entry = f"{type(strategy).__name__}[{spec}]"
        row, found = audit_program(
            entry, fn, args, strategy.mesh, compile_hlo=compile_hlo
        )
        row["spec"] = spec
        entries.append(row)
        findings.extend(found)
    return entries, findings


def ring_crosscheck(entries: list[dict]) -> tuple[list[dict], list[dict]]:
    """Pin the lowered ring entries to ``ring_plan``'s analytic ``R``:
    the count the ICI comms model prices.  Drift between the plan
    arithmetic and the lowered program is a ``ring-plan-drift``
    finding — the scaling-efficiency rows would be pricing a program
    that no longer exists."""
    from ..parallel.ring import ring_plan

    batch, _ = _representative_batch()
    rows: list[dict] = []
    findings: list[dict] = []
    for e in entries:
        sp = e["mesh_axes"].get("seq", 1)
        if sp <= 1:
            continue
        _, r_planned = ring_plan(batch.l1p, batch.l2p, sp, pallas=False)
        permutes = sum(
            op["count"]
            for op in e["collectives"]
            if op["op"] == "ppermute"
        )
        gathers = sum(
            op["count"]
            for op in e["collectives"]
            if op["op"] == "all_gather"
        )
        ok = permutes == r_planned and gathers == 1
        rows.append(
            {
                "entry": e["entry"],
                "planned_r": int(r_planned),
                "lowered_ppermutes": int(permutes),
                "lowered_all_gathers": int(gathers),
                "match": ok,
            }
        )
        if not ok:
            findings.append(
                {
                    "kind": "ring-plan-drift",
                    "entry": e["entry"],
                    "detail": (
                        f"ring_plan says R={r_planned} neighbour "
                        f"exchanges + 1 candidate all_gather, the "
                        f"lowered program performs {permutes} + "
                        f"{gathers}: the comms model and the program "
                        "have drifted apart (parallel/ring.py vs "
                        "analysis/costmodel.py)"
                    ),
                }
            )
    return rows, findings


def audit_collectives(*, compile_hlo: bool = True) -> dict:
    """The full comms-audit body: per-spec entries, findings, the ring
    cross-check, and the modelled ICI comms/scaling sheet for the
    production schedule (``analysis/costmodel.py``)."""
    from ..models.workload import input3_class_problem
    from .costmodel import schedule_cost_sheet

    entries, findings = audit_spec_entries(compile_hlo=compile_hlo)
    ring_rows, ring_findings = ring_crosscheck(entries)
    findings = findings + ring_findings
    sheet = schedule_cost_sheet(input3_class_problem(), "pallas")
    comms = sheet.get("comms")
    return {
        "entries": entries,
        "ring_crosscheck": ring_rows,
        "findings": findings,
        "comms": comms,
        "counts": {
            "entries": len(entries),
            "collectives": sum(
                sum(op["count"] for op in e["collectives"])
                for e in entries
            ),
            "payload_bytes": sum(e["payload_bytes"] for e in entries),
            "findings": len(findings),
        },
    }


def inventory_totals(*, max_devices: int | None = None) -> dict:
    """Never-fatal summary for ``bench.py comms_record``: inventory
    totals over the specs the current device count can mesh (a single
    real chip audits nothing and reports zero entries — the CPU audit
    paths force 8 virtual devices and cover all specs)."""
    entries, findings = audit_spec_entries(
        compile_hlo=False, max_devices=max_devices
    )
    return {
        "entries": len(entries),
        "collectives": sum(
            sum(op["count"] for op in e["collectives"]) for e in entries
        ),
        "payload_bytes": sum(e["payload_bytes"] for e in entries),
        "findings": len(findings),
    }


def run_or_raise() -> dict:
    """Driver entry (``scripts/analyze.py``): run the audit, raise
    :class:`CollectiveAuditError` naming every finding, return the
    body when clean."""
    body = audit_collectives()
    if body["findings"]:
        rows = "\n  ".join(
            f"[{f['kind']}] {f['entry']}: {f['detail']}"
            for f in body["findings"]
        )
        raise CollectiveAuditError(
            f"collective audit: {len(body['findings'])} finding(s):\n"
            f"  {rows}"
        )
    if not any(e["collectives"] for e in body["entries"]):
        raise CollectiveAuditError(
            "collective audit inventoried ZERO collectives across every "
            "sharded entry point — the ring path should contribute R "
            "ppermutes + 1 all_gather; the walk or the entry derivations "
            "have drifted (analysis/collectives.py)"
        )
    return body
