"""Jaxpr/StableHLO audit of the scorer entry points and the schedule.

The cost model (:mod:`.costmodel`) prices what the kernels *should*
cost; this pass inspects what the compiler is actually *given*.  Every
registered entry point (``contracts.ENTRY_CONTRACTS`` — the same five
the eval_shape tier audits) is lowered on CPU with abstract operands
(no FLOPs run; lowering a pallas body is cheap, executing it is not)
and the result is walked for the between-kernel losses ROADMAP items 2
and 5 are about:

* **Donation coverage** — every large input buffer that is NOT donated
  (``jax.jit``'s ``donate_argnums`` / ``tf.aliasing_output``) forces
  XLA to keep input and output alive simultaneously; on the chunk
  pipeline that is the rows/chunks arrays every launch.  Each audited
  body is lowered under the :class:`~.dataflow.DonationPlan`'s argnums
  and the gate is ENFORCED: an un-donated large buffer fails the audit
  unless the plan explicitly pins it live (scalar / below-threshold /
  alias-hazard, or a function-local jit outside the plan's
  module-level scope) — pinned rows are listed with their reason.
* **Implicit transfers / widenings** — ``device_put`` equations in a
  supposedly device-resident body, and ``convert_element_type``
  equations that WIDEN (target itemsize > source): each widening in a
  hot body multiplies VPU pass bytes and VMEM pressure.
* **Executables per schedule** — the static launch/executable counts
  the megakernel work must drive down: each bucket body must lower to
  exactly ONE ``pallas_call`` (the fused kernel), and the number of
  distinct compiled programs per schedule is the bucket cache-key
  count (``ops.schedule.BucketKernelConfig.cache_key``).

Pure lowering + jaxpr walking: CPU-only, zero devices, seconds.
"""

from __future__ import annotations

import dataclasses

from . import TraceAuditError

#: An input buffer at or above this size is "large": its round trip is
#: material HBM traffic on every launch.  16 KiB keeps the production
#: schedule's per-chunk rows arrays (24-40 KiB on the input3-class
#: workload, MiB-scale on wide buckets) in scope while letting scalars,
#: the value table, and short seq1ext operands pass.
LARGE_BUFFER_BYTES = 16 << 10


@dataclasses.dataclass(frozen=True)
class BufferInfo:
    """One flattened input operand of a lowered entry point."""

    index: int
    shape: tuple
    dtype: str
    nbytes: int
    donated: bool

    def describe(self) -> str:
        kib = self.nbytes / 1024
        mark = "donated" if self.donated else "UNDONATED"
        return (
            f"arg{self.index}: {self.dtype}{list(self.shape)} "
            f"{kib:8.1f} KiB {mark}"
        )


@dataclasses.dataclass(frozen=True)
class EntryTraceReport:
    """Audit result of one entry point at one shape bucket."""

    entry: str
    bucket: tuple  # (b, nc, l1p, l2p)
    n_args: int
    large_buffers: tuple  # BufferInfo rows (nbytes >= threshold)
    undonated_large: tuple  # undonated AND not pinned by the plan
    convert_widenings: int
    device_puts: int
    pallas_calls: int
    donate_argnums: tuple = ()  # the DonationPlan argnums lowered under
    pinned_live: tuple = ()  # "describe — reason" rows the plan pins

    @property
    def donation_covered(self) -> bool:
        return not self.undonated_large


def _walk_jaxpr(jaxpr, counts: dict) -> None:
    """Recursively count primitives of interest through every nested
    jaxpr (pjit bodies, scan/while carries, cond branches, custom-call
    wrappers)."""
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "convert_element_type":
            src = eqn.invars[0].aval
            dst = eqn.outvars[0].aval
            if dst.dtype.itemsize > src.dtype.itemsize:
                counts["convert_widenings"] += 1
        elif name == "device_put":
            counts["device_puts"] += 1
        elif name == "pallas_call":
            counts["pallas_calls"] += 1
        for sub in eqn.params.values():
            if hasattr(sub, "jaxpr"):  # ClosedJaxpr
                _walk_jaxpr(sub.jaxpr, counts)
            elif hasattr(sub, "eqns"):  # raw Jaxpr
                _walk_jaxpr(sub, counts)
            elif isinstance(sub, (tuple, list)):
                for item in sub:
                    if hasattr(item, "jaxpr"):
                        _walk_jaxpr(item.jaxpr, counts)
                    elif hasattr(item, "eqns"):
                        _walk_jaxpr(item, counts)


def walk_counts(fn, *args) -> dict:
    """Primitive counts of interest for ``fn`` traced at ``args``
    (abstract or concrete)."""
    import jax

    counts = {"convert_widenings": 0, "device_puts": 0, "pallas_calls": 0}
    closed = jax.make_jaxpr(fn)(*args)
    _walk_jaxpr(closed.jaxpr, counts)
    return counts


def buffer_infos(fn, *args, donate_argnums=()) -> list:
    """Flattened :class:`BufferInfo` rows for ``fn`` lowered at
    ``args`` — donation read back from the lowering itself
    (``Lowered.args_info``), not from the caller's intent, so a
    donation the platform rejects reads as not donated."""
    import warnings

    import jax
    import numpy as np

    with warnings.catch_warnings():
        # CPU rejects some donations with a UserWarning; the audit's
        # whole point is to REPORT that state, not to spam stderr.
        warnings.simplefilter("ignore")
        lowered = jax.jit(fn, donate_argnums=donate_argnums).lower(*args)
    infos = []
    for i, leaf in enumerate(jax.tree_util.tree_leaves(lowered.args_info)):
        # jax.stages.ArgInfo spells the aval field `aval` in newer
        # releases and `_aval` in 0.4.x; accept both.
        aval = getattr(leaf, "aval", None) or leaf._aval
        nbytes = int(np.prod(aval.shape, dtype=np.int64)) * aval.dtype.itemsize
        infos.append(
            BufferInfo(
                index=i,
                shape=tuple(aval.shape),
                dtype=str(aval.dtype),
                nbytes=nbytes,
                donated=bool(leaf.donated),
            )
        )
    return infos


def _plan_for(fn):
    """The DonationPlan entry governing ``fn`` (a body callable or a
    functools.partial of one), or None when the callable sits outside
    the plan's module-level scope (function-local jits: the shard_map
    per-shard fn, the pallas pair scorer)."""
    from .dataflow import donation_plan

    name = getattr(getattr(fn, "func", fn), "__name__", None)
    return donation_plan().entry_for_body(name) if name else None


def _split_undonated(large, entry_plan):
    """Partition un-donated large buffers into (violations, pinned
    rows): the plan's pinned argnums — and everything on an out-of-plan
    entry — are listed with their reason instead of failing the gate."""
    undonated = [i for i in large if not i.donated]
    if entry_plan is None:
        return (), tuple(
            f"{i.describe()} — no module-level donation plan entry "
            "(function-local jit)"
            for i in undonated
        )
    pins = {p.argnum: p for p in entry_plan.pinned}
    violations, pinned = [], []
    for info in undonated:
        pin = pins.get(info.index)
        if pin is not None:
            pinned.append(f"{info.describe()} — {pin.reason}")
        else:
            violations.append(info)
    return tuple(violations), tuple(pinned)


def trace_entry(
    contract, bucket, threshold: int = LARGE_BUFFER_BYTES
) -> EntryTraceReport:
    """Lower one :class:`~.contracts.EntryContract` at one audit bucket
    — under the DonationPlan's argnums when the body has a plan entry —
    and collect its :class:`EntryTraceReport`."""
    b, nc, l1p, l2p = bucket
    fn, args = contract.make(b, nc, l1p, l2p)
    entry_plan = _plan_for(fn)
    donate = entry_plan.donate if entry_plan is not None else ()
    try:
        infos = buffer_infos(fn, *args, donate_argnums=donate)
        counts = walk_counts(fn, *args)
    except Exception as exc:  # noqa: BLE001 - re-raise with context
        raise TraceAuditError(
            f"{contract.name} failed to lower at bucket (b={b}, nc={nc}, "
            f"l1p={l1p}, l2p={l2p}): {exc!r}"
        ) from exc
    large = tuple(i for i in infos if i.nbytes >= threshold)
    violations, pinned = _split_undonated(large, entry_plan)
    return EntryTraceReport(
        entry=contract.name,
        bucket=tuple(bucket),
        n_args=len(infos),
        large_buffers=large,
        undonated_large=violations,
        convert_widenings=counts["convert_widenings"],
        device_puts=counts["device_puts"],
        pallas_calls=counts["pallas_calls"],
        donate_argnums=tuple(donate),
        pinned_live=pinned,
    )


def audit_entry_points(buckets=None, threshold: int = LARGE_BUFFER_BYTES):
    """Lower every registered entry point over the audit buckets and
    return the :class:`EntryTraceReport` rows.  Raises
    :class:`TraceAuditError` if any entry fails to lower, or if an
    entry claims device residency but emits host transfers
    (``device_put`` inside a chunk body)."""
    from .contracts import _AUDIT_BUCKETS, ENTRY_CONTRACTS

    if buckets is None:
        buckets = _AUDIT_BUCKETS
    reports = []
    for contract in ENTRY_CONTRACTS:
        for bucket in buckets:
            rep = trace_entry(contract, bucket, threshold=threshold)
            if rep.device_puts:
                raise TraceAuditError(
                    f"{rep.entry} lowers with {rep.device_puts} device_put "
                    f"equation(s) at bucket {rep.bucket}: chunk bodies must "
                    "be device-resident — hoist the transfer to the "
                    "dispatch boundary (ops/dispatch.py)"
                )
            if rep.undonated_large:
                rows = "; ".join(i.describe() for i in rep.undonated_large)
                raise TraceAuditError(
                    f"{rep.entry} at bucket {rep.bucket} has "
                    f"{len(rep.undonated_large)} un-donated large "
                    f"buffer(s) the DonationPlan neither donates nor pins "
                    f"live: {rows} — extend analysis/dataflow.py's plan "
                    "(donate it if provably dead, pin it with a reason if "
                    "not) rather than relaxing this gate"
                )
            reports.append(rep)
    return reports


def audit_schedule(problem, backend: str = "pallas") -> dict:
    """Trace-audit the COMPOSED schedule: every launch group's resolved
    body is traced at its production chunk shapes, and the LAUNCH-BUDGET
    gate holds the lowering to the fusion planner's declaration — the
    schedule must lower to EXACTLY ``FusedScheduleConfig
    .declared_launches`` ``pallas_call`` launches (r6; supersedes the
    per-bucket one-launch gate, which the fused schedule satisfies as a
    corollary: one call per chunk per group).  A lowering that de-fuses
    (extra calls per chunk) or silently re-splits the grid fails here
    before hardware ever sees it.  Donation coverage is reported for the
    chunk-pipeline operands.  Returns a JSON-ready dict."""
    import jax
    import numpy as np

    from ..ops.schedule import (
        fused_schedule_config,
        kernel_configs,
        production_schedule,
    )

    _, sched = production_schedule(problem, backend)
    cfgs = kernel_configs(problem, backend, buckets=True)
    declared = fused_schedule_config(problem, backend).declared_launches
    rows = []
    total_large = 0
    total_donated = 0
    actual_launches = 0  # traced pallas_calls x chunks, aligned groups
    budgeted_launches = 0  # chunks of the aligned groups (1 call each)
    all_pinned: list = []
    for i, part in enumerate(sched):
        batch = part["batch"]
        body = part["body"]
        rows_arr = np.asarray(part["rows"])
        lens_arr = np.asarray(part["lens"])
        nc, cb, l2p = rows_arr.shape
        # The production pipeline (io/pipeline.py) dispatches chunk by
        # chunk: trace the body at the single-chunk invocation shape,
        # so "pallas calls per chunk" x n_chunks is the schedule's
        # static launch count.
        args = (
            jax.ShapeDtypeStruct(
                np.asarray(batch.seq1ext).shape,
                np.asarray(batch.seq1ext).dtype,
            ),
            jax.ShapeDtypeStruct((), np.int32),
            jax.ShapeDtypeStruct((1, cb, l2p), np.int32),
            jax.ShapeDtypeStruct((1, cb), np.int32),
            jax.ShapeDtypeStruct((27 * 27,), np.int32),
        )
        entry_plan = _plan_for(body)
        donate = entry_plan.donate if entry_plan is not None else ()
        try:
            counts = walk_counts(body, *args)
            infos = buffer_infos(body, *args, donate_argnums=donate)
        except Exception as exc:  # noqa: BLE001 - re-raise with context
            raise TraceAuditError(
                f"schedule bucket {i} (l1p={batch.l1p}, l2p={batch.l2p}, "
                f"cb={cb}) failed to lower: {exc!r}"
            ) from exc
        aligned = batch.l1p % 128 == 0 and batch.l2p % 128 == 0
        if aligned and backend == "pallas":
            actual_launches += nc * counts["pallas_calls"]
            budgeted_launches += nc
        large = [b for b in infos if b.nbytes >= LARGE_BUFFER_BYTES]
        violations, pinned = _split_undonated(large, entry_plan)
        if violations:
            vrows = "; ".join(v.describe() for v in violations)
            raise TraceAuditError(
                f"schedule bucket {i} (l1p={batch.l1p}, l2p={batch.l2p}) "
                f"has {len(violations)} un-donated large buffer(s) the "
                f"DonationPlan neither donates nor pins live: {vrows} — "
                "extend analysis/dataflow.py's plan rather than relaxing "
                "this gate"
            )
        total_large += len(large)
        total_donated += sum(1 for b in large if b.donated)
        all_pinned.extend(pinned)
        rows.append(
            {
                "bucket": i,
                "l1p": int(batch.l1p),
                "l2p": int(batch.l2p),
                "cb": int(cb),
                "chunks": int(nc),
                "pallas_calls_per_chunk": counts["pallas_calls"],
                "convert_widenings": counts["convert_widenings"],
                "device_puts": counts["device_puts"],
                "large_buffers": len(large),
                "donate_argnums": list(donate),
                "undonated_large_buffers": [
                    v.describe() for v in violations
                ],
                "pinned_live": list(pinned),
            }
        )
        del lens_arr
    # The launch-budget gate (r6): the lowered schedule must spend
    # EXACTLY the launch count the fusion planner declared — one
    # pallas_call per chunk per launch group.  More means a group
    # de-fused or re-split in lowering; fewer means the trace walk went
    # blind.  Fix the plan or the kernel, never this gate (and the
    # committed golden is REGENERATED on deliberate schedule changes,
    # not loosened).
    if backend == "pallas" and actual_launches != budgeted_launches:
        raise TraceAuditError(
            f"schedule lowers to {actual_launches} pallas_call "
            f"launch(es) against a launch budget of {budgeted_launches} "
            f"(fused schedule declares {declared}): a launch group "
            "de-fused or re-split in lowering — update the fusion plan "
            "(ops/schedule.plan_fusion_groups) and regenerate the "
            "golden in lockstep"
        )
    executables = (
        len({c.cache_key for c in cfgs}) if cfgs is not None else len(sched)
    )
    return {
        "backend": backend,
        "buckets": rows,
        "executables": executables,
        "launches": int(sum(r["chunks"] for r in rows)),
        "declared_launches": int(declared),
        "donation": {
            "large_buffers": total_large,
            "donated_large_buffers": total_donated,
            "undonated_large_buffers": total_large - total_donated
            - len(all_pinned),
            "pinned_live": list(all_pinned),
            "covered": total_large == total_donated + len(all_pinned),
        },
    }
