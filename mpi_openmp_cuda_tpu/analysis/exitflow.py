"""Failure-path certifier: whole-program exception-flow analysis.

The robustness story is spread over four PRs — the retry taxonomy
(``resilience/policy.py``: ``FATAL_ERROR_TYPES`` propagate, everything
else retries), the sysexits contract (``io/cli.py``: 64 usage / 65
fatal / 75 resumable), the finally-first flush (every exit path leaves
the run report behind), and the typed serve wire errors
(``{"id","error"}`` replies) — but until this pass it was enforced
only by *sampled* chaos runs.  This module makes it a static theorem
over the package AST, the eighth analysis tier:

1. **Propagation graph.**  Every ``raise`` site, every
   ``try/except/finally``, and the intra-package call graph (reusing
   :mod:`.lockgraph`'s module index and call resolution; lambdas and
   nested defs are walked as their own nodes with closure-aware
   higher-order edges, the :mod:`.dataflow` trick, because the retry
   plane invokes them under *its* handlers, not their definer's).
2. **Sink proof.**  Each production-reachable raise site's exception
   is walked up the graph — through matching handlers, re-raises and
   ``raise X from e`` chains — until it terminates in a legal sink:
   the RetryPolicy ladder (``retry-policy``), a serve wire-error reply
   or quarantine route (``wire-reply``), the CLI sysexits map
   (``exit-map``), a reasoned ``# advisory:`` swallow marker
   (``advisory``), or a typed narrow handler (``handled``).  A path
   that escapes the root without a classifier is an
   ``unclassified-raise`` finding; a broad handler that swallows
   without a marker is ``swallow-unmarked``; a handler arm shadowed by
   an earlier broader arm is ``double-classified``.
3. **Flush contract.**  In ``io/cli.py`` and ``serve/loop.py``, every
   exit statement of the driver function must sit inside the try whose
   ``finally`` performs the terminal metrics/trace flush (pre-arm
   usage returns excepted), or it is a ``flush-bypass`` finding; and
   exit 75 (``EX_TEMPFAIL``) must be reachable only from a
   ``DrainInterrupt`` handler or an ``_is_resumable``-style
   cause-chain predicate rooted in deadline/drain types
   (``tempfail-unrooted`` otherwise).
4. **Fault registry cross-check.**  Every site name in
   ``resilience/faults.py`` (including the ``hang:``/``kill:``
   survival aliases) must still name a fire point the production graph
   reaches — a renamed site can never silently make ``make chaos``
   vacuous (``fault-site-unreachable``).

``run_or_raise`` raises :class:`.ExitFlowError` on any finding;
``scripts/exitpath_audit.py`` diffs the report against the committed
golden (``make exitpath-audit``).
"""

from __future__ import annotations

import ast
import builtins
import dataclasses
import re
from pathlib import Path

from . import ExitFlowError
from .lockgraph import _index_module, _package_files, _resolve_call

# -- taxonomy --------------------------------------------------------------

#: Legal sink kinds, most specific classifier first: a site whose paths
#: reach several sinks reports the highest-priority one as primary.
SINK_PRIORITY = (
    "retry-policy",
    "wire-reply",
    "exit-map",
    "advisory",
    "handled",
    "swallow",
    "import-time",
    "out-of-plane",
)

#: The reasoned-swallow marker: ``# advisory: <why this may be dropped>``.
#: A bare marker (no reason text) does not count (seqlint SEQ014 flags it).
_ADVISORY_RE = re.compile(r"#\s*advisory:\s*(.*\S)?")

#: Names whose presence in a cli handler body marks the sysexits map.
_EXIT_NAMES = {"EX_OK", "EX_USAGE", "EX_FATAL", "EX_TEMPFAIL"}
_EXIT_CODES = {0, 1, 2, 64, 65, 75}

#: Calls whose presence in a serve-plane handler body marks the typed
#: wire-error reply / quarantine route.
_WIRE_CALLS = {"_block_failed", "_bisect", "_score_block_sync", "fail", "send"}

#: Calls that constitute the finally-first flush (cli and serve teardown).
_FLUSH_CALLS = {"flush_run_report", "flush_trace", "record_steady_gauge"}

#: Exception types that legally root an exit-75 (resumable) mapping.
_RESUMABLE_ROOTS = {"DeadlineExpiredError", "DrainInterrupt"}

#: Exit-code constant names legal on a pre-arm (pre-flush-try) return.
_PREARM_OK = {"EX_USAGE", "EX_OK"}
_PREARM_CODES = {0, 64}

#: Fault-registry fire/probe call names (module function + bound aliases).
_FAULT_CALLS = {"fire", "scheduled", "_fault_fire", "_fault_scheduled", "_fault"}

#: Attribute names too generic for the last-segment call fallback (they
#: resolve to builtin container/file verbs far more often than package
#: functions; resolving them would drown the graph in bogus edges).
_GENERIC_ATTRS = {
    "append", "add", "get", "pop", "items", "keys", "values", "update",
    "join", "read", "write", "strip", "split", "encode", "decode",
    "sort", "copy", "extend", "format", "count", "index", "close",
}

#: Cap on last-segment fallback candidates: an attr name matching more
#: package functions than this is treated as unresolvable.
_FALLBACK_CAP = 6

# -- data model ------------------------------------------------------------


@dataclasses.dataclass
class Handler:
    """One ``except`` arm with its statically-derived classification."""

    types: tuple  # declared type names after alias expansion; () = bare
    broad: bool  # bare / Exception / BaseException
    line: int
    end: int
    kind: str  # sink kind, "reraise", or "raise-new"
    new_type: str | None = None  # for raise-new
    logs: bool = False
    marker: str | None = None  # advisory reason text (None = no marker)
    binds: str | None = None  # `except X as name` binding


@dataclasses.dataclass
class _TryCtx:
    """One enclosing try whose handlers guard the current position."""

    handlers: list


@dataclasses.dataclass
class RaiseSite:
    exc: str  # type name or "<dynamic>"
    line: int
    ctx: tuple  # innermost-first _TryCtx stack at the raise


@dataclasses.dataclass
class _Func:
    module: str
    qualname: str
    params: frozenset
    parent: tuple | None = None  # definer key for nested defs / lambdas
    def_ctx: tuple = ()  # definer's try stack at the definition site
    raises: list = dataclasses.field(default_factory=list)
    calls: list = dataclasses.field(default_factory=list)  # (desc, line, ctx)
    #: function references passed/registered: (target, receiver, line, ctx)
    #: where target is a func key or a call descriptor and receiver is the
    #: descriptor of the call the reference rides in (None = bare ref).
    refs: list = dataclasses.field(default_factory=list)
    #: calls to closure parameters: (line, ctx) — the higher-order
    #: invocation points (``fn()`` inside RetryPolicy.run).
    param_calls: list = dataclasses.field(default_factory=list)
    tries: list = dataclasses.field(default_factory=list)  # list[list[Handler]]
    returns: list = dataclasses.field(default_factory=list)  # (line, kind)
    hard_exits: list = dataclasses.field(default_factory=list)  # (line, name)
    node: object = None

    def key(self):
        return (self.module, self.qualname)


# -- per-function AST walk -------------------------------------------------


def _type_names(node, aliases):
    """Declared handler type(s) as a flat name tuple (alias-expanded)."""
    if node is None:
        return ()
    items = node.elts if isinstance(node, ast.Tuple) else [node]
    names = []
    for item in items:
        if isinstance(item, ast.Attribute):
            names.append(item.attr)
        elif isinstance(item, ast.Name):
            names.extend(aliases.get(item.id, (item.id,)))
    return tuple(names)


def _walk_no_defs(node):
    """ast.walk that does not descend into nested defs/lambdas."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        sub = stack.pop()
        yield sub
        if not isinstance(
            sub, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            stack.extend(ast.iter_child_nodes(sub))


def _body_walk(body_nodes):
    """Every node in a handler body, including the statements themselves,
    without descending into nested defs/lambdas."""
    for stmt in body_nodes:
        yield stmt
        yield from _walk_no_defs(stmt)


def _call_names(body_nodes):
    """All called names (Name id or Attribute attr) in handler bodies."""
    out = set()
    for stmt in body_nodes:
        for sub in _body_walk([stmt]):
            if isinstance(sub, ast.Call):
                if isinstance(sub.func, ast.Name):
                    out.add(sub.func.id)
                elif isinstance(sub.func, ast.Attribute):
                    out.add(sub.func.attr)
    return out


def _raise_type(node: ast.Raise, binds: dict, classmap) -> str:
    """The (static) exception type a raise statement throws."""
    exc = node.exc
    if isinstance(exc, ast.Call):
        if isinstance(exc.func, ast.Name):
            name = exc.func.id
        elif isinstance(exc.func, ast.Attribute):
            name = exc.func.attr
        else:
            return "<dynamic>"
        if name == "ArgumentTypeError":
            # argparse catches this inside parse_args and performs the
            # usage exit itself: a legal exit-map sink by construction.
            return name
        if name in classmap or isinstance(getattr(builtins, name, None), type):
            return name
        return "<dynamic>"
    if isinstance(exc, ast.Name):
        bound = binds.get(exc.id)
        if bound:
            return bound
        if exc.id in classmap or isinstance(
            getattr(builtins, exc.id, None), type
        ):
            return exc.id
        return "<dynamic>"
    if isinstance(exc, ast.Attribute):
        return exc.attr if exc.attr[:1].isupper() else "<dynamic>"
    return "<dynamic>"


def _in_serve_plane(module: str) -> bool:
    return module.startswith("serve/") or "/serve/" in module


def _classify_handler(h, types, module, qualname, lines, classmap):
    """Map one except arm to its propagation behaviour / sink kind."""
    broad = (not types) or bool(set(types) & {"Exception", "BaseException"})
    end = h.body[-1].end_lineno if h.body else h.lineno
    marker = None
    for ln in lines[h.lineno - 1: end]:
        m = _ADVISORY_RE.search(ln)
        if m:
            marker = (m.group(1) or "").strip() or None
            break
    logs = "log_line" in _call_names(h.body)
    bare_raise = False
    new_type = None
    for sub in _body_walk(h.body):
        if isinstance(sub, ast.Raise):
            if sub.exc is None:
                bare_raise = True
            elif new_type is None:
                new_type = _raise_type(sub, {}, classmap)
    # Classifier recognizers come first: the RetryPolicy ladder's fatal
    # arm re-raises, but *reaching the ladder* is the classification.
    if module.endswith("resilience/policy.py") and qualname.startswith(
        "RetryPolicy."
    ):
        kind = "retry-policy"
    elif _in_serve_plane(module) and (_call_names(h.body) & _WIRE_CALLS):
        kind = "wire-reply"
    elif module.endswith("io/cli.py") and _is_exit_map(h):
        kind = "exit-map"
    elif bare_raise:
        kind = "reraise"
    elif new_type is not None:
        kind = "raise-new"
    elif marker is not None:
        kind = "advisory"
    elif not broad:
        kind = "handled"
    else:
        kind = "swallow"
    return Handler(
        types=types,
        broad=broad,
        line=h.lineno,
        end=end,
        kind=kind,
        new_type=new_type,
        logs=logs,
        marker=marker,
        binds=h.name,
    )


def _is_exit_map(h: ast.ExceptHandler) -> bool:
    for sub in _body_walk(h.body):
        if isinstance(sub, ast.Name) and sub.id in _EXIT_NAMES:
            return True
        if (
            isinstance(sub, ast.Return)
            and isinstance(sub.value, ast.Constant)
            and not isinstance(sub.value.value, bool)
            and sub.value.value in _EXIT_CODES
        ):
            return True
    return False


def _arg_names(args: ast.arguments) -> set:
    params = {a.arg for a in args.posonlyargs + args.args + args.kwonlyargs}
    if args.vararg:
        params.add(args.vararg.arg)
    if args.kwarg:
        params.add(args.kwarg.arg)
    return params


class _FnWalker:
    """Walk one function body tracking the enclosing-try stack; nested
    defs and lambdas become their own _Func nodes (they run under
    whatever handlers their *caller* installs — never the definer's)."""

    def __init__(self, module, qualname, params, outer_params, lines,
                 aliases, classmap, out):
        self.fn = _Func(module, qualname, frozenset(params) | outer_params)
        self.lines = lines
        self.aliases = aliases
        self.classmap = classmap
        self.out = out
        self.local_defs = {}  # nested def name -> func key
        self.binds = {}  # except-binding name -> type name
        out[self.fn.key()] = self.fn

    # -- statements --------------------------------------------------------

    def walk(self, body, ctx=()):
        for stmt in body:
            self._stmt(stmt, ctx)

    def _stmt(self, node, ctx):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            key = self._child(node.name, node.args, node.body, ctx)
            self.local_defs[node.name] = key
            return
        if isinstance(node, ast.ClassDef):
            return  # nested classes are out of the failure plane
        if isinstance(node, ast.Try):
            handlers = []
            for h in node.handlers:
                types = _type_names(h.type, self.aliases)
                handlers.append(
                    _classify_handler(
                        h, types, self.fn.module, self.fn.qualname,
                        self.lines, self.classmap,
                    )
                )
            self.fn.tries.append(handlers)
            tc = _TryCtx(handlers)
            self.walk(node.body, (tc,) + ctx)
            for h, hd in zip(node.handlers, handlers):
                if h.name and hd.types:
                    self.binds[h.name] = hd.types[0]
                # Handler bodies are guarded by OUTER tries only
                # (sibling arms never catch each other).
                self.walk(h.body, ctx)
                if h.name:
                    self.binds.pop(h.name, None)
            self.walk(node.orelse, ctx)  # else runs after the body succeeded
            self.walk(node.finalbody, ctx)
            return
        if isinstance(node, ast.Raise):
            if node.exc is not None:
                exc = _raise_type(node, self.binds, self.classmap)
                self.fn.raises.append(RaiseSite(exc, node.lineno, ctx))
            for sub in (node.exc, node.cause):
                if sub is not None:
                    self._expr(sub, ctx)
            return
        if isinstance(node, ast.Return):
            self.fn.returns.append((node.lineno, _return_kind(node.value)))
            if node.value is not None:
                self._expr(node.value, ctx)
            return
        for sub in ast.iter_child_nodes(node):
            if isinstance(sub, ast.expr):
                self._expr(sub, ctx)
            elif isinstance(sub, ast.stmt):
                self._stmt(sub, ctx)
            elif isinstance(sub, (ast.excepthandler, ast.withitem)):
                self._stmt_like(sub, ctx)

    def _stmt_like(self, node, ctx):
        for sub in ast.iter_child_nodes(node):
            if isinstance(sub, ast.expr):
                self._expr(sub, ctx)
            elif isinstance(sub, ast.stmt):
                self._stmt(sub, ctx)

    # -- expressions -------------------------------------------------------

    def _expr(self, node, ctx):
        if isinstance(node, ast.Lambda):
            self._child(f"<lambda>L{node.lineno}", node.args, node.body, ctx)
            return
        if isinstance(node, ast.Call):
            self._call(node, ctx)
            return
        for sub in ast.iter_child_nodes(node):
            if isinstance(sub, ast.expr):
                self._expr(sub, ctx)

    def _call(self, node: ast.Call, ctx):
        desc = _call_desc(node.func)
        if desc is not None:
            if desc[0] == "name" and desc[1] in self.fn.params:
                self.fn.param_calls.append((node.lineno, ctx))
            elif desc in (("mod", "sys", "exit"), ("mod", "os", "_exit")):
                self.fn.hard_exits.append((node.lineno, desc[2]))
            else:
                self.fn.calls.append((desc, node.lineno, ctx))
        if isinstance(node.func, ast.Attribute):
            self._expr(node.func.value, ctx)
        elif not isinstance(node.func, ast.Name):
            self._expr(node.func, ctx)
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            target = self._ref_target(arg, ctx)
            if target is not None:
                self.fn.refs.append((target, desc, node.lineno, ctx))
            else:
                self._expr(arg, ctx)

    def _ref_target(self, arg, ctx):
        """A function-valued argument (the higher-order edge source)."""
        if isinstance(arg, ast.Lambda):
            return self._child(
                f"<lambda>L{arg.lineno}", arg.args, arg.body, ctx
            )
        if isinstance(arg, ast.Name):
            if arg.id in self.local_defs:
                return self.local_defs[arg.id]
            if arg.id not in self.fn.params:
                # Maybe a module-level function passed by name; the
                # resolver decides (plain data names resolve to nothing).
                return ("name", arg.id)
            return None
        if (
            isinstance(arg, ast.Attribute)
            and isinstance(arg.value, ast.Name)
            and arg.value.id == "self"
        ):
            return ("self", arg.attr)
        return None

    def _child(self, name, args, body, ctx):
        w = _FnWalker(
            self.fn.module, f"{self.fn.qualname}.{name}", _arg_names(args),
            self.fn.params, self.lines, self.aliases, self.classmap,
            self.out,
        )
        w.fn.parent = self.fn.key()
        w.fn.def_ctx = ctx
        w.local_defs = dict(self.local_defs)
        if isinstance(body, list):
            w.walk(body)
        else:
            w._expr(body, ())
        return w.fn.key()


def _return_kind(value):
    if value is None:
        return ("none", None)
    if isinstance(value, ast.Name):
        return ("name", value.id)
    if isinstance(value, ast.Constant) and isinstance(value.value, int):
        return ("const", value.value)
    return ("expr", None)


def _call_desc(func):
    """Call descriptor compatible with lockgraph._resolve_call."""
    if isinstance(func, ast.Name):
        return ("name", func.id)
    if isinstance(func, ast.Attribute):
        base = func.value
        if isinstance(base, ast.Name) and base.id == "self":
            return ("self", func.attr)
        if (
            isinstance(base, ast.Attribute)
            and isinstance(base.value, ast.Name)
            and base.value.id == "self"
        ):
            return ("selfattr", base.attr, func.attr)
        if isinstance(base, ast.Name):
            return ("mod", base.id, func.attr)
        return ("varattr", "<expr>", func.attr)
    return None


def _tuple_aliases(tree: ast.Module) -> dict:
    """Module-level ``FATAL_ERROR_TYPES = (ValueError, TypeError)``-style
    exception-tuple constants, expanded at handler-type resolution."""
    out = {}
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Tuple)
        ):
            names = [
                e.id for e in node.value.elts if isinstance(e, ast.Name)
            ]
            if names and all(n[:1].isupper() for n in names):
                out[node.targets[0].id] = tuple(names)
    return out


# -- package graph ---------------------------------------------------------


class _Graph:
    """Parsed package: func table, indexes, class hierarchy, edges."""

    def __init__(self, package_root: str | Path | None = None):
        if package_root is None:
            package_root = Path(__file__).resolve().parent.parent
        self.root = Path(package_root)
        self.funcs: dict = {}
        self.indexes: dict = {}
        self.classes: dict = {}  # class name -> (module, _ClassInfo)
        self.classmap: dict = {}  # class name -> tuple of base names
        self.module_raises: dict = {}  # rel -> import-time raise count
        self.sources: dict = {}  # rel -> source lines
        self.trees: dict = {}  # rel -> parsed module
        self.files = 0
        self._parse()
        self._index_edges()

    def _parse(self):
        for path, rel in _package_files(self.root):
            try:
                text = path.read_text()
                tree = ast.parse(text, filename=str(path))
            except (SyntaxError, OSError):
                continue  # seqlint owns syntax errors
            self.files += 1
            lines = text.splitlines()
            self.sources[rel] = lines
            self.trees[rel] = tree
            self.indexes[rel] = _index_module(rel, tree)
            aliases = _tuple_aliases(tree)
            for node in ast.walk(tree):
                if isinstance(node, ast.ClassDef):
                    self.classmap[node.name] = tuple(
                        b.attr if isinstance(b, ast.Attribute) else b.id
                        for b in node.bases
                        if isinstance(b, (ast.Name, ast.Attribute))
                    )
            for cname, cinfo in self.indexes[rel].classes.items():
                self.classes[cname] = (rel, cinfo)
            for node in tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._walk_fn(rel, node.name, node, lines, aliases)
                elif isinstance(node, ast.ClassDef):
                    for stmt in node.body:
                        if isinstance(
                            stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ):
                            self._walk_fn(
                                rel, f"{node.name}.{stmt.name}", stmt,
                                lines, aliases,
                            )
                else:
                    # Import-time raises (module-body guards) are a
                    # legal fail-fast sink of their own.
                    n = sum(
                        1
                        for sub in _walk_no_defs(node)
                        if isinstance(sub, ast.Raise) and sub.exc is not None
                    )
                    if isinstance(node, ast.Raise) and node.exc is not None:
                        n += 1
                    if n:
                        self.module_raises[rel] = (
                            self.module_raises.get(rel, 0) + n
                        )

    def _walk_fn(self, rel, qualname, node, lines, aliases):
        w = _FnWalker(
            rel, qualname, _arg_names(node.args), frozenset(), lines,
            aliases, self.classmap, self.funcs,
        )
        w.fn.node = node
        w.walk(node.body)

    # -- resolution --------------------------------------------------------

    def resolve(self, desc, module, qualname):
        """Resolve a call/ref descriptor to candidate func keys."""
        if (
            isinstance(desc, tuple)
            and len(desc) == 2
            and desc in self.funcs
        ):
            return [desc]  # already a key (lambda / nested def)
        if desc[0] == "name":
            # Nested-def scoping: resolve through the enclosing chain.
            parts = qualname.split(".")
            for i in range(len(parts), 0, -1):
                key = (module, ".".join(parts[:i] + [desc[1]]))
                if key in self.funcs:
                    return [key]
        got = _resolve_call(
            desc, module, qualname, self.indexes, self.classes, self.funcs
        )
        if got is not None:
            return [got]
        # Last-segment fallback for dynamic receivers (``dist.broadcast``,
        # ``loop.tick``): honest over-approximation, capped, with the
        # builtin container verbs excluded.
        attr = None
        if desc[0] in ("varattr", "mod"):
            attr = desc[2]
        elif desc[0] in ("self", "selfattr"):
            attr = desc[-1]
        if attr and attr not in _GENERIC_ATTRS and not attr.startswith("__"):
            cands = self._lastseg.get(attr, [])
            if 0 < len(cands) <= _FALLBACK_CAP:
                return list(cands)
        return []

    def _index_edges(self):
        self._lastseg = {}
        for key in self.funcs:
            seg = key[1].rsplit(".", 1)[-1]
            self._lastseg.setdefault(seg, []).append(key)
        #: callers[key] -> list of (caller key, line, ctx) frames.
        self.callers = {}
        #: forward adjacency for reachability.
        self.forward = {}
        self.retry_run = sorted(
            k
            for k in self.funcs
            if k[0].endswith("resilience/policy.py")
            and k[1].startswith("RetryPolicy.run")
        )
        for fn in self.funcs.values():
            fkey = fn.key()
            if fn.parent is not None:
                # Definition edge: production reach flows definer ->
                # closure, but adds no caller frame (invocation frames
                # come from the pass sites / receivers below).
                self.forward.setdefault(fn.parent, set()).add(fkey)
            for desc, line, ctx in fn.calls:
                for tkey in self.resolve(desc, fn.module, fn.qualname):
                    self.forward.setdefault(fkey, set()).add(tkey)
                    self.callers.setdefault(tkey, []).append(
                        (fkey, line, ctx)
                    )
            for target, receiver, line, ctx in fn.refs:
                for tkey in self.resolve(target, fn.module, fn.qualname):
                    self.forward.setdefault(fkey, set()).add(tkey)
                    self.callers.setdefault(tkey, []).extend(
                        self._invocation_frames(receiver, fn, line, ctx)
                    )

    def _invocation_frames(self, receiver, fn, line, ctx):
        """Where a passed function reference is actually invoked: the
        receiver's parameter-call sites when known (``fn()`` inside
        RetryPolicy.run), the retry ladder when the receiver forwards
        into it (run_degrading), else the pass site itself (the
        registration-point approximation for signal handlers and thread
        targets)."""
        if receiver is not None:
            cands = self.resolve(receiver, fn.module, fn.qualname)
            frames = []
            for ckey in cands:
                cfn = self.funcs[ckey]
                frames.extend(
                    (ckey, ln, cctx) for ln, cctx in cfn.param_calls
                )
            if frames:
                return frames
            names = {c[1].rsplit(".", 1)[-1] for c in cands}
            if "run_degrading" in names or receiver[-1] == "run_degrading":
                frames = [
                    (rkey, ln, cctx)
                    for rkey in self.retry_run
                    for ln, cctx in self.funcs[rkey].param_calls
                ]
                if frames:
                    return frames
        return [(fn.key(), line, ctx)]

    # -- reachability ------------------------------------------------------

    def roots(self):
        keys = []
        for mod, names in (
            ("io/cli.py", ("main", "run")),
            ("serve/loop.py", ("run_serve",)),
            ("serve/fleet.py", ("run_fleet_worker",)),
        ):
            for key in self.funcs:
                if key[0].endswith(mod) and key[1] in names:
                    keys.append(key)
        if not keys:
            keys = sorted(k for k in self.funcs if k[1] == "main")
        return keys

    def production_set(self):
        seen = set(self.roots())
        stack = list(seen)
        while stack:
            key = stack.pop()
            for nxt in self.forward.get(key, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append(nxt)
        return seen


# -- exception hierarchy ---------------------------------------------------


def _ancestors(name: str, classmap: dict) -> list:
    seen: list = []
    queue = [name]
    while queue:
        n = queue.pop(0)
        if n in seen:
            continue
        seen.append(n)
        queue.extend(classmap.get(n, ()))
    return seen


def _is_subtype(exc: str, target: str, classmap: dict) -> bool:
    for a in _ancestors(exc, classmap):
        if a == target:
            return True
        A = getattr(builtins, a, None)
        T = getattr(builtins, target, None)
        if isinstance(A, type) and isinstance(T, type):
            try:
                if issubclass(A, T):
                    return True
            except TypeError:  # advisory: non-class builtin shadowing a name
                pass
    return False


def _base_only(exc: str, classmap: dict) -> bool:
    """True when ``exc`` derives from BaseException but not Exception
    (DrainInterrupt / KeyboardInterrupt: must sail past ``except
    Exception`` nets)."""
    for a in _ancestors(exc, classmap):
        A = getattr(builtins, a, None)
        if isinstance(A, type) and issubclass(A, BaseException):
            return not issubclass(A, Exception)
    return False  # unplaceable types default to Exception-derived


def _matches(exc: str, handler: Handler, classmap: dict) -> bool:
    if not handler.types or "BaseException" in handler.types:
        return True
    if "Exception" in handler.types:
        return exc == "<dynamic>" or not _base_only(exc, classmap)
    if exc == "<dynamic>":
        return False
    return any(_is_subtype(exc, t, classmap) for t in handler.types)


# -- sink-proof walk -------------------------------------------------------

_WALK_CAP = 40000  # frames per site; a backstop, never hit in practice


def _classify_site(graph: _Graph, key, site: RaiseSite, production: set):
    """All sinks (and root escapes) one raise site's exception reaches."""
    sinks: set = set()
    escapes: list = []
    seen = set()
    stack = [(key, site.exc, site.ctx)]
    budget = _WALK_CAP
    while stack and budget:
        budget -= 1
        fkey, exc, ctx = stack.pop()
        mark = (fkey, exc, tuple(id(c) for c in ctx))
        if mark in seen:
            continue
        seen.add(mark)
        caught = False
        for i, tc in enumerate(ctx):
            hit = None
            for handler in tc.handlers:
                if _matches(exc, handler, graph.classmap):
                    hit = handler
                    break
            if hit is None:
                continue
            if hit.kind == "reraise":
                stack.append((fkey, exc, ctx[i + 1:]))
            elif hit.kind == "raise-new":
                stack.append(
                    (fkey, hit.new_type or "<dynamic>", ctx[i + 1:])
                )
            else:
                sinks.add(hit.kind)
            caught = True
            break
        if caught:
            continue
        # Escaped the function: continue up through production callers;
        # a frameless closure escapes through its definition site.
        frames = [
            f for f in graph.callers.get(fkey, []) if f[0] in production
        ]
        if not frames:
            parent = graph.funcs[fkey].parent
            if parent is not None and parent in production:
                stack.append((parent, exc, graph.funcs[fkey].def_ctx))
            else:
                escapes.append(f"{fkey[0]}:{fkey[1]}")
            continue
        for ckey, _line, cctx in frames:
            stack.append((ckey, exc, cctx))
    return sinks, escapes


# -- flush / exit-75 contract ---------------------------------------------


def _flush_try(fn: _Func):
    """The try statement whose finally performs the terminal flush."""
    if fn.node is None:
        return None
    for sub in _walk_no_defs(fn.node):
        if isinstance(sub, ast.Try) and sub.finalbody:
            called = set()
            for stmt in sub.finalbody:
                for c in ast.walk(stmt):
                    if isinstance(c, ast.Call):
                        if isinstance(c.func, ast.Attribute):
                            called.add(c.func.attr)
                        elif isinstance(c.func, ast.Name):
                            called.add(c.func.id)
            if called & _FLUSH_CALLS:
                return sub.lineno, sub.finalbody[-1].end_lineno, sorted(
                    called & _FLUSH_CALLS
                )
    return None


def _check_flush(graph: _Graph, findings: list) -> dict:
    """Every exit statement in the cli/serve drivers must pass through
    the finally-first flush (pre-arm usage returns excepted)."""
    out = {}
    for mod, fname in (("io/cli.py", "run"), ("serve/loop.py", "run_serve")):
        fn = next(
            (
                f
                for k, f in graph.funcs.items()
                if k[0].endswith(mod) and k[1] == fname
            ),
            None,
        )
        if fn is None:
            continue
        rel = fn.module
        span = _flush_try(fn)
        if span is None:
            findings.append(
                {
                    "kind": "flush-bypass",
                    "module": rel,
                    "line": fn.node.lineno if fn.node else 0,
                    "detail": f"{fname}() has no finally-first flush block",
                }
            )
            continue
        lo, hi, calls = span
        protected = 0
        for line, rk in fn.returns:
            if lo <= line <= hi:
                protected += 1
                continue
            if line < lo and (
                (rk[0] == "name" and rk[1] in _PREARM_OK)
                or (rk[0] == "const" and rk[1] in _PREARM_CODES)
            ):
                continue  # pre-arm usage exit: nothing armed to flush yet
            findings.append(
                {
                    "kind": "flush-bypass",
                    "module": rel,
                    "line": line,
                    "detail": (
                        f"{fname}() returns outside the flush try "
                        f"(lines {lo}-{hi})"
                    ),
                }
            )
        for line, name in fn.hard_exits:
            if not lo <= line <= hi:
                findings.append(
                    {
                        "kind": "flush-bypass",
                        "module": rel,
                        "line": line,
                        "detail": (
                            f"{fname}() calls {name}() outside the "
                            "flush try"
                        ),
                    }
                )
        out[rel] = {
            "function": fname,
            "flush_try": [lo, hi],
            "flush_calls": calls,
            "protected_returns": protected,
        }
    return out


def _resumable_predicates(graph: _Graph) -> set:
    """cli-module functions whose body walks the ``__cause__`` /
    ``__context__`` chain AND names a deadline/drain root type — the
    only predicates allowed to gate an exit-75."""
    out = set()
    for key, fn in graph.funcs.items():
        if not key[0].endswith("io/cli.py") or fn.node is None:
            continue
        attrs = set()
        names = set()
        for sub in ast.walk(fn.node):
            if isinstance(sub, ast.Attribute):
                attrs.add(sub.attr)
            elif isinstance(sub, ast.Name):
                names.add(sub.id)
        if {"__cause__", "__context__"} <= attrs and (
            names & _RESUMABLE_ROOTS
        ):
            out.add(key[1].rsplit(".", 1)[-1])
    return out


def _check_exit75(graph: _Graph, findings: list) -> None:
    """EX_TEMPFAIL (75) may be produced only under a DrainInterrupt
    handler or behind a resumable-cause predicate."""
    preds = _resumable_predicates(graph)
    for key, fn in graph.funcs.items():
        if not key[0].endswith("io/cli.py") or fn.node is None:
            continue
        for sub in _walk_no_defs(fn.node):
            is75 = (
                isinstance(sub, ast.Name)
                and sub.id == "EX_TEMPFAIL"
                and isinstance(sub.ctx, ast.Load)
            )
            if not is75:
                continue
            if _legal_75(fn.node, sub, preds, graph.classmap):
                continue
            findings.append(
                {
                    "kind": "tempfail-unrooted",
                    "module": key[0],
                    "line": sub.lineno,
                    "detail": (
                        f"{key[1]} maps exit 75 outside a DrainInterrupt "
                        "handler / resumable-cause predicate"
                    ),
                }
            )


def _legal_75(fn_node, node, preds, classmap) -> bool:
    """Is this EX_TEMPFAIL load inside a legal resumable context?"""
    path = _path_to(fn_node, node)
    if path is None:
        return False
    for anc in path:
        if isinstance(anc, ast.ExceptHandler):
            for t in _type_names(anc.type, {}):
                if t in _RESUMABLE_ROOTS or any(
                    a in _RESUMABLE_ROOTS for a in _ancestors(t, classmap)
                ):
                    return True
        if isinstance(anc, (ast.If, ast.IfExp)) and _calls_pred(
            anc.test, preds
        ):
            return True
    return False


def _calls_pred(test, preds) -> bool:
    for sub in ast.walk(test):
        if isinstance(sub, ast.Call):
            name = None
            if isinstance(sub.func, ast.Name):
                name = sub.func.id
            elif isinstance(sub.func, ast.Attribute):
                name = sub.func.attr
            if name in preds:
                return True
    return False


def _path_to(root, target):
    """Ancestor chain (outermost-first) from root down to target."""
    path: list = []

    def visit(node):
        if node is target:
            return True
        for sub in ast.iter_child_nodes(node):
            path.append(node)
            if visit(sub):
                return True
            path.pop()
        return False

    return path if visit(root) else None


# -- fault-registry cross-check -------------------------------------------


def _fault_registry(graph: _Graph):
    """Statically read KNOWN_SITES and the hang/kill alias maps out of
    the analysed package's resilience/faults.py."""
    rel = next(
        (r for r in graph.trees if r.endswith("resilience/faults.py")),
        None,
    )
    if rel is None:
        return None
    sites: set = set()
    aliases: dict = {}  # base fire-point name -> alias site
    for node in graph.trees[rel].body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        if tgt.id in ("KNOWN_SITES", "SERVE_SITES", "FLEET_SITES"):
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Constant) and isinstance(
                    sub.value, str
                ):
                    sites.add(sub.value)
        elif tgt.id in ("_HANG_SITES", "_KILL_SITES") and isinstance(
            node.value, ast.Dict
        ):
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) and isinstance(
                    v, ast.Constant
                ):
                    aliases[str(k.value)] = str(v.value)
    return rel, sites, aliases


def _collect_fault_points(graph: _Graph) -> dict:
    """Every literal ``fire('<site>')``-family call in the package,
    attributed to its enclosing top-level function (module-level fire
    points attribute to None = import-time, always live)."""
    spans: dict = {}
    for key, fn in graph.funcs.items():
        if fn.node is not None:
            spans.setdefault(key[0], []).append(
                (fn.node.lineno, fn.node.end_lineno or fn.node.lineno, key)
            )
    points: dict = {}
    for rel, tree in graph.trees.items():
        owners = spans.get(rel, [])
        for sub in ast.walk(tree):
            if not isinstance(sub, ast.Call) or not sub.args:
                continue
            name = None
            if isinstance(sub.func, ast.Name):
                name = sub.func.id
            elif isinstance(sub.func, ast.Attribute):
                name = sub.func.attr
            if name not in _FAULT_CALLS:
                continue
            arg = sub.args[0]
            if not (
                isinstance(arg, ast.Constant) and isinstance(arg.value, str)
            ):
                continue
            owner = None
            for lo, hi, key in owners:
                if lo <= sub.lineno <= hi:
                    owner = key
                    break
            points.setdefault(arg.value, []).append((rel, sub.lineno, owner))
    return points


def _fault_reachable(owner, production: set) -> bool:
    if owner is None:
        return True  # module-level fire point: import-time
    if owner in production:
        return True
    # Fire points inside closures count through a production definer.
    return any(
        k[0] == owner[0] and k[1].startswith(owner[1] + ".")
        for k in production
    )


def _check_faults(graph: _Graph, production: set, findings: list) -> dict:
    reg = _fault_registry(graph)
    if reg is None:
        return {}
    rel, sites, aliases = reg
    points = _collect_fault_points(graph)
    reachable_points = sum(
        1
        for plist in points.values()
        for (_m, _l, owner) in plist
        if _fault_reachable(owner, production)
    )
    for site in sorted(sites):
        hits = list(points.get(site, []))
        hits.extend(
            p
            for base, alias in aliases.items()
            if alias == site
            for p in points.get(base, [])
        )
        if not hits:
            findings.append(
                {
                    "kind": "fault-site-unreachable",
                    "module": rel,
                    "line": 0,
                    "detail": (
                        f"registry site {site!r} has no fire()/scheduled() "
                        "point anywhere in the package (renamed site? "
                        "make chaos would be vacuous for it)"
                    ),
                }
            )
            continue
        if not any(
            _fault_reachable(owner, production) for (_m, _l, owner) in hits
        ):
            findings.append(
                {
                    "kind": "fault-site-unreachable",
                    "module": rel,
                    "line": hits[0][1],
                    "detail": (
                        f"registry site {site!r} fires only outside the "
                        "production call graph"
                    ),
                }
            )
    return {
        "registered": len(sites),
        "fire_points": sum(len(v) for v in points.values()),
        "reachable_fire_points": reachable_points,
    }


# -- handler hygiene (swallows, shadowed arms) ----------------------------


def _check_handlers(graph: _Graph, findings: list):
    broad = wire = 0
    advisory = []
    for key, fn in sorted(graph.funcs.items()):
        for handlers in fn.tries:
            for j, h in enumerate(handlers):
                if h.broad:
                    broad += 1
                if h.kind == "wire-reply":
                    wire += 1
                if h.marker:
                    advisory.append(f"{key[0]}: {h.marker}")
                if h.kind == "swallow":
                    findings.append(
                        {
                            "kind": "swallow-unmarked",
                            "module": key[0],
                            "line": h.line,
                            "detail": (
                                f"{key[1]} swallows "
                                f"{'/'.join(h.types) or 'everything'} "
                                "without a reasoned '# advisory:' marker"
                                + (" (logs only)" if h.logs else "")
                            ),
                        }
                    )
                # Shadowed arm: an earlier broader arm already claims
                # this arm's type — the exception is double-classified
                # and the later classifier is dead code.
                for earlier in handlers[:j]:
                    if _shadows(earlier, h, graph.classmap):
                        findings.append(
                            {
                                "kind": "double-classified",
                                "module": key[0],
                                "line": h.line,
                                "detail": (
                                    f"{key[1]}: handler for "
                                    f"{'/'.join(h.types) or 'everything'} "
                                    "is shadowed by the broader arm at "
                                    f"line {earlier.line}"
                                ),
                            }
                        )
                        break
    return broad, wire, sorted(advisory)


def _shadows(earlier: Handler, later: Handler, classmap) -> bool:
    if not earlier.types or "BaseException" in earlier.types:
        return True
    if "Exception" in earlier.types:
        if not later.types:
            return False  # bare still catches BaseException kinds
        return all(
            not _base_only(t, classmap)
            and _resolves_as_exception(t, classmap)
            for t in later.types
        )
    if not later.types:
        return False
    return all(
        any(_is_subtype(t, e, classmap) for e in earlier.types)
        for t in later.types
    )


def _resolves_as_exception(name: str, classmap) -> bool:
    """Only shadow-flag types we can actually place in the hierarchy."""
    return any(
        isinstance(getattr(builtins, a, None), type)
        for a in _ancestors(name, classmap)
    )


# -- audit entry points ----------------------------------------------------


def audit_exitflow(package_root: str | Path | None = None) -> dict:
    graph = _Graph(package_root)
    production = graph.production_set()
    findings: list = []

    broad, wire, advisory = _check_handlers(graph, findings)

    sink_counts: dict = {}
    raise_modules: dict = dict(graph.module_raises)
    total = prod_sites = 0
    for key, fn in sorted(graph.funcs.items()):
        for site in fn.raises:
            total += 1
            raise_modules[key[0]] = raise_modules.get(key[0], 0) + 1
            if key not in production:
                sink_counts["out-of-plane"] = (
                    sink_counts.get("out-of-plane", 0) + 1
                )
                continue
            prod_sites += 1
            if site.exc == "ArgumentTypeError":
                # argparse's type= callbacks: parse_args catches the
                # raise and performs the usage exit itself.
                sink_counts["exit-map"] = sink_counts.get("exit-map", 0) + 1
                continue
            sinks, escapes = _classify_site(graph, key, site, production)
            for esc in escapes:
                findings.append(
                    {
                        "kind": "unclassified-raise",
                        "module": key[0],
                        "line": site.line,
                        "detail": (
                            f"{site.exc} raised in {key[1]} escapes the "
                            f"production graph uncaught (via {esc})"
                        ),
                    }
                )
            primary = next((k for k in SINK_PRIORITY if k in sinks), None)
            if primary is None and not escapes:
                # No terminal frame reached (walk budget / pure-cycle
                # corner): count it visibly rather than dropping it.
                primary = "handled"
            if primary is not None:
                sink_counts[primary] = sink_counts.get(primary, 0) + 1
    import_raises = sum(graph.module_raises.values())
    if import_raises:
        sink_counts["import-time"] = import_raises

    flush = _check_flush(graph, findings)
    _check_exit75(graph, findings)
    faults = _check_faults(graph, production, findings)

    findings.sort(key=lambda f: (f["kind"], f["module"], f["line"]))
    return {
        "files": graph.files,
        "functions": len(graph.funcs),
        "sinks": {k: sink_counts[k] for k in sorted(sink_counts)},
        "raise_modules": {
            k: raise_modules[k] for k in sorted(raise_modules)
        },
        "advisory": advisory,
        "flush": flush,
        "fault_sites": faults,
        "findings": findings,
        "counts": {
            "raise_sites": total,
            "production_raises": prod_sites,
            "production_functions": len(production),
            "broad_handlers": broad,
            "wire_reply_handlers": wire,
            "advisory_markers": len(advisory),
            "findings": len(findings),
        },
    }


def run_or_raise(package_root: str | Path | None = None) -> dict:
    """Audit and raise :class:`ExitFlowError` on any finding."""
    report = audit_exitflow(package_root)
    if report["findings"]:
        rows = "\n".join(
            f"  [{f['kind']}] {f['module']}:{f['line']}: {f['detail']}"
            for f in report["findings"]
        )
        raise ExitFlowError(
            f"exception-flow audit failed "
            f"({len(report['findings'])} finding(s)):\n{rows}"
        )
    return report
