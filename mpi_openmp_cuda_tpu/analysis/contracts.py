"""Declarative shape/dtype/value-range contracts for the scorer entry
points.

Two enforcement tiers, per the MPI-rical argument (PAPERS.md) that
distributed-kernel invariants need *tooling*, not author discipline:

* **Abstract** (:func:`audit_entry_points`) — every registered entry
  point is traced with ``jax.eval_shape`` over representative abstract
  operands (no FLOPs, no device, no TPU) and its output aval is checked
  against the declared contract.  Runs in ``make analyze`` and CI.
* **Concrete** (:func:`validate_dispatch`) — the numeric-range gates
  that cannot be seen in an aval (float32 exactness ceiling, rowpack
  epilogue bound, superblock divisibility) are checked against the
  CONCRETE dispatch decision at the single place all of them become
  real: ``AlignmentScorer._score_local``.  Enabled by ``--check`` /
  ``SEQALIGN_CHECK``; each failure is a distinct
  :class:`~..analysis.ContractViolation` subclass naming the violated
  bound and the fix.
* **Traced** (:func:`checked_pallas_body`) — a
  ``jax.experimental.checkify`` wrapper over the fused body for the
  value-range facts that only exist inside the traced program (len2
  within the padded bucket, codes within the alphabet, int32 prefix-cast
  headroom).  Debug aid for new kernel work; not on the hot path.

Adding a contract for a new entry point = one :class:`EntryContract`
row in :data:`ENTRY_CONTRACTS`.  See ARCHITECTURE.md §9.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Sequence

import numpy as np

from . import (
    ContractViolation,
    ExactnessViolation,
    FeedViolation,
    RowpackViolation,
    SuperblockViolation,
)

_LANE = 128
_VAL_SIZE = 27 * 27  # ALPHABET_SIZE**2 flat substitution table


# --------------------------------------------------------------------------
# Concrete value-range gates (the --check tier).
# --------------------------------------------------------------------------


def check_feed(feed: str, maxv: int) -> None:
    """``feed`` must be the feed ``mxu_feed`` affords for this weight
    magnitude — a narrower feed silently truncates operands on the MXU."""
    from ..ops.pallas_scorer import mxu_feed

    if feed not in ("i8", "bf16", "f32"):
        raise FeedViolation(
            f"unknown MXU feed {feed!r}: legal feeds are 'i8', 'bf16', 'f32' "
            "(ops/pallas_scorer.mxu_feed)"
        )
    afforded = mxu_feed(np.asarray([maxv], dtype=np.int64))
    order = {"i8": 0, "bf16": 1, "f32": 2}
    if order[feed] < order[afforded]:
        raise FeedViolation(
            f"feed {feed!r} cannot represent max|v|={maxv} exactly "
            f"(i8 holds |v|<=127, bf16 |v|<=128); use feed {afforded!r} "
            "from ops/pallas_scorer.mxu_feed(val_flat)"
        )


def check_exactness(maxv: int, l2p: int) -> None:
    """f32-formulation exactness ceiling: every prefix partial of the
    delta formulation is an integer bounded by ``2 * l2p * max|v|`` and
    must stay below 2^24 (f32 integer-exact range); the gather int16
    window additionally caps |v| at 32767.  Length-aware per PR 2."""
    from ..ops.matmul_scorer import max_exact_value

    ceiling = max_exact_value(l2p)
    if maxv > ceiling:
        raise ExactnessViolation(
            f"max|v|={maxv} exceeds the f32 exactness ceiling "
            f"max_exact_value(l2p={l2p})={ceiling}: prefix partials up to "
            f"2*{l2p}*{maxv} would round in float32. Route this batch to "
            "the gather formulation (dispatch auto-selects it; see "
            "ops/matmul_scorer.max_exact_value)"
        )


def check_rowpack(feed: str, l2p: int, l2s: int | None, maxv: int) -> None:
    """Row-packing preconditions: packing only exists for single
    char-block buckets, l2s must be a legal sub-tile class for this
    feed, and the packed epilogue key ``(t1 + gdec) * 2^klb + key``
    needs the packed score magnitude ``3 * l2s * max|v|`` below 2^19."""
    from ..ops.dispatch import pack_classes

    if l2s is None:
        return
    if l2p != _LANE:
        raise RowpackViolation(
            f"row packing (l2s={l2s}) requires a single char-block bucket "
            f"(L2P == {_LANE}), got L2P={l2p}: multi-block buckets walk "
            "blocks per pair and cannot share tiles (dispatch.choose_rowpack)"
        )
    from ..ops.bounds import ROWPACK_EPILOGUE_LIMIT

    legal = pack_classes(feed, maxv)
    if l2s not in legal:
        if 3 * l2s * maxv >= ROWPACK_EPILOGUE_LIMIT:
            raise RowpackViolation(
                f"rowpack class l2s={l2s} breaches the packed int32 "
                f"epilogue gate for feed {feed!r}: 3*{l2s}*{maxv} = "
                f"{3 * l2s * maxv} >= 2^19 = {ROWPACK_EPILOGUE_LIMIT}, so the packed "
                f"argmax key would collide. Legal classes for max|v|={maxv}: "
                f"{legal or '() — packing disabled at this magnitude'} "
                "(dispatch.pack_classes)"
            )
        raise RowpackViolation(
            f"rowpack class l2s={l2s} is not a legal sub-tile class for "
            f"feed {feed!r} at max|v|={maxv}: legal classes are {legal} "
            "(dispatch.pack_classes)"
        )


def check_superblock(nbn: int, sb: int | None) -> None:
    """Superblock width must tile the offset-block count exactly and
    stay within the packed argmax key budget (klb <= 12 => sb <= 24)."""
    if sb is None:
        return
    if sb < 1 or nbn % sb != 0:
        raise SuperblockViolation(
            f"superblock sb={sb} does not tile the offset-block count "
            f"nbn={nbn}: the kernel grid needs nbn % sb == 0 "
            f"(divisors of {nbn} are legal; pallas_scorer.choose_superblock)"
        )
    from ..ops.bounds import SUPERBLOCK_CAP

    if sb > SUPERBLOCK_CAP:
        raise SuperblockViolation(
            f"superblock sb={sb} exceeds the packed argmax key bound "
            f"sb <= {SUPERBLOCK_CAP} (key bits klb <= 12 keep "
            "(t1+gdec)*2^klb+key inside int32; pallas_scorer._superblock)"
        )


def validate_dispatch(
    *,
    feed: str,
    maxv: int,
    l1p: int,
    l2p: int,
    sb: int | None,
    l2s: int | None,
) -> None:
    """Validate one CONCRETE pallas dispatch decision — the ``--check`` /
    ``SEQALIGN_CHECK`` hook called from ``AlignmentScorer._score_local``
    after the choosers have run.  Raises a distinct
    :class:`ContractViolation` subclass per violated gate."""
    check_feed(feed, maxv)
    check_exactness(maxv, l2p)
    check_rowpack(feed, l2p, l2s, maxv)
    check_superblock(l1p // _LANE, sb)


# --------------------------------------------------------------------------
# Abstract entry-point contracts (eval_shape tier).
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class EntryContract:
    """One scorer entry point and its declared abstract contract.

    ``make`` returns ``(callable, args)`` for ``jax.eval_shape``;
    ``out_shape``/``out_dtype`` declare the result aval.  Construction is
    deferred into ``make`` so importing this module stays jax-light.
    """

    name: str
    make: Callable[[int, int, int, int], tuple]  # (b, nc, l1p, l2p) ->
    out_shape: Callable[[int, int, int, int], tuple]
    out_dtype: str
    doc: str = ""


def _aval(shape: Sequence[int], dtype: str):
    import jax

    return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))


def _chunk_args(b: int, nc: int, l1p: int, l2p: int) -> tuple:
    """Abstract operands for the chunked [NC, CB, L2P] -> [NC, CB, 3]
    bodies (cb = b // nc)."""
    cb = b // nc
    return (
        _aval((l1p + l2p + 1,), "int32"),  # seq1ext
        _aval((), "int32"),  # len1
        _aval((nc, cb, l2p), "int32"),  # seq2_chunks
        _aval((nc, cb), "int32"),  # len2_chunks
        _aval((_VAL_SIZE,), "int32"),  # val_flat
    )


def _pair_args(b: int, nc: int, l1p: int, l2p: int) -> tuple:
    """Abstract operands for the per-shard pair scorer
    ([BL, L2P] -> [BL, 3])."""
    return (
        _aval((l1p + l2p + 1,), "int32"),
        _aval((), "int32"),
        _aval((b, l2p), "int32"),  # rows
        _aval((b,), "int32"),  # lens
        _aval((_VAL_SIZE,), "int32"),
    )


def _make_gather(b, nc, l1p, l2p):
    from ..ops.xla_scorer import score_chunks_body

    return score_chunks_body, _chunk_args(b, nc, l1p, l2p)


def _make_mm(b, nc, l1p, l2p):
    from ..ops.matmul_scorer import score_chunks_mm_body

    return score_chunks_mm_body, _chunk_args(b, nc, l1p, l2p)


def _make_pallas(b, nc, l1p, l2p):
    import functools

    from ..ops.pallas_scorer import score_chunks_pallas_body

    # interpret-free: eval_shape never runs the kernel, only shapes it.
    fn = functools.partial(score_chunks_pallas_body, feed="f32")
    return fn, _chunk_args(b, nc, l1p, l2p)


def _make_pair(b, nc, l1p, l2p):
    from ..ops.pallas_scorer import pallas_pair_scorer

    return pallas_pair_scorer(l1p, l2p, "f32", None), _pair_args(
        b, nc, l1p, l2p
    )


def _make_shard_map(b, nc, l1p, l2p):
    """The BatchSharding shard_map wrapper, over however many devices the
    host exposes (CPU CI: the analyze driver forces 8 virtual devices)."""
    import jax

    from ..parallel.mesh import make_mesh
    from ..parallel.sharding import _sharded_fn

    mesh = make_mesh()
    ndev = len(mesh.devices.ravel())
    bp = max(b, ndev)  # at least one row per device
    bp += (-bp) % ndev
    cb = max(1, bp // ndev)
    fn = _sharded_fn(mesh, cb, ("pallas", l1p, l2p, "f32", None))
    return fn, _pair_args(bp, nc, l1p, l2p)


def _chunk_out(b, nc, l1p, l2p):
    return (nc, b // nc, 3)


def _pair_out(b, nc, l1p, l2p):
    return (b, 3)


def _shard_out(b, nc, l1p, l2p):
    import jax

    ndev = jax.device_count()
    bp = max(b, ndev)
    bp += (-bp) % ndev
    return (bp, 3)


ENTRY_CONTRACTS: tuple[EntryContract, ...] = (
    EntryContract(
        name="xla_scorer.score_chunks_body",
        make=_make_gather,
        out_shape=_chunk_out,
        out_dtype="int32",
        doc="gather formulation, [NC,CB,L2P] -> [NC,CB,3] int32",
    ),
    EntryContract(
        name="matmul_scorer.score_chunks_mm_body",
        make=_make_mm,
        out_shape=_chunk_out,
        out_dtype="int32",
        doc="matmul delta formulation, [NC,CB,L2P] -> [NC,CB,3] int32",
    ),
    EntryContract(
        name="pallas_scorer.score_chunks_pallas_body",
        make=_make_pallas,
        out_shape=_chunk_out,
        out_dtype="int32",
        doc="fused pallas body, [NC,CB,L2P] -> [NC,CB,3] int32",
    ),
    EntryContract(
        name="pallas_scorer.pallas_pair_scorer",
        make=_make_pair,
        out_shape=_pair_out,
        out_dtype="int32",
        doc="per-shard pair callable, [BL,L2P] -> [BL,3] int32",
    ),
    EntryContract(
        name="sharding._sharded_fn (shard_map wrapper)",
        make=_make_shard_map,
        out_shape=_shard_out,
        out_dtype="int32",
        doc="jitted shard_map scorer over the host mesh, [BP,L2P] -> [BP,3]",
    ),
)

# Representative shape buckets: the 128-aligned pallas regime, a
# multi-block wide bucket, and a tiny non-aligned bucket (mm fallback
# inside the pallas body).
_AUDIT_BUCKETS: tuple[tuple[int, int, int, int], ...] = (
    # (b, nc, l1p, l2p)
    (8, 2, 512, 128),
    (16, 4, 3072, 2048),
    (4, 1, 200, 40),
)


def audit_entry_points(buckets=_AUDIT_BUCKETS) -> list[str]:
    """``jax.eval_shape`` every registered entry point over the audit
    buckets and verify the output aval.  Returns human-readable report
    rows; raises :class:`ContractViolation` on the first mismatch."""
    import jax

    rows = []
    for contract in ENTRY_CONTRACTS:
        for b, nc, l1p, l2p in buckets:
            fn, args = contract.make(b, nc, l1p, l2p)
            try:
                out = jax.eval_shape(fn, *args)
            except ContractViolation:
                raise
            except Exception as exc:  # noqa: BLE001 - re-raise with context
                raise ContractViolation(
                    f"{contract.name} failed abstract evaluation at bucket "
                    f"(b={b}, nc={nc}, l1p={l1p}, l2p={l2p}): {exc!r}"
                ) from exc
            want_shape = tuple(contract.out_shape(b, nc, l1p, l2p))
            want_dtype = np.dtype(contract.out_dtype)
            got_shape = tuple(out.shape)
            got_dtype = np.dtype(out.dtype)
            if got_shape != want_shape or got_dtype != want_dtype:
                raise ContractViolation(
                    f"{contract.name}: output contract mismatch at bucket "
                    f"(b={b}, nc={nc}, l1p={l1p}, l2p={l2p}): declared "
                    f"{want_shape} {want_dtype}, traced {got_shape} "
                    f"{got_dtype}"
                )
            rows.append(
                f"{contract.name:<45s} (b={b:>3d}, l1p={l1p:>5d}, "
                f"l2p={l2p:>5d}) -> {got_shape} {got_dtype} OK"
            )
    return rows


# --------------------------------------------------------------------------
# checkify tier: traced value-range checks.
# --------------------------------------------------------------------------


def checked_pallas_body(feed: str = "f32", sb: int | None = None):
    """Wrap the fused body in ``jax.experimental.checkify`` asserts over
    facts only visible on traced values: chunk lengths within the padded
    bucket, codes within the alphabet, and weights within the int32
    prefix-cast headroom.  Returns ``fn(args...) -> (err, out)``; call
    ``err.throw()`` to surface violations.  The checks run in a
    checkified PROLOGUE over the inputs only — checkify cannot discharge
    its error state through ``pallas_call``'s aliased refs, so the
    kernel itself is invoked outside the transform.  Debug tool for
    kernel work — the hot path stays checkify-free."""
    import jax.numpy as jnp
    from jax.experimental import checkify

    from ..ops.matmul_scorer import max_exact_value
    from ..ops.pallas_scorer import score_chunks_pallas_body

    def prologue(seq2_chunks, len2_chunks, val_flat):
        l2p = seq2_chunks.shape[-1]
        checkify.check(
            jnp.all(len2_chunks <= l2p),
            "len2 {m} exceeds the padded bucket width "  # noqa: UP032
            + str(l2p)
            + " (rows would read past the chunk)",
            m=jnp.max(len2_chunks),
        )
        checkify.check(
            jnp.all((seq2_chunks >= 0) & (seq2_chunks < 27)),
            "seq2 codes outside the alphabet [0, 27)",
        )
        ceiling = max_exact_value(l2p)
        absmax = jnp.max(jnp.abs(val_flat))
        checkify.check(
            absmax <= ceiling,
            "max|v| {m} exceeds max_exact_value(l2p="
            + str(l2p)
            + ")="
            + str(ceiling)
            + ": f32 prefix partials would round / int32 prefix cast "
            "would overflow",
            m=absmax,
        )
        return 0

    checked_prologue = checkify.checkify(prologue)

    def fn(seq1ext, len1, seq2_chunks, len2_chunks, val_flat):
        err, _ = checked_prologue(seq2_chunks, len2_chunks, val_flat)
        out = score_chunks_pallas_body(
            seq1ext, len1, seq2_chunks, len2_chunks, val_flat, feed=feed,
            sb=sb,
        )
        return err, out

    return fn
