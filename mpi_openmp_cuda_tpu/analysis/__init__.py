"""Static analysis & machine-checked contracts (`seqcheck`).

PR 2 shipped with an *unmeasured assumption* ("2-wide f32 tiles spill
VMEM") sitting in the kernel chooser for a whole PR cycle, and the
numeric gates that keep the fused kernel exact — ``max_exact_value(l2p)``,
the ``3 * l2s * maxv < 2**19`` rowpack epilogue bound — were enforced
only by convention at the call sites in ``ops/dispatch.py``.  This
package turns those conventions into four cooperating passes, all
runnable on CPU-only CI (``make analyze``):

* :mod:`.contracts` — declarative shape/dtype/value-range contracts on
  every scorer entry point, verified abstractly via ``jax.eval_shape``
  and (under ``--check`` / ``SEQALIGN_CHECK``) at runtime via
  ``jax.experimental.checkify``.
* :mod:`.vmem` — a static per-config VMEM footprint model derived from
  the ``BlockSpec``s of ``_pallas_call`` / ``_pallas_call_packed``,
  exhaustively swept over the chooser space; an emitted config past the
  per-core budget is a red X, not a surprise on real hardware.
* :mod:`.seqlint` — an AST lint with repo-specific rules (host syncs in
  jitted scoring paths, scattered env reads, Python branches on traced
  values, bare asserts in runtime paths, wall-clock reads in
  deterministic resilience/journal decision paths).
* :mod:`.recompile` — a jit cache-miss counting harness so tests can pin
  the expected number of compilations per bucketed schedule.
* :mod:`.costmodel` — a static FLOP / bytes-moved / launch-count cost
  sheet per emittable kernel config and per composed bucketed schedule,
  producing the ``predicted_mfu_vs_feed_roofline`` bench.py emits next
  to the measured number and the hot-config ranking for the AOT cache.
* :mod:`.traceaudit` — a jaxpr/StableHLO walker over the lowered entry
  points and schedule bodies: un-donated large buffers on the chunk
  pipeline, implicit host transfers / ``convert`` widenings in hot
  paths, and the executables-per-schedule static launch count.
* :mod:`.lockgraph` — a whole-program lock-graph audit: every lock
  acquisition site plus the intra-package call graph, failing on
  lock-order cycles, blocking operations reachable while a serve-plane
  or obs lock is held, and cross-class acquire/release splits.
* :mod:`.interleave` — a small-scope model checker that runs the REAL
  fleet-protocol state machines (``Membership``, ``LeaseTable``,
  ``RequestQueue``, ``FleetCoordinator``) under a virtual scheduler,
  exhaustively enumerating sleep-set-pruned interleavings to a depth
  bound and asserting the §8.6 protocol invariants on every schedule.
* :mod:`.collectives` — a mesh-aware collective-safety pass over every
  sharded entry point (each ``parallel/specs.py`` mesh form lowered on
  the forced multi-device CPU backend): the per-device collective
  inventory (op, axes, shape, dtype, payload bytes), fail-closed
  ordering-consistency proofs (unregistered axes, replica-divergent
  branches), resharding hygiene against the post-partitioning HLO, and
  the ring-plan cross-check that ties the lowered programs to the ICI
  comms model in :mod:`.costmodel`.
* :mod:`.dataflow` — a whole-program donation-safety pass: def-use /
  liveness for every array operand flowing into the module-level jit
  entry points across all call sites (dispatch, pipeline, fleet, and
  the retry/degrade/rescue re-dispatch ladders), emitting the
  machine-checked ``DonationPlan`` that the ``donate_argnums`` wiring
  and traceaudit's enforced donation gate are derived from.
* :mod:`.exitflow` — a failure-path certifier: the whole-program
  raise/except/finally propagation graph over the intra-package call
  graph, proving every production-reachable raise site terminates in
  exactly one legal sink (the RetryPolicy transient/fatal ladder, a
  typed serve wire-error reply, the ``io/cli.py`` sysexits map, or a
  reasoned ``# advisory:`` swallow marker), that every exit path in
  ``io/cli.py`` / ``serve/loop.py`` passes through the finally-first
  flush, that exit 75 is reachable only from deadline/drain-rooted
  causes, and that every fault-registry site still names a fire point
  the graph can reach.
* :mod:`.ranges` — a value-range certifier: abstract interpretation
  over every scoring jaxpr in an interval domain (one-hot and
  congruence refinements, widening-to-fixpoint loops, ``pallas_call``
  kernel recursion), seeded from the entry contracts' input envelopes.
  It re-derives every hand numeric bound (``max_exact_value``, the
  2^19 rowpack gate, the 2^31 argmax packing) and diffs each against
  its wired source in ``ops/bounds.py`` — drift, a lossy narrowing, an
  overflow-capable accumulator, or an unknown primitive (fail closed)
  is a typed finding in the emitted ``RangeCert``.

Everything raises a :class:`SeqcheckError` subclass with a message
naming the violated bound and the fix, so a CI failure is actionable
without rerunning anything on a TPU.
"""

from __future__ import annotations


class SeqcheckError(RuntimeError):
    """Base of every analysis-pass failure (contracts, VMEM audit, lint
    driver).  Always carries an actionable message: the violated bound,
    the observed value, and where the legal policy lives."""


class ContractViolation(SeqcheckError):
    """A scorer entry point was (or would be) invoked outside its
    declared shape/dtype/value-range contract."""


class ExactnessViolation(ContractViolation):
    """Weight magnitudes exceed the float32 exactness ceiling for the
    requested formulation at the batch's Seq2 bucket width."""


class FeedViolation(ContractViolation):
    """The requested MXU feed does not match the one the value table
    affords (``pallas_scorer.mxu_feed``)."""


class RowpackViolation(ContractViolation):
    """A row-packing request breaches the packed kernel's int32 epilogue
    gate (``3 * l2s * maxv < 2**19``) or its shape preconditions."""


class SuperblockViolation(ContractViolation):
    """An offset-super-block width the kernel cannot execute (does not
    divide the offset-block count, or exceeds the ``sb <= 24`` packed
    argmax-key bound)."""


class VmemBudgetError(SeqcheckError):
    """A kernel configuration's modelled VMEM footprint exceeds the
    per-core budget."""


class LintError(SeqcheckError):
    """The repo-specific AST lint found violations (driver-level error;
    individual findings are :class:`.seqlint.LintFinding` rows)."""


class CostModelError(SeqcheckError):
    """The static cost sheet cannot price an emittable configuration or
    schedule (non-finite / non-positive modelled cost — the iteration
    model and the kernel walk have drifted apart)."""


class TraceAuditError(SeqcheckError):
    """A lowered entry point or schedule body violates a trace-level
    invariant (failed to lower, host transfer inside a chunk body,
    pallas-launch count drift)."""


class ScheduleDriftError(SeqcheckError):
    """The schedule-audit report drifted from the committed golden
    baseline (launch count, predicted MFU, donation coverage): either
    regenerate the golden deliberately (scripts/schedule_audit.py
    --update) or fix the regression."""


class LockGraphError(SeqcheckError):
    """The whole-program lock-graph audit (analysis/lockgraph.py) found
    a lock-order cycle, a blocking operation reachable while a
    serve-plane/obs lock is held, or a lock acquired and released by
    different classes."""


class InterleaveViolation(SeqcheckError):
    """The interleaving explorer (analysis/interleave.py) found a
    schedule that violates a fleet-protocol invariant (double demux,
    fenced-epoch post admitted, dead-worker resurrection, dropped
    reply).  The message carries the exact event schedule so the
    counterexample replays deterministically."""


class DataflowError(SeqcheckError):
    """The donation-safety dataflow pass (analysis/dataflow.py) found a
    plan violation: a donated operand that is not provably dead at some
    call site, a re-dispatch path that stages device buffers above the
    retry boundary (a retried chunk would alias donated inputs), or
    ``donate_argnums`` wiring that drifted from the proven plan.  The
    message carries the blocking call path, so the counterexample reads
    like a stack trace."""


class CollectiveAuditError(SeqcheckError):
    """The collective-safety pass (analysis/collectives.py) found a
    sharding-plane hazard: a collective over an unregistered mesh axis,
    a replica-divergent collective sequence (the static signature of a
    multi-host deadlock — fail closed), an implicit partitioner-inserted
    reshard on a large intermediate, a large operand entering a sharded
    program unplaced, or lowered ring structure that drifted from
    ``ring_plan``'s analytic exchange count."""


class RangeCertError(SeqcheckError):
    """The value-range certifier (analysis/ranges.py) could not certify
    the scoring tree: a hand constant drifted from its machine-derived
    value, an accumulator's proved interval escapes its exactness
    window, a ``convert_element_type`` narrows away live range, or an
    unknown primitive made the analysis fail closed.  The message names
    the entry/bucket (or constant row) and the interval evidence."""


class ExitFlowError(SeqcheckError):
    """The failure-path certifier (analysis/exitflow.py) found an
    exception-flow hazard: a raise site whose exception can escape the
    production call graph without reaching a classifier, a broad
    swallow without a reasoned ``# advisory:`` marker, a shadowed
    (double-classified) handler arm, an exit path that bypasses the
    finally-first flush, an exit-75 mapping not rooted in a
    deadline/drain cause, or a fault-registry site with no reachable
    fire point.  The message names the site and the escape path."""


__all__ = [
    "SeqcheckError",
    "ContractViolation",
    "ExactnessViolation",
    "FeedViolation",
    "RowpackViolation",
    "SuperblockViolation",
    "VmemBudgetError",
    "LintError",
    "CostModelError",
    "TraceAuditError",
    "ScheduleDriftError",
    "LockGraphError",
    "InterleaveViolation",
    "DataflowError",
    "CollectiveAuditError",
    "RangeCertError",
    "ExitFlowError",
]
