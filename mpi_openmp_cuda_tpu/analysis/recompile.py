"""Jit cache-miss counting harness (the recompile detector).

The bucketed schedule exists so a production stream compiles each shape
bucket ONCE and then stays on the fast path; a stray recompile (a
closure captured as a traced constant, a non-hashable static arg, a
drifting weak_type) silently multiplies serving latency without failing
any correctness test.  This harness turns the compile count into a
pinned, assertable number.

Signal: ``jax.monitoring``'s ``/jax/core/compile/backend_compile_duration``
event fires exactly once per real XLA/Mosaic backend compilation (cache
hits — both in-memory jit cache and the persistent compilation cache —
do not fire it).  jax 0.4.x has no listener-unregister API, so ONE
module-level listener increments a process-global counter and the
context manager reports deltas.

Caveats for test authors:

* Helper ops (``jnp.ones`` etc.) compile tiny programs too — pin
  *deltas around warmed code paths* (steady-state zero; deterministic
  repeat counts after ``jax.clear_caches()``), not absolute magic
  numbers for cold processes.
* The persistent compile cache must be off (the test conftest disables
  it) or cold counts become machine-dependent.
"""

from __future__ import annotations

import contextlib

from ..obs.events import publish
from . import SeqcheckError

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_state = {"registered": False, "count": 0}


def _listener(event: str, duration: float, **kwargs) -> None:
    if event == _COMPILE_EVENT:
        _state["count"] += 1
        # Mirror every backend compile onto the obs bus (armed runs count
        # it as the `recompiles` counter; otherwise one attribute check).
        publish("recompile")


def _ensure_registered() -> None:
    if _state["registered"]:
        return
    from jax import monitoring

    monitoring.register_event_duration_secs_listener(_listener)
    _state["registered"] = True


def compile_count() -> int:
    """Process-global backend-compilation count since the harness was
    first armed (monotonic; compare deltas, not absolutes)."""
    _ensure_registered()
    return _state["count"]


class CompileTally:
    """Result handle for :func:`count_compiles`: ``.count`` is live
    inside the block and frozen after it."""

    def __init__(self, start: int):
        self._start = start
        self._end: int | None = None

    @property
    def count(self) -> int:
        end = self._end if self._end is not None else _state["count"]
        return end - self._start

    def _freeze(self) -> None:
        self._end = _state["count"]


@contextlib.contextmanager
def count_compiles():
    """``with count_compiles() as tally:`` — ``tally.count`` is the
    number of backend compilations triggered inside the block."""
    _ensure_registered()
    tally = CompileTally(_state["count"])
    try:
        yield tally
    finally:
        tally._freeze()


@contextlib.contextmanager
def assert_compiles(expected: int | None = None, *, at_most: int | None = None):
    """Pin the compilations of a block: exact (``expected``) or bounded
    (``at_most``).  Raises :class:`SeqcheckError` naming the breach —
    the steady-state form is ``assert_compiles(0)`` around a warmed
    scoring call."""
    if (expected is None) == (at_most is None):
        raise ValueError("pass exactly one of expected= / at_most=")
    with count_compiles() as tally:
        yield tally
    n = tally.count
    if expected is not None and n != expected:
        raise SeqcheckError(
            f"recompile detector: block compiled {n} program(s), pinned "
            f"expectation is {expected}. A higher count means a jit "
            "cache miss slipped in (unhashed static arg, traced-constant "
            "closure, dtype/weak_type drift); lower means the pin is "
            "stale — update it WITH the dispatch change that removed the "
            "compilation."
        )
    if at_most is not None and n > at_most:
        raise SeqcheckError(
            f"recompile detector: block compiled {n} program(s), bound "
            f"is {at_most}: a jit cache miss slipped into a warmed path."
        )
