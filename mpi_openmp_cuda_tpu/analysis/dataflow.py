"""Whole-program donation-safety dataflow pass (``dataflow``).

PR 7's trace auditor LISTS every un-donated >= 16 KiB input buffer on
the chunk pipeline; this pass is the proof that lets the wiring act on
the list.  Donating blind is how you get use-after-donate crashes in
the retry/degrade/rescue re-dispatch paths: ``jax.jit(donate_argnums)``
deletes the caller's buffer on platforms that can alias it, so a retry
that re-reads its inputs must be *proved* to re-stage fresh device
buffers rather than alias the donated ones.

The pass walks the package AST (reusing lockgraph's module index and
call-descriptor resolution) and, for every module-level
``X = jax.jit(body)`` entry point, checks three properties:

(a) **call-site staging** — every package call site of the wrapper
    (direct, through dispatch indirections like
    ``resolve_xla_formulation(...)(*args)``, and through
    wrapper-returning helpers like ``aot.compile._target``) must stage
    each positional operand FRESH at the site: a ``jnp.asarray(...)``
    / ``jnp.int32(...)`` construction from host data, or a tuple built
    by a helper whose every return is such constructions.  An operand
    whose provenance is a device-typed local would ALIAS the wrapper's
    input (``jnp.asarray`` on a device array is a no-op) and is a
    hazard, not a staging.
(b) **post-dispatch liveness** — the name holding the staged operands
    must be dead after the executing call: no read downstream in
    execution order (sibling ``if``/``else`` branches do not count; a
    call inside a loop whose operands were staged OUTSIDE the loop is
    live — the next iteration would re-read deleted buffers).
(c) **re-staging on retry** — from every re-dispatch root (the
    ChunkPipeline dispatch/materialise retry ladders, whose rescore
    closures the pass inlines, and the fleet worker's score path),
    every call path to a staging site must create device buffers ONLY
    at the staging leaf, below the retry boundary: each retried
    attempt then re-enters the staging code with host operands and
    cannot see a donated buffer.  The degrade/rescue lambdas live in
    (and are inlined into) dispatch/materialise, so the backend-chain
    fallbacks ride the same proof.

The result is a machine-checked :class:`DonationPlan`: per entry, the
argnums that are provably dead after dispatch AND large enough to
matter (>= traceaudit's 16 KiB bound at some audit bucket) become
``donate``; everything else is pinned live with a reason — and, for
hazards, the blocking call path embedded, the same counterexample
shape interleave's violation schedules carry.  The plan is the single
source of truth: this pass cross-checks the ``donate_argnums``
literals actually wired on the jit assignments against it and fails on
drift, traceaudit lowers the audited bodies under it (flipping the
donation section from honest-zero reporting to an enforced gate), and
scripts/donation_audit.py diffs the stable view against the committed
golden.

Pure AST + arithmetic: no jax import, no devices, milliseconds.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path

from . import DataflowError
from .lockgraph import _index_module, _package_files, _resolve_call
from .traceaudit import LARGE_BUFFER_BYTES

#: ``jax.numpy`` constructors that stage a NEW device buffer when fed
#: host data (the freshness predicate of rule a).  Reductions/ops are
#: deliberately absent: an op output is fresh too, but the repo's
#: staging contract is "host numpy in, one constructor per operand" —
#: anything else deserves a hazard row and a human look.
_FRESH_CTORS = frozenset({
    "asarray", "array", "zeros", "ones", "full", "arange",
    "int8", "int32", "int64", "uint32", "float32",
})

#: Variable/receiver types the AST cannot see: the retry ladders invoke
#: the scorer through closure-captured degrader state and a lambda
#: parameter.  Like lockgraph's ``_ATTR_TYPE_HINTS``, these encode the
#: package's WIRING CONTRACT (io/pipeline.py routes all scoring through
#: ``degrader.scorer`` at call time); the vacuous-proof check below
#: fails the audit if a hint rots and a root stops reaching a staging
#: site.
_VAR_TYPE_HINTS: dict[tuple[str, str], str] = {
    ("io/pipeline.py", "deg.scorer"): "AlignmentScorer",
    ("io/pipeline.py", "sc"): "AlignmentScorer",
}

#: Constructor-parameter wiring (attribute assigned from an ``__init__``
#: parameter): the fleet worker scores through the ChunkPipeline the
#: serve loop hands it.
_ATTR_TYPE_HINTS: dict[tuple[str, str, str], str] = {
    ("serve/fleet.py", "FleetWorker", "pipeline"): "ChunkPipeline",
}

#: The re-dispatch roots of rule (c): every function that can invoke
#: the scorer MORE THAN ONCE for the same logical chunk (retry budget,
#: degrade ladder, breaker bypass, fleet re-claim).  Their rescore
#: closures are lambdas/nested defs defined inside these bodies, which
#: the call collector inlines, so the whole ladder is covered.
_REDISPATCH_ROOTS: tuple[tuple[str, str], ...] = (
    ("io/pipeline.py", "ChunkPipeline.dispatch"),
    ("io/pipeline.py", "ChunkPipeline.materialise"),
    ("serve/fleet.py", "FleetWorker._score_offer"),
)

#: The chunked-scorer ABI every module-level entry shares (contracts'
#: ``_chunk_args`` order).  The byte model below prices each position
#: at the trace-audit buckets; an entry with a different signature
#: (seeded test packages) has no size model and donates every provably
#: dead argnum instead.
_CHUNK_PARAMS = ("seq1ext", "len1", "seq2_chunks", "len2_chunks", "val_flat")


def _chunk_arg_bytes(bucket: tuple[int, int, int, int]) -> tuple[int, ...]:
    """Per-position operand bytes at one (b, nc, l1p, l2p) audit bucket
    — int32 end to end, mirroring ``contracts._chunk_args``."""
    b, nc, l1p, l2p = bucket
    cb = b // nc
    return (
        (l1p + l2p + 1) * 4,  # seq1ext
        4,                    # len1 scalar
        nc * cb * l2p * 4,    # seq2_chunks rows
        nc * cb * 4,          # len2_chunks
        27 * 27 * 4,          # val_flat
    )


# -- plan dataclasses ------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PinnedArg:
    """One argnum deliberately left undonated, with its proof."""

    argnum: int
    name: str
    kind: str  # "scalar" | "below-threshold" | "alias-hazard"
    reason: str
    #: For hazards: the blocking call path (re-dispatch root down to
    #: the offending site) plus the hazard rows — the counterexample.
    #: For size pins: the staging sites the decision covers.
    path: tuple[str, ...] = ()

    def to_json(self) -> dict:
        return {
            "argnum": self.argnum,
            "name": self.name,
            "kind": self.kind,
            "reason": self.reason,
            "path": list(self.path),
        }


@dataclasses.dataclass(frozen=True)
class EntryPlan:
    """The donation decision for one module-level jit entry point."""

    module: str
    wrapper: str
    body: str
    params: tuple[str, ...]
    donate: tuple[int, ...]
    pinned: tuple[PinnedArg, ...]
    call_sites: tuple[str, ...]  # "module:qualname" rows, sorted
    #: The donate_argnums literal actually wired on the jit assignment
    #: (None = unannotated — a wiring finding AND a SEQ011 finding).
    wired: tuple[int, ...] | None

    def to_json(self) -> dict:
        return {
            "module": self.module,
            "wrapper": self.wrapper,
            "body": self.body,
            "params": list(self.params),
            "donate": list(self.donate),
            "wired": None if self.wired is None else list(self.wired),
            "pinned": [p.to_json() for p in self.pinned],
            "call_sites": list(self.call_sites),
        }


@dataclasses.dataclass(frozen=True)
class DonationPlan:
    """The whole-package donation-safety verdict."""

    entries: tuple[EntryPlan, ...]
    #: Rule (c) rows: {root, leaf, path, ok}.
    restage_paths: tuple[dict, ...]
    #: {kind, entry, detail} rows; empty == the plan is enforceable.
    findings: tuple[dict, ...]

    def entry_for_body(self, body_name: str) -> EntryPlan | None:
        for e in self.entries:
            if e.body == body_name:
                return e
        return None

    def donate_for_callable(self, fn) -> tuple[int, ...] | None:
        """Plan donation for a body callable (functools.partial of a
        body included); None when the callable is outside the plan
        (function-local jits below the shard_map/pair seam)."""
        name = getattr(getattr(fn, "func", fn), "__name__", None)
        entry = self.entry_for_body(name) if name else None
        return entry.donate if entry is not None else None

    def to_body(self) -> dict:
        """The ``kind="donation-audit"`` run-report body."""
        return {
            "plan": {
                "large_buffer_bytes": LARGE_BUFFER_BYTES,
                "entries": [e.to_json() for e in self.entries],
            },
            "restage_paths": [dict(r) for r in self.restage_paths],
            "findings": [dict(f) for f in self.findings],
            "counts": {
                "entries": len(self.entries),
                "donated_argnums": sum(len(e.donate) for e in self.entries),
                "pinned": sum(len(e.pinned) for e in self.entries),
                "restage_paths": len(self.restage_paths),
                "findings": len(self.findings),
            },
        }


# -- AST collection --------------------------------------------------------


@dataclasses.dataclass
class _FuncNode:
    """One function/method with lambdas and nested defs INLINED: their
    bodies run under the enclosing retry machinery (policy.run invokes
    the closures), which is exactly the flow rule (c) must see."""

    module: str
    qualname: str
    node: ast.AST
    calls: list = dataclasses.field(default_factory=list)  # (desc, line)
    #: Lines of device-buffer constructions (jnp.* / jax.device_put).
    stages: list = dataclasses.field(default_factory=list)

    def key(self) -> tuple[str, str]:
        return (self.module, self.qualname)


def _is_jnp_stage(call: ast.Call) -> bool:
    func = call.func
    if not isinstance(func, ast.Attribute):
        return False
    root = func.value
    if isinstance(root, ast.Name) and root.id == "jnp":
        return True
    return (
        isinstance(root, ast.Name)
        and root.id == "jax"
        and func.attr == "device_put"
    )


def _dotted(node: ast.AST) -> str | None:
    """`a.b.c` receiver chains as a dotted string (None when the chain
    roots in anything but a plain Name)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _collect_func(module: str, qualname: str, node: ast.AST) -> _FuncNode:
    fn = _FuncNode(module, qualname, node)
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        if _is_jnp_stage(sub):
            fn.stages.append(sub.lineno)
            continue
        func = sub.func
        desc = None
        if isinstance(func, ast.Name):
            desc = ("name", func.id)
        elif isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name) and base.id == "self":
                desc = ("self", func.attr)
            elif (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
            ):
                desc = ("selfattr", base.attr, func.attr)
            elif isinstance(base, ast.Name):
                desc = ("mod", base.id, func.attr)
            else:
                recv = _dotted(base)
                if recv is not None:
                    desc = ("varattr", recv, func.attr)
        if desc is not None:
            fn.calls.append((desc, sub.lineno))
    return fn


class _Package:
    """The parsed package: func table (lambda-inlined), module indexes,
    class table, and the module-level jit assignments."""

    def __init__(self, package_root: str | Path | None = None):
        if package_root is None:
            package_root = Path(__file__).resolve().parent.parent
        self.root = Path(package_root)
        self.trees: dict[str, ast.Module] = {}
        self.indexes: dict = {}
        self.funcs: dict[tuple[str, str], _FuncNode] = {}
        self.classes: dict = {}
        for path, rel in _package_files(self.root):
            try:
                tree = ast.parse(path.read_text(), filename=str(path))
            except SyntaxError:
                continue  # seqlint owns syntax errors
            self.trees[rel] = tree
            index = _index_module(rel, tree)
            self.indexes[rel] = index
            for (mod, cls, attr), tname in _ATTR_TYPE_HINTS.items():
                if mod == rel and cls in index.classes:
                    index.classes[cls].attr_types.setdefault(attr, tname)
            for cname, cinfo in index.classes.items():
                self.classes.setdefault(cname, (rel, cinfo))
            for node in tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fn = _collect_func(rel, node.name, node)
                    self.funcs[fn.key()] = fn
                elif isinstance(node, ast.ClassDef):
                    for stmt in node.body:
                        if isinstance(
                            stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
                        ):
                            fn = _collect_func(
                                rel, f"{node.name}.{stmt.name}", stmt
                            )
                            self.funcs[fn.key()] = fn

    def resolve(self, desc, module: str, qualname: str):
        """lockgraph's resolution plus the varattr/type-hint kinds."""
        kind = desc[0]
        if kind in ("varattr", "mod"):
            tname = _VAR_TYPE_HINTS.get((module, desc[1]))
            if tname is not None and tname in self.classes:
                home, _ = self.classes[tname]
                key = (home, f"{tname}.{desc[2]}")
                if key in self.funcs:
                    return key
            if kind == "varattr":
                return None
        return _resolve_call(
            desc, module, qualname, self.indexes, self.classes, self.funcs
        )

    def reachable(self, start: tuple[str, str]) -> dict:
        """Func keys reachable from ``start`` (inclusive) -> call path
        — the same shortest-witness shape lockgraph._reachable emits."""
        paths = {start: (start,)}
        frontier = [start]
        while frontier:
            cur = frontier.pop()
            info = self.funcs.get(cur)
            if info is None:
                continue
            for desc, _line in info.calls:
                callee = self.resolve(desc, info.module, info.qualname)
                if callee is not None and callee not in paths:
                    paths[callee] = paths[cur] + (callee,)
                    frontier.append(callee)
        return paths


# -- module-level jit discovery --------------------------------------------


@dataclasses.dataclass
class _JitEntry:
    module: str
    wrapper: str
    body: str
    lineno: int
    wired: tuple[int, ...] | None
    wired_literal: bool  # False = donate_argnums present but not a literal
    params: tuple[str, ...]


def is_jit_call(value: ast.AST) -> bool:
    """``jax.jit(...)`` / bare ``jit(...)`` — shared predicate with
    seqlint's SEQ011 (which re-implements it lexically; keep in step)."""
    if not isinstance(value, ast.Call):
        return False
    func = value.func
    if isinstance(func, ast.Name):
        return func.id == "jit"
    return (
        isinstance(func, ast.Attribute)
        and func.attr == "jit"
        and isinstance(func.value, ast.Name)
        and func.value.id == "jax"
    )


def _literal_argnums(node: ast.AST) -> tuple[int, ...] | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, ast.Tuple) and all(
        isinstance(e, ast.Constant) and isinstance(e.value, int)
        for e in node.elts
    ):
        return tuple(e.value for e in node.elts)
    return None


def _jit_entries(pkg: _Package) -> list[_JitEntry]:
    entries: list[_JitEntry] = []
    for rel, tree in sorted(pkg.trees.items()):
        defs = {
            n.name: n
            for n in tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        for node in tree.body:
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and is_jit_call(node.value)
            ):
                continue
            call = node.value
            if not call.args or not isinstance(call.args[0], ast.Name):
                continue  # jit of a non-Name (lambda/partial): no body
            body = call.args[0].id
            wired = None
            wired_literal = True
            for kw in call.keywords:
                if kw.arg == "donate_argnums":
                    wired = _literal_argnums(kw.value)
                    wired_literal = wired is not None
            params: tuple[str, ...] = ()
            bdef = defs.get(body)
            if bdef is not None:
                params = tuple(
                    a.arg for a in bdef.args.posonlyargs + bdef.args.args
                )
            entries.append(_JitEntry(
                module=rel,
                wrapper=node.targets[0].id,
                body=body,
                lineno=node.lineno,
                wired=wired,
                wired_literal=wired_literal,
                params=params,
            ))
    return entries


# -- call-site staging / liveness ------------------------------------------


@dataclasses.dataclass
class _CallSite:
    module: str
    qualname: str
    line: int
    wrappers: tuple[tuple[str, str], ...]  # jit entries invoked here
    n_args: int
    fresh: tuple[bool, ...]
    hazards: tuple[str, ...]  # staging hazards, human rows
    reused: tuple[str, ...]  # post-call reads of the staged holder

    def site(self) -> str:
        return f"{self.module}:{self.qualname}"

    def ok(self, argnum: int) -> bool:
        return (
            not self.reused
            and argnum < self.n_args
            and self.fresh[argnum]
        )


def _parent_map(root: ast.AST) -> dict:
    parents: dict = {}
    for node in ast.walk(root):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _resolves_to_wrapper(name: str, module: str, pkg: _Package, wrappers):
    """Map a Name in ``module`` to a jit-entry key (module, wrapper)."""
    if (module, name) in wrappers:
        return (module, name)
    imp = pkg.indexes[module].from_imports.get(name)
    if imp is not None and imp[0] is not None and tuple(imp) in wrappers:
        return tuple(imp)
    return None


def _returner_map(pkg: _Package, wrappers) -> tuple[dict, list]:
    """Functions whose returns can hand a jit wrapper to the caller
    (``resolve_xla_formulation``, ``aot.compile._target``): func key ->
    set of wrapper keys.  A wrapper passed positionally into a partial
    would shift argnums — flagged, never silently supported."""
    out: dict = {}
    findings: list[dict] = []
    for key, fn in pkg.funcs.items():
        returned: set = set()
        for sub in ast.walk(fn.node):
            if not isinstance(sub, ast.Return) or sub.value is None:
                continue
            parents = None
            for leaf in ast.walk(sub.value):
                if not isinstance(leaf, ast.Name):
                    continue
                wkey = _resolves_to_wrapper(
                    leaf.id, fn.module, pkg, wrappers
                )
                if wkey is None:
                    continue
                if parents is None:
                    parents = _parent_map(sub.value)
                par = parents.get(leaf)
                if (
                    isinstance(par, ast.Call)
                    and par.args
                    and par.args[0] is leaf
                    and len(par.args) > 1
                    and isinstance(par.func, (ast.Name, ast.Attribute))
                    and (
                        getattr(par.func, "id", None) == "partial"
                        or getattr(par.func, "attr", None) == "partial"
                    )
                ):
                    findings.append({
                        "kind": "positional-partial",
                        "entry": f"{wkey[0]}:{wkey[1]}",
                        "detail": (
                            f"{fn.module}:{fn.qualname}:{leaf.lineno} "
                            "returns a POSITIONAL functools.partial of a "
                            "jit entry — the bound args shift every "
                            "argnum and the plan cannot map donation "
                            "through it; bind by keyword instead"
                        ),
                    })
                    continue
                returned.add(wkey)
        if returned:
            out[key] = returned
    return out, findings


def _fresh_providers(pkg: _Package) -> dict:
    """Functions whose every return is a Tuple of fresh jnp
    constructions (``aot.compile._concrete_args``): func key -> arity."""
    out: dict = {}
    for key, fn in pkg.funcs.items():
        arity = None
        for sub in ast.walk(fn.node):
            if not isinstance(sub, ast.Return) or sub.value is None:
                continue
            if not (
                isinstance(sub.value, ast.Tuple)
                and sub.value.elts
                and all(
                    isinstance(e, ast.Call)
                    and _is_jnp_stage(e)
                    and isinstance(e.func, ast.Attribute)
                    and e.func.attr in _FRESH_CTORS
                    for e in sub.value.elts
                )
            ):
                arity = None
                break
            n = len(sub.value.elts)
            if arity is not None and arity != n:
                arity = None
                break
            arity = n
        if arity is not None:
            out[key] = arity
    return out


def _device_locals(fn_node: ast.AST) -> set[str]:
    """Names assigned from a jnp construction anywhere in the function:
    feeding one back into ``jnp.asarray`` would alias, not stage."""
    out: set[str] = set()
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call):
            if _is_jnp_stage(sub.value):
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
    return out


def _expr_fresh(expr: ast.AST, device_names: set[str]) -> str | None:
    """None when ``expr`` stages a fresh device buffer; else the hazard
    description."""
    if not (isinstance(expr, ast.Call) and _is_jnp_stage(expr)):
        return (
            f"operand is not a jnp staging construction "
            f"({ast.dump(expr)[:60]}...)"
        )
    func = expr.func
    if isinstance(func, ast.Attribute) and func.attr not in _FRESH_CTORS:
        if func.attr == "device_put":
            return None
        return f"jnp.{func.attr} is not a recognised staging constructor"
    for leaf in ast.walk(expr):
        if isinstance(leaf, ast.Name) and leaf.id in device_names:
            return (
                f"operand built from device-typed local {leaf.id!r} — "
                "jnp.asarray on a device array aliases instead of staging"
            )
    return None


def _reads_after(
    fn_node: ast.AST, call: ast.Call, holders: set[str], parents: dict
) -> list[str]:
    """Reads of ``holders`` that can execute AFTER ``call``: statements
    following the call's statement chain in each enclosing block, plus
    — when the call sits in a loop whose holder assignment is outside
    that loop — any read in the loop at all (the next iteration)."""
    rows: list[str] = []

    def loads_in(node: ast.AST, skip: ast.AST | None = None):
        for sub in ast.walk(node):
            if sub is skip:
                continue
            if (
                isinstance(sub, ast.Name)
                and isinstance(sub.ctx, ast.Load)
                and sub.id in holders
            ):
                yield sub

    # Assignment lines of each holder (for the loop rule).
    assign_lines: dict[str, int] = {}
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Assign):
            for tgt in sub.targets:
                for leaf in ast.walk(tgt):
                    if isinstance(leaf, ast.Name) and leaf.id in holders:
                        assign_lines.setdefault(leaf.id, sub.lineno)

    node: ast.AST = call
    while node is not fn_node:
        parent = parents.get(node)
        if parent is None:
            break
        if isinstance(parent, (ast.For, ast.While, ast.AsyncFor)):
            for h in holders:
                line = assign_lines.get(h)
                staged_inside = (
                    line is not None
                    and parent.lineno <= line <= parent.end_lineno
                )
                if not staged_inside:
                    rows.append(
                        f"call at line {call.lineno} sits in a loop "
                        f"(line {parent.lineno}) but {h!r} is staged "
                        "outside it: the next iteration re-reads "
                        "donated buffers"
                    )
        for field in ("body", "orelse", "finalbody"):
            block = getattr(parent, field, None)
            if not isinstance(block, list) or node not in block:
                continue
            after = block[block.index(node) + 1:]
            for stmt in after:
                for leaf in loads_in(stmt):
                    rows.append(
                        f"{sorted(holders & {leaf.id})[0]!s} re-read at "
                        f"line {leaf.lineno} after the donating call at "
                        f"line {call.lineno}"
                    )
        node = parent
    return rows


def _call_sites(pkg: _Package, wrappers, returners) -> list[_CallSite]:
    providers = _fresh_providers(pkg)
    sites: list[_CallSite] = []
    for key, fn in pkg.funcs.items():
        parents = None
        bindings: dict[str, set] = {}  # local name -> wrapper keys
        tuple_assigns: dict[str, ast.AST] = {}  # name -> value expr
        for sub in ast.walk(fn.node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                tgt = sub.targets[0]
                if isinstance(tgt, ast.Name):
                    tuple_assigns.setdefault(tgt.id, sub.value)
                if isinstance(sub.value, ast.Call) and isinstance(
                    sub.value.func, ast.Name
                ):
                    callee = pkg.resolve(
                        ("name", sub.value.func.id), fn.module, fn.qualname
                    )
                    if callee in returners:
                        names = (
                            [tgt]
                            if isinstance(tgt, ast.Name)
                            else list(getattr(tgt, "elts", []))
                        )
                        for n in names:
                            if isinstance(n, ast.Name):
                                bindings.setdefault(n.id, set()).update(
                                    returners[callee]
                                )
        device_names = _device_locals(fn.node)
        for sub in ast.walk(fn.node):
            if not isinstance(sub, ast.Call):
                continue
            func = sub.func
            wkeys: set = set()
            if isinstance(func, ast.Name):
                w = _resolves_to_wrapper(func.id, fn.module, pkg, wrappers)
                if w is not None:
                    wkeys.add(w)
                wkeys.update(bindings.get(func.id, ()))
            elif isinstance(func, ast.Call) and isinstance(
                func.func, ast.Name
            ):
                callee = pkg.resolve(
                    ("name", func.func.id), fn.module, fn.qualname
                )
                if callee in returners:
                    wkeys.update(returners[callee])
            if not wkeys:
                continue
            # Positional operand exprs + the holder name to track.
            holders: set[str] = set()
            hazards: list[str] = []
            fresh: list[bool] = []
            if (
                len(sub.args) == 1
                and isinstance(sub.args[0], ast.Starred)
                and isinstance(sub.args[0].value, ast.Name)
            ):
                hname = sub.args[0].value.id
                holders.add(hname)
                value = tuple_assigns.get(hname)
                if isinstance(value, ast.Tuple):
                    for e in value.elts:
                        why = _expr_fresh(e, device_names)
                        fresh.append(why is None)
                        if why is not None:
                            hazards.append(
                                f"arg{len(fresh) - 1}: {why} "
                                f"(line {e.lineno})"
                            )
                elif isinstance(value, ast.Call) and isinstance(
                    value.func, ast.Name
                ):
                    callee = pkg.resolve(
                        ("name", value.func.id), fn.module, fn.qualname
                    )
                    if callee in providers:
                        fresh = [True] * providers[callee]
                    else:
                        hazards.append(
                            f"*{hname} built by "
                            f"{value.func.id}(), which is not a proven "
                            "fresh-staging helper"
                        )
                else:
                    hazards.append(
                        f"*{hname} has no visible tuple construction in "
                        "this function"
                    )
            else:
                for i, e in enumerate(sub.args):
                    if isinstance(e, ast.Starred):
                        hazards.append(f"arg{i}: unresolvable *star operand")
                        fresh.append(False)
                        continue
                    if isinstance(e, ast.Name):
                        src = tuple_assigns.get(e.id)
                        why = (
                            _expr_fresh(src, device_names)
                            if src is not None
                            else "no visible staging assignment"
                        )
                        holders.add(e.id)
                    else:
                        why = _expr_fresh(e, device_names)
                    fresh.append(why is None)
                    if why is not None:
                        hazards.append(f"arg{i}: {why} (line {e.lineno})")
            if parents is None:
                parents = _parent_map(fn.node)
            reused = (
                _reads_after(fn.node, sub, holders, parents)
                if holders
                else []
            )
            sites.append(_CallSite(
                module=fn.module,
                qualname=fn.qualname,
                line=sub.lineno,
                wrappers=tuple(sorted(wkeys)),
                n_args=len(fresh),
                fresh=tuple(fresh),
                hazards=tuple(hazards),
                reused=tuple(reused),
            ))
    return sites


# -- the plan --------------------------------------------------------------


def _max_arg_bytes(params: tuple[str, ...]) -> tuple[int, ...] | None:
    """Max per-position operand bytes over the trace-audit buckets for
    the chunked-scorer ABI; None for foreign signatures."""
    if params != _CHUNK_PARAMS:
        return None
    from .contracts import _AUDIT_BUCKETS

    per_bucket = [_chunk_arg_bytes(b) for b in _AUDIT_BUCKETS]
    return tuple(max(col) for col in zip(*per_bucket))


def _plan_entry(
    entry: _JitEntry, sites: list[_CallSite], root_paths: dict
) -> tuple[EntryPlan, list[dict]]:
    findings: list[dict] = []
    ekey = (entry.module, entry.wrapper)
    mine = [s for s in sites if ekey in s.wrappers]
    name = f"{entry.module}:{entry.wrapper}"
    nparams = len(entry.params)
    max_bytes = _max_arg_bytes(entry.params)

    def blocking_path(site: _CallSite) -> list[str]:
        rows = []
        fkey = (site.module, site.qualname)
        for root, paths in root_paths.items():
            if fkey in paths:
                rows.append(
                    " -> ".join(f"{m}:{q}" for m, q in paths[fkey])
                )
                break
        rows.extend(site.hazards)
        rows.extend(site.reused)
        return rows

    donate: list[int] = []
    pinned: list[PinnedArg] = []
    for argnum in range(nparams):
        pname = entry.params[argnum]
        bad = [s for s in mine if not s.ok(argnum)]
        if bad:
            site = bad[0]
            pinned.append(PinnedArg(
                argnum=argnum,
                name=pname,
                kind="alias-hazard",
                reason=(
                    f"not provably dead at "
                    f"{site.site()}:{site.line} — donation would delete "
                    "a buffer the caller still reads"
                ),
                path=tuple(
                    [f"{site.site()}:{site.line}"] + blocking_path(site)
                ),
            ))
            continue
        nbytes = max_bytes[argnum] if max_bytes is not None else None
        if nbytes is not None and nbytes < LARGE_BUFFER_BYTES:
            kind = "scalar" if nbytes <= 8 else "below-threshold"
            reason = (
                "0-d scalar operand: nothing to reclaim"
                if kind == "scalar"
                else (
                    f"provably dead but max {nbytes / 1024:.1f} KiB over "
                    f"the audit buckets, under the "
                    f"{LARGE_BUFFER_BYTES / 1024:.0f} KiB large-buffer "
                    "bound: donating reclaims no material HBM while "
                    "costing an unusable-donation warning per compile "
                    "on backends that cannot alias it"
                )
            )
            pinned.append(PinnedArg(
                argnum=argnum,
                name=pname,
                kind=kind,
                reason=reason,
                path=tuple(
                    sorted({f"{s.site()}:{s.line}" for s in mine})
                ),
            ))
            continue
        donate.append(argnum)

    if not mine:
        findings.append({
            "kind": "no-call-sites",
            "entry": name,
            "detail": (
                "no package call site of this jit entry resolved — the "
                "call-site discovery (or a _VAR_TYPE_HINTS row) rotted; "
                "a plan proven against zero sites proves nothing"
            ),
        })
    wired = entry.wired
    if not entry.wired_literal:
        findings.append({
            "kind": "wiring-drift",
            "entry": name,
            "detail": (
                f"{entry.module}:{entry.lineno} wires donate_argnums "
                "with a non-literal expression: the plan cannot "
                "cross-check it — spell the argnums as a literal tuple"
            ),
        })
    elif tuple(wired or ()) != tuple(donate):
        findings.append({
            "kind": "wiring-drift",
            "entry": name,
            "detail": (
                f"{entry.module}:{entry.lineno} wires donate_argnums="
                f"{wired!r} but the proof says {tuple(donate)!r}: wire "
                "exactly the provably-dead large argnums (analysis/"
                "dataflow.py is the single source)"
            ),
        })
    plan = EntryPlan(
        module=entry.module,
        wrapper=entry.wrapper,
        body=entry.body,
        params=entry.params,
        donate=tuple(donate),
        pinned=tuple(pinned),
        call_sites=tuple(sorted({s.site() for s in mine})),
        wired=wired,
    )
    return plan, findings


def _restage_rows(
    pkg: _Package, sites: list[_CallSite], roots
) -> tuple[list[dict], list[dict], dict]:
    """Rule (c): every re-dispatch root must reach at least one staging
    leaf, and every function on the witness path except the leaf must
    stage nothing."""
    rows: list[dict] = []
    findings: list[dict] = []
    leaves = {(s.module, s.qualname) for s in sites}
    root_paths: dict = {}
    for root in roots:
        rname = f"{root[0]}:{root[1]}"
        if root not in pkg.funcs:
            findings.append({
                "kind": "restage-root-missing",
                "entry": rname,
                "detail": (
                    "re-dispatch root no longer exists — update "
                    "_REDISPATCH_ROOTS in analysis/dataflow.py"
                ),
            })
            continue
        paths = pkg.reachable(root)
        root_paths[root] = paths
        reached = sorted(leaves & set(paths))
        if not reached:
            findings.append({
                "kind": "restage-unproven",
                "entry": rname,
                "detail": (
                    "re-dispatch root reaches NO staging site through "
                    "the resolved call graph: either the retry ladder "
                    "stopped scoring (real bug) or a _VAR_TYPE_HINTS "
                    "row rotted (fix the hint) — a vacuous proof fails "
                    "closed"
                ),
            })
            continue
        for leaf in reached:
            path = paths[leaf]
            stagers = [
                f for f in path[:-1] if pkg.funcs[f].stages
            ]
            ok = not stagers
            rows.append({
                "root": rname,
                "leaf": f"{leaf[0]}:{leaf[1]}",
                "path": [f"{m}:{q}" for m, q in path],
                "ok": ok,
            })
            for f in stagers:
                lines = pkg.funcs[f].stages
                findings.append({
                    "kind": "stage-above-retry",
                    "entry": rname,
                    "detail": (
                        f"{f[0]}:{f[1]} stages device buffers (line "
                        f"{lines[0]}) ABOVE the staging leaf on the "
                        "re-dispatch path "
                        + " -> ".join(f"{m}:{q}" for m, q in path)
                        + ": a retry would re-read them after donation "
                        "— keep every operand host-side until the leaf"
                    ),
                })
    return rows, findings, root_paths


def build_plan(
    package_root: str | Path | None = None,
    *,
    redispatch_roots=_REDISPATCH_ROOTS,
) -> DonationPlan:
    """Run the whole pass and return the :class:`DonationPlan`.

    ``redispatch_roots`` exists for seeded-violation tests walking a
    synthetic package tree; production callers always audit the real
    roots."""
    pkg = _Package(package_root)
    entries = _jit_entries(pkg)
    wrappers = {(e.module, e.wrapper) for e in entries}
    returners, findings = _returner_map(pkg, wrappers)
    sites = _call_sites(pkg, wrappers, returners)
    restage, rfindings, root_paths = _restage_rows(
        pkg, sites, redispatch_roots
    )
    findings.extend(rfindings)
    plans: list[EntryPlan] = []
    for entry in sorted(entries, key=lambda e: (e.module, e.wrapper)):
        plan, efindings = _plan_entry(entry, sites, root_paths)
        plans.append(plan)
        findings.extend(efindings)
    return DonationPlan(
        entries=tuple(plans),
        restage_paths=tuple(restage),
        findings=tuple(
            sorted(findings, key=lambda f: (f["kind"], f["entry"]))
        ),
    )


_PLAN_CACHE: dict = {}


def donation_plan() -> DonationPlan:
    """The cached plan for the installed package tree (traceaudit and
    the dispatch-side consumers ask per lowering; the AST walk runs
    once per process)."""
    plan = _PLAN_CACHE.get("plan")
    if plan is None:
        plan = _PLAN_CACHE["plan"] = build_plan()
    return plan


def audit_dataflow(package_root: str | Path | None = None) -> dict:
    """The full audit report body (never raises on findings)."""
    return build_plan(package_root).to_body()


def run_or_raise(package_root: str | Path | None = None) -> dict:
    """Driver entry: build the plan, raise :class:`DataflowError` on
    findings, return the report body when clean."""
    body = audit_dataflow(package_root)
    if body["findings"]:
        rows = "\n  ".join(
            f"[{f['kind']}] {f['entry']}: {f['detail']}"
            for f in body["findings"]
        )
        raise DataflowError(
            f"dataflow: {len(body['findings'])} finding(s):\n  {rows}"
        )
    return body
