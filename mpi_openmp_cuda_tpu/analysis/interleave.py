"""Small-scope interleaving explorer (``interleave``) — pillar four of
the analysis plane.

The fleet protocol's safety net (ARCHITECTURE §8.6) was *tested* by
chaos tiers that sample a handful of schedules.  This module makes the
matrix machine-checked: it runs the **real** protocol state machines —
:class:`~..resilience.membership.Membership`,
:class:`~..resilience.membership.LeaseTable` (``admits`` is the one
acceptance predicate), :class:`~..serve.fleet.FleetCoordinator` over a
real :class:`~..resilience.rescue.MemoryBoard`, and the real
:class:`~..serve.queue.RequestQueue` — under a virtual scheduler that
**exhaustively enumerates every interleaving of protocol events up to a
depth bound**, sleep-set pruned (classic DPOR: a pruned schedule is
Mazurkiewicz-equivalent to an explored one, so safety verdicts are
unaffected).

Event alphabet (the §8.6 failure matrix, one event per row):

* ``tick`` — one coordinator board poll (``FleetCoordinator.pump``):
  membership observe (join/death verdicts), stale-post fencing, result
  collection/demux, lease expiry → re-dispatch.  **Worker death** is
  heartbeat silence — exactly as in production, a SIGKILLed worker is
  indistinguishable from one the scheduler never runs again, so every
  schedule that stops beating a worker explores its death; **lease
  expiry** is ticks elapsing with a lease outstanding (the fencing
  scenario pins ``lease_ticks=1`` so expiry is reachable inside the
  depth bound).
* ``w<i>.beat`` — one heartbeat post (liveness proof).
* ``w<i>.claim`` — scan the offer, race ``board.claim`` on the
  epoch-stamped claim key (exactly-one-winner is asserted).
* ``w<i>.post`` — post the scored result under the claimed epoch.
* ``w<i>.stale`` — the adversarial zombie probe: re-post previously
  scored rows at the CURRENT offer's result key but carrying the stale
  claimed epoch in the payload — the buggy-writer shape
  ``LeaseTable.admits`` exists to fence.  A coordinator that admits
  without the epoch check demuxes it; the invariant catches that (the
  seeded-bug test in tests/test_interleave.py proves it).

Invariants, checked after every transition and at quiescence:

1. **each offer demuxed exactly once** — never two completions (demux
   or local fallback) for one block id;
2. **a fenced epoch's post is never admitted** — every demuxed row set
   carries exactly the newest epoch ever offered for its block;
3. **a dead worker is never resurrected** — once membership's verdict
   lands, a resumed heartbeat must not flip the worker live again;
4. **no reply is dropped** — from every reachable state, freezing the
   workers and pumping the coordinator drains every outstanding block
   (re-dispatch or local fallback) within a bounded number of ticks.

State is never copied: the explorer replays each event prefix from a
fresh scenario (stateless-replay DFS), so the real classes run with
their real mutation paths and no deepcopy aliasing.  Everything is
deterministic — virtual clock, fixed event order, no randomness — so
the explored-schedule counts are pinned byte-exact in the committed
``concurrency-audit`` golden.
"""

from __future__ import annotations

import contextlib
import io
import json

from ..resilience.membership import (
    board_read_json,
    claim_key,
    heartbeat_key,
    offer_key,
    result_key,
    worker_key,
)
from ..resilience.rescue import MemoryBoard
from ..serve.fleet import FleetCoordinator
from ..serve.queue import ADMIT_CLOSED, ADMIT_OK, RequestQueue
from . import InterleaveViolation

#: Quiescence bound: ticks allowed to drain all outstanding blocks once
#: workers freeze.  Death verdicts take ``deadline_ticks`` and expiry
#: ``lease_ticks`` — far below this; hitting the bound IS the
#: dropped-reply violation.
_QUIESCE_TICKS = 50


class VirtualClock:
    """The explorer's ServeClock stand-in: ``now()`` jumps a full poll
    interval per read (every ``pump`` polls — one pump == one tick) and
    ``block_until`` evaluates its predicate exactly once, immediately
    (single-threaded exploration never actually waits)."""

    def __init__(self):
        self._t = 0.0

    def now(self) -> float:
        self._t += 10.0
        return self._t

    def block_until(self, cond, predicate, timeout_s) -> bool:
        return bool(predicate())


class _Recorder:
    """Coordinator callbacks: where demuxed / locally-scored blocks
    land, in completion order."""

    def __init__(self):
        self.demuxed = []  # (block label, rows) in demux order
        self.local = []  # block labels completed via local fallback

    def demux(self, rows, block):
        self.demuxed.append((block.label, rows))

    def local_score(self, block):
        self.local.append(block.label)


class _ModelBlock:
    """The minimal superblock the coordinator's offer path can post:
    one row, so worker results are shape ``(1, 3)`` int64 and carry
    ``(worker idx, epoch, 0)`` as verifiable provenance."""

    def __init__(self):
        self.label = "?"
        self.weights = [1]
        self.seq1_codes = [1]
        self.codes = [[1]]


class _ModelWorker:
    """One worker's local state.  The board verbs and the key schema
    are the REAL ones (resilience/membership.py) — only the scoring is
    modelled (provenance rows instead of an alignment)."""

    def __init__(self, idx: int):
        self.idx = idx
        self.wid = f"mw{idx}"
        self.beats = 0
        self.claimed: dict[str, int] = {}  # bid -> claimed epoch


class _FleetState:
    """One replay's world: the real board/coordinator plus the
    invariant-checking ledgers."""

    def __init__(self):
        self.board = None
        self.coord = None
        self.workers = []
        self.recorder = None
        self.bids = []
        self.ledger = {}  # bid -> newest epoch ever offered
        self.seen_dead = set()
        self.winners = {}  # (bid, epoch) -> wid
        self.checked = 0  # demux records already invariant-checked


class FleetScenario:
    """The lease/epoch protocol under exploration."""

    def __init__(self, name: str, *, workers: int = 2, stale: bool = False,
                 lease_ticks: int | None = None,
                 seed_admit_bug: bool = False):
        self.name = name
        self.n_workers = int(workers)
        self.stale = bool(stale)
        self.lease_ticks = lease_ticks
        self.seed_admit_bug = bool(seed_admit_bug)
        self.invariants = (
            "demux-exactly-once",
            "fenced-epoch-never-admitted",
            "dead-worker-never-resurrected",
            "no-reply-dropped",
        )

    # -- world construction ------------------------------------------------

    def fresh(self) -> _FleetState:
        state = _FleetState()
        state.board = MemoryBoard()
        state.recorder = _Recorder()
        coord = FleetCoordinator(
            state.board,
            local_score=state.recorder.local_score,
            demux=state.recorder.demux,
            clock=VirtualClock(),
            lease_s=2.0,
            poll_s=1.0,  # lease_ticks = deadline_ticks = 2
        )
        if self.lease_ticks is not None:
            coord.leases.lease_ticks = int(self.lease_ticks)
        state.coord = coord
        state.workers = [_ModelWorker(i) for i in range(self.n_workers)]
        for w in state.workers:
            state.board.post(worker_key(w.wid), json.dumps({"wid": w.wid}))
            w.beats = 1
            state.board.post(heartbeat_key(w.wid), str(w.beats))
        coord.pump(idle=True)  # tick 1: every worker joins
        block = _ModelBlock()
        bid = coord.offer(block)
        block.label = bid
        state.bids = [bid]
        state.ledger = {bid: 0}
        if self.seed_admit_bug:
            # The seeded fencing bug the acceptance criteria demand: an
            # admit that ignores the epoch.  Instance-attribute override
            # of the REAL predicate — everything else runs unmodified.
            leases = coord.leases
            coord.leases.admits = (
                lambda bid, epoch, _t=leases: bid in _t._leases
            )
        return state

    # -- the event alphabet ------------------------------------------------

    def enabled(self, state: _FleetState):
        evs = ["tick"]
        board = state.board
        for w in state.workers:
            evs.append(f"w{w.idx}.beat")
            for bid in state.bids:
                offer = board_read_json(board, offer_key(bid))
                epoch = offer.get("epoch") if offer else None
                if (
                    offer is not None
                    and isinstance(epoch, int)
                    and w.claimed.get(bid) != epoch
                    and board.get(claim_key(bid, epoch)) is None
                    and board.get(result_key(bid, epoch)) is None
                ):
                    evs.append(f"w{w.idx}.claim")
                if bid in w.claimed and board.get(
                    result_key(bid, w.claimed[bid])
                ) is None:
                    evs.append(f"w{w.idx}.post")
                if (
                    self.stale
                    and bid in w.claimed
                    and offer is not None
                    and isinstance(epoch, int)
                    and epoch > w.claimed[bid]
                    and board.get(result_key(bid, epoch)) is None
                ):
                    evs.append(f"w{w.idx}.stale")
        return evs

    def execute(self, state: _FleetState, ev: str) -> None:
        if ev == "tick":
            state.coord.pump(idle=True)
            return
        widx, verb = ev.split(".", 1)
        w = state.workers[int(widx[1:])]
        board = state.board
        bid = state.bids[0]
        if verb == "beat":
            w.beats += 1
            board.post(heartbeat_key(w.wid), str(w.beats))
        elif verb == "claim":
            offer = board_read_json(board, offer_key(bid))
            epoch = int(offer["epoch"])
            if board.claim(
                claim_key(bid, epoch),
                json.dumps({"wid": w.wid, "epoch": epoch}),
            ):
                if (bid, epoch) in state.winners:
                    raise InterleaveViolation(
                        f"two claim winners for {bid} epoch {epoch}: "
                        f"{state.winners[(bid, epoch)]} and {w.wid}"
                    )
                state.winners[(bid, epoch)] = w.wid
                w.claimed[bid] = epoch
        elif verb == "post":
            epoch = w.claimed[bid]
            board.post(
                result_key(bid, epoch),
                json.dumps({
                    "bid": bid, "epoch": epoch, "wid": w.wid,
                    "rows": [[w.idx, epoch, 0]],
                }),
            )
        elif verb == "stale":
            # Re-post the rows scored under the OLD claimed epoch at the
            # CURRENT offer's result key: key recomputed, payload stale.
            offer = board_read_json(board, offer_key(bid))
            cur = int(offer["epoch"])
            old = w.claimed[bid]
            board.post(
                result_key(bid, cur),
                json.dumps({
                    "bid": bid, "epoch": old, "wid": w.wid,
                    "rows": [[w.idx, old, 0]],
                }),
            )
        else:
            raise InterleaveViolation(f"unknown event {ev!r} (model bug)")

    # -- invariants --------------------------------------------------------

    def check(self, state: _FleetState, schedule) -> None:
        rec = state.recorder
        for label, rows in rec.demuxed[state.checked:]:
            epoch = int(rows[0][1])
            if epoch != state.ledger[label]:
                raise InterleaveViolation(
                    f"fenced-epoch post ADMITTED: block {label} demuxed "
                    f"rows carrying epoch {epoch}, newest offered epoch "
                    f"is {state.ledger[label]} — LeaseTable.admits must "
                    f"fence it; schedule={list(schedule)}"
                )
        state.checked = len(rec.demuxed)
        done: dict[str, int] = {}
        for label, _rows in rec.demuxed:
            done[label] = done.get(label, 0) + 1
        for label in rec.local:
            done[label] = done.get(label, 0) + 1
        for label, n in done.items():
            if n > 1:
                raise InterleaveViolation(
                    f"block {label} completed {n} times (demux/local) — "
                    f"exactly-once broken; schedule={list(schedule)}"
                )
        for wid, view in state.coord.membership.workers.items():
            if not view.alive:
                state.seen_dead.add(wid)
            elif wid in state.seen_dead:
                raise InterleaveViolation(
                    f"dead worker {wid} RESURRECTED after its death "
                    f"verdict; schedule={list(schedule)}"
                )
        for bid in state.bids:
            offer = board_read_json(state.board, offer_key(bid))
            if offer is not None and isinstance(offer.get("epoch"), int):
                state.ledger[bid] = max(state.ledger[bid], offer["epoch"])

    def finish(self, state: _FleetState, schedule) -> None:
        """Leaf closure: freeze the workers, pump until every block
        drains (death verdicts → re-dispatch → local fallback), then
        require exactly one completion per block."""
        ticks = 0
        while state.coord.blocks and ticks < _QUIESCE_TICKS:
            self.execute(state, "tick")
            self.check(state, schedule)
            ticks += 1
        if state.coord.blocks:
            raise InterleaveViolation(
                f"reply DROPPED: blocks {sorted(state.coord.blocks)} "
                f"still outstanding after {_QUIESCE_TICKS} quiescence "
                f"ticks; schedule={list(schedule)}"
            )
        done: dict[str, int] = {}
        for label, _rows in state.recorder.demuxed:
            done[label] = done.get(label, 0) + 1
        for label in state.recorder.local:
            done[label] = done.get(label, 0) + 1
        for bid in state.bids:
            if done.get(bid, 0) != 1:
                raise InterleaveViolation(
                    f"block {bid} completed {done.get(bid, 0)} times at "
                    f"quiescence (want exactly 1); "
                    f"schedule={list(schedule)}"
                )

    # -- independence (sleep-set pruning) ----------------------------------

    def _actor(self, ev: str) -> str:
        return "coord" if ev == "tick" else ev.split(".", 1)[0]

    def _footprint(self, ev: str):
        if ev == "tick":
            return {"*"}
        _w, verb = ev.split(".", 1)
        if verb == "beat":
            return {f"hb/{_w}"}
        return {"blk"}  # claim/post/stale all race on the block's keys

    def independent(self, a: str, b: str) -> bool:
        if self._actor(a) == self._actor(b):
            return False
        fa, fb = self._footprint(a), self._footprint(b)
        if "*" in fa or "*" in fb:
            return False
        return not (fa & fb)


class QueueScenario:
    """The RequestQueue under exploration: three submitting clients, the
    popping loop, drain close, and source close, interleaved every way.
    Invariants: every admitted request is delivered exactly once (pop or
    drain), rejected requests never appear, sequence ids are unique,
    depth never exceeds ``max_depth``, and a submit after ``close()``
    is always verdict ``closed``."""

    MAX_DEPTH = 2
    CLIENTS = 3

    def __init__(self, name: str = "request-queue"):
        self.name = name
        self.invariants = (
            "admitted-delivered-exactly-once",
            "rejected-never-delivered",
            "seq-unique",
            "depth-bounded",
            "closed-means-closed",
        )

    def fresh(self):
        state = {
            "queue": RequestQueue(self.MAX_DEPTH, VirtualClock()),
            "tokens": [object() for _ in range(self.CLIENTS)],
            "verdicts": {},  # client idx -> ADMIT_* verdict
            "popped": [],
            "closed": False,
            "close_src_done": False,
        }
        state["queue"].open_source()
        return state

    def enabled(self, state):
        evs = []
        for i in range(self.CLIENTS):
            if i not in state["verdicts"]:
                evs.append(f"s{i}.submit")
        evs.append("pop")
        if not state["closed"]:
            evs.append("close")
        if not state["close_src_done"]:
            evs.append("close_src")
        return evs

    def execute(self, state, ev: str) -> None:
        q = state["queue"]
        if ev == "pop":
            state["popped"].extend(q.pop_ready(0.0, 0.0))
        elif ev == "close":
            state["closed"] = True
            q.close()
        elif ev == "close_src":
            state["close_src_done"] = True
            q.close_source()
        else:
            i = int(ev.split(".", 1)[0][1:])
            was_closed = state["closed"]
            verdict = q.submit({"id": f"c{i}"}, state["tokens"][i])
            state["verdicts"][i] = verdict
            if was_closed and verdict != ADMIT_CLOSED:
                raise InterleaveViolation(
                    f"submit after close() returned {verdict!r}, want "
                    f"{ADMIT_CLOSED!r}"
                )

    def check(self, state, schedule) -> None:
        depth = state["queue"].depth()
        if depth > self.MAX_DEPTH:
            raise InterleaveViolation(
                f"queue depth {depth} exceeds max_depth "
                f"{self.MAX_DEPTH}; schedule={list(schedule)}"
            )

    def finish(self, state, schedule) -> None:
        drained = state["queue"].drain_pending()
        out = list(state["popped"]) + list(drained)
        seqs = [r.seq for r in out]
        if len(set(seqs)) != len(seqs):
            raise InterleaveViolation(
                f"duplicate sequence ids {sorted(seqs)}; "
                f"schedule={list(schedule)}"
            )
        by_token = {}
        for r in out:
            by_token[id(r.responder)] = by_token.get(id(r.responder), 0) + 1
        for i, verdict in state["verdicts"].items():
            n = by_token.get(id(state["tokens"][i]), 0)
            want = 1 if verdict == ADMIT_OK else 0
            if n != want:
                raise InterleaveViolation(
                    f"client {i} verdict {verdict!r} delivered {n} "
                    f"time(s), want {want}; schedule={list(schedule)}"
                )

    def independent(self, a: str, b: str) -> bool:
        return False  # one shared queue: every pair of events conflicts


# -- the explorer ----------------------------------------------------------


def explore(scenario, depth: int) -> dict:
    """Exhaustive sleep-set DFS over ``scenario`` to ``depth`` events.

    Stateless replay: every node rebuilds the world from scratch and
    re-executes its prefix, so the real classes mutate real state with
    no copying.  Returns the stats dict (schedules / transitions /
    pruned / violations); exploration stops at the FIRST violating
    schedule — a model checker's job is the counterexample."""
    stats = {
        "name": scenario.name,
        "depth": int(depth),
        "schedules": 0,
        "transitions": 0,
        "pruned": 0,
        "violations": [],
        "invariants": list(scenario.invariants),
    }

    def recurse(prefix, sleep):
        state = scenario.fresh()
        for ev in prefix:
            scenario.execute(state, ev)
            stats["transitions"] += 1
            scenario.check(state, prefix)
        enabled = scenario.enabled(state)
        if len(prefix) >= depth or not enabled:
            scenario.finish(state, prefix)
            stats["schedules"] += 1
            return
        explored = []
        for ev in enabled:
            if ev in sleep:
                stats["pruned"] += 1
                continue
            child_sleep = {
                s for s in (sleep | set(explored))
                if scenario.independent(s, ev)
            }
            recurse(prefix + [ev], child_sleep)
            explored.append(ev)

    try:
        # The coordinator narrates joins/deaths/redispatches on stderr
        # (obs.events.log_line); thousands of replays must not flood the
        # terminal — the bus itself stays unarmed, nothing else changes.
        with contextlib.redirect_stderr(io.StringIO()):
            recurse([], set())
    except InterleaveViolation as exc:
        stats["violations"].append(str(exc))
    return stats


#: The committed exploration matrix (golden-pinned, >1000 schedules).
#: fleet-races: two workers racing one offer — claim exclusivity,
#:   exactly-once under death/expiry re-dispatch.
#: fleet-fencing: one worker with the adversarial stale re-post enabled
#:   and lease_ticks=1, deep enough that claim → expiry → re-offer →
#:   stale post → collect all fit inside the depth bound.
#: request-queue: admission/pop/close/close-source interleavings.
def scenarios():
    return [
        (FleetScenario("fleet-races", workers=2), 6),
        (
            FleetScenario(
                "fleet-fencing", workers=1, stale=True, lease_ticks=1
            ),
            8,
        ),
        (QueueScenario(), 6),
    ]


def run_all() -> dict:
    """Explore every committed scenario; the concurrency-audit report's
    ``interleave`` section."""
    rows = [explore(scn, depth) for scn, depth in scenarios()]
    return {
        "scenarios": rows,
        "total_schedules": sum(r["schedules"] for r in rows),
        "total_transitions": sum(r["transitions"] for r in rows),
    }


def run_or_raise() -> dict:
    """Driver entry: explore, raise :class:`InterleaveViolation` on any
    violating schedule, return the report section when clean."""
    report = run_all()
    bad = [
        f"[{r['name']}] {v}"
        for r in report["scenarios"]
        for v in r["violations"]
    ]
    if bad:
        raise InterleaveViolation(
            "interleave: protocol invariant violated:\n  "
            + "\n  ".join(bad)
        )
    return report
