"""Small-scope interleaving explorer (``interleave``) — pillar four of
the analysis plane.

The fleet protocol's safety net (ARCHITECTURE §8.6) was *tested* by
chaos tiers that sample a handful of schedules.  This module makes the
matrix machine-checked: it runs the **real** protocol state machines —
:class:`~..resilience.membership.Membership`,
:class:`~..resilience.membership.LeaseTable` (``admits`` is the one
acceptance predicate), :class:`~..serve.fleet.FleetCoordinator` over a
real :class:`~..resilience.rescue.MemoryBoard`, and the real
:class:`~..serve.queue.RequestQueue` — under a virtual scheduler that
**exhaustively enumerates every interleaving of protocol events up to a
depth bound**, sleep-set pruned (classic DPOR: a pruned schedule is
Mazurkiewicz-equivalent to an explored one, so safety verdicts are
unaffected).

Event alphabet (the §8.6 failure matrix, one event per row):

* ``tick`` — one coordinator board poll (``FleetCoordinator.pump``):
  membership observe (join/death verdicts), stale-post fencing, result
  collection/demux, lease expiry → re-dispatch.  **Worker death** is
  heartbeat silence — exactly as in production, a SIGKILLed worker is
  indistinguishable from one the scheduler never runs again, so every
  schedule that stops beating a worker explores its death; **lease
  expiry** is ticks elapsing with a lease outstanding (the fencing
  scenario pins ``lease_ticks=1`` so expiry is reachable inside the
  depth bound).
* ``w<i>.beat`` — one heartbeat post (liveness proof).
* ``w<i>.claim`` — scan the offer, race ``board.claim`` on the
  epoch-stamped claim key (exactly-one-winner is asserted).
* ``w<i>.post`` — post the scored result under the claimed epoch.
* ``w<i>.stale`` — the adversarial zombie probe: re-post previously
  scored rows at the CURRENT offer's result key but carrying the stale
  claimed epoch in the payload — the buggy-writer shape
  ``LeaseTable.admits`` exists to fence.  A coordinator that admits
  without the epoch check demuxes it; the invariant catches that (the
  seeded-bug test in tests/test_interleave.py proves it).

The failover scenario (PR 16) extends the alphabet with the
coordinator-level failure modes: ``crash`` (the leader dies ``kill -9``
style, board debris intact), ``sb<i>.tick`` (one standby watch tick —
observe the newest generation's beat, race ``try_acquire`` on verdict,
replay the predecessor's checkpoint on a win), and leader *starvation*
(a leader the scheduler never runs again is the zombie shape — its
deposition on the next pump is explored, not assumed).  Its invariants:
exactly one leader per generation, no reply duplicated across
generations, no reply dropped.

Invariants, checked after every transition and at quiescence:

1. **each offer demuxed exactly once** — never two completions (demux
   or local fallback) for one block id;
2. **a fenced epoch's post is never admitted** — every demuxed row set
   carries exactly the newest epoch ever offered for its block;
3. **a dead worker is never resurrected** — once membership's verdict
   lands, a resumed heartbeat must not flip the worker live again;
4. **no reply is dropped** — from every reachable state, freezing the
   workers and pumping the coordinator drains every outstanding block
   (re-dispatch or local fallback) within a bounded number of ticks.

State is never copied: the explorer replays each event prefix from a
fresh scenario (stateless-replay DFS), so the real classes run with
their real mutation paths and no deepcopy aliasing.  Everything is
deterministic — virtual clock, fixed event order, no randomness — so
the explored-schedule counts are pinned byte-exact in the committed
``concurrency-audit`` golden.
"""

from __future__ import annotations

import contextlib
import io
import json

from ..resilience.membership import (
    OFFER_PREFIX,
    LeaderLease,
    board_read_json,
    claim_key,
    heartbeat_key,
    offer_key,
    read_checkpoint,
    result_key,
    worker_key,
)
from ..resilience.rescue import MemoryBoard
from ..serve.fleet import FleetCoordinator, LeadershipLostError
from ..serve.queue import ADMIT_CLOSED, ADMIT_OK, RequestQueue
from . import InterleaveViolation

#: Quiescence bound: ticks allowed to drain all outstanding blocks once
#: workers freeze.  Death verdicts take ``deadline_ticks`` and expiry
#: ``lease_ticks`` — far below this; hitting the bound IS the
#: dropped-reply violation.
_QUIESCE_TICKS = 50


class VirtualClock:
    """The explorer's ServeClock stand-in: ``now()`` jumps a full poll
    interval per read (every ``pump`` polls — one pump == one tick) and
    ``block_until`` evaluates its predicate exactly once, immediately
    (single-threaded exploration never actually waits)."""

    def __init__(self):
        self._t = 0.0

    def now(self) -> float:
        self._t += 10.0
        return self._t

    def block_until(self, cond, predicate, timeout_s) -> bool:
        return bool(predicate())


class _Recorder:
    """Coordinator callbacks: where demuxed / locally-scored blocks
    land, in completion order."""

    def __init__(self):
        self.demuxed = []  # (block label, rows) in demux order
        self.local = []  # block labels completed via local fallback

    def demux(self, rows, block):
        self.demuxed.append((block.label, rows))

    def local_score(self, block):
        self.local.append(block.label)


class _ModelBlock:
    """The minimal superblock the coordinator's offer path can post:
    one row, so worker results are shape ``(1, 3)`` int64 and carry
    ``(worker idx, epoch, 0)`` as verifiable provenance."""

    def __init__(self):
        self.label = "?"
        self.weights = [1]
        self.seq1_codes = [1]
        self.codes = [[1]]


class _ModelWorker:
    """One worker's local state.  The board verbs and the key schema
    are the REAL ones (resilience/membership.py) — only the scoring is
    modelled (provenance rows instead of an alignment)."""

    def __init__(self, idx: int):
        self.idx = idx
        self.wid = f"mw{idx}"
        self.beats = 0
        self.claimed: dict[str, int] = {}  # bid -> claimed epoch


class _FleetState:
    """One replay's world: the real board/coordinator plus the
    invariant-checking ledgers."""

    def __init__(self):
        self.board = None
        self.coord = None
        self.workers = []
        self.recorder = None
        self.bids = []
        self.ledger = {}  # bid -> newest epoch ever offered
        self.seen_dead = set()
        self.winners = {}  # (bid, epoch) -> wid
        self.checked = 0  # demux records already invariant-checked


class FleetScenario:
    """The lease/epoch protocol under exploration."""

    def __init__(self, name: str, *, workers: int = 2, stale: bool = False,
                 lease_ticks: int | None = None,
                 seed_admit_bug: bool = False):
        self.name = name
        self.n_workers = int(workers)
        self.stale = bool(stale)
        self.lease_ticks = lease_ticks
        self.seed_admit_bug = bool(seed_admit_bug)
        self.invariants = (
            "demux-exactly-once",
            "fenced-epoch-never-admitted",
            "dead-worker-never-resurrected",
            "no-reply-dropped",
        )

    # -- world construction ------------------------------------------------

    def fresh(self) -> _FleetState:
        state = _FleetState()
        state.board = MemoryBoard()
        state.recorder = _Recorder()
        coord = FleetCoordinator(
            state.board,
            local_score=state.recorder.local_score,
            demux=state.recorder.demux,
            clock=VirtualClock(),
            lease_s=2.0,
            poll_s=1.0,  # lease_ticks = deadline_ticks = 2
        )
        if self.lease_ticks is not None:
            coord.leases.lease_ticks = int(self.lease_ticks)
        state.coord = coord
        state.workers = [_ModelWorker(i) for i in range(self.n_workers)]
        for w in state.workers:
            state.board.post(worker_key(w.wid), json.dumps({"wid": w.wid}))
            w.beats = 1
            state.board.post(heartbeat_key(w.wid), str(w.beats))
        coord.pump(idle=True)  # tick 1: every worker joins
        block = _ModelBlock()
        bid = coord.offer(block)
        block.label = bid
        state.bids = [bid]
        state.ledger = {bid: 0}
        if self.seed_admit_bug:
            # The seeded fencing bug the acceptance criteria demand: an
            # admit that ignores the epoch.  Instance-attribute override
            # of the REAL predicate — everything else runs unmodified.
            leases = coord.leases
            coord.leases.admits = (
                lambda bid, epoch, _t=leases: bid in _t._leases
            )
        return state

    # -- the event alphabet ------------------------------------------------

    def enabled(self, state: _FleetState):
        evs = ["tick"]
        board = state.board
        for w in state.workers:
            evs.append(f"w{w.idx}.beat")
            for bid in state.bids:
                offer = board_read_json(board, offer_key(bid))
                epoch = offer.get("epoch") if offer else None
                if (
                    offer is not None
                    and isinstance(epoch, int)
                    and w.claimed.get(bid) != epoch
                    and board.get(claim_key(bid, epoch)) is None
                    and board.get(result_key(bid, epoch)) is None
                ):
                    evs.append(f"w{w.idx}.claim")
                if bid in w.claimed and board.get(
                    result_key(bid, w.claimed[bid])
                ) is None:
                    evs.append(f"w{w.idx}.post")
                if (
                    self.stale
                    and bid in w.claimed
                    and offer is not None
                    and isinstance(epoch, int)
                    and epoch > w.claimed[bid]
                    and board.get(result_key(bid, epoch)) is None
                ):
                    evs.append(f"w{w.idx}.stale")
        return evs

    def execute(self, state: _FleetState, ev: str) -> None:
        if ev == "tick":
            state.coord.pump(idle=True)
            return
        widx, verb = ev.split(".", 1)
        w = state.workers[int(widx[1:])]
        board = state.board
        bid = state.bids[0]
        if verb == "beat":
            w.beats += 1
            board.post(heartbeat_key(w.wid), str(w.beats))
        elif verb == "claim":
            offer = board_read_json(board, offer_key(bid))
            epoch = int(offer["epoch"])
            if board.claim(
                claim_key(bid, epoch),
                json.dumps({"wid": w.wid, "epoch": epoch}),
            ):
                if (bid, epoch) in state.winners:
                    raise InterleaveViolation(
                        f"two claim winners for {bid} epoch {epoch}: "
                        f"{state.winners[(bid, epoch)]} and {w.wid}"
                    )
                state.winners[(bid, epoch)] = w.wid
                w.claimed[bid] = epoch
        elif verb == "post":
            epoch = w.claimed[bid]
            board.post(
                result_key(bid, epoch),
                json.dumps({
                    "bid": bid, "epoch": epoch, "wid": w.wid,
                    "rows": [[w.idx, epoch, 0]],
                }),
            )
        elif verb == "stale":
            # Re-post the rows scored under the OLD claimed epoch at the
            # CURRENT offer's result key: key recomputed, payload stale.
            offer = board_read_json(board, offer_key(bid))
            cur = int(offer["epoch"])
            old = w.claimed[bid]
            board.post(
                result_key(bid, cur),
                json.dumps({
                    "bid": bid, "epoch": old, "wid": w.wid,
                    "rows": [[w.idx, old, 0]],
                }),
            )
        else:
            raise InterleaveViolation(f"unknown event {ev!r} (model bug)")

    # -- invariants --------------------------------------------------------

    def check(self, state: _FleetState, schedule) -> None:
        rec = state.recorder
        for label, rows in rec.demuxed[state.checked:]:
            epoch = int(rows[0][1])
            if epoch != state.ledger[label]:
                raise InterleaveViolation(
                    f"fenced-epoch post ADMITTED: block {label} demuxed "
                    f"rows carrying epoch {epoch}, newest offered epoch "
                    f"is {state.ledger[label]} — LeaseTable.admits must "
                    f"fence it; schedule={list(schedule)}"
                )
        state.checked = len(rec.demuxed)
        done: dict[str, int] = {}
        for label, _rows in rec.demuxed:
            done[label] = done.get(label, 0) + 1
        for label in rec.local:
            done[label] = done.get(label, 0) + 1
        for label, n in done.items():
            if n > 1:
                raise InterleaveViolation(
                    f"block {label} completed {n} times (demux/local) — "
                    f"exactly-once broken; schedule={list(schedule)}"
                )
        for wid, view in state.coord.membership.workers.items():
            if not view.alive:
                state.seen_dead.add(wid)
            elif wid in state.seen_dead:
                raise InterleaveViolation(
                    f"dead worker {wid} RESURRECTED after its death "
                    f"verdict; schedule={list(schedule)}"
                )
        for bid in state.bids:
            offer = board_read_json(state.board, offer_key(bid))
            if offer is not None and isinstance(offer.get("epoch"), int):
                state.ledger[bid] = max(state.ledger[bid], offer["epoch"])

    def finish(self, state: _FleetState, schedule) -> None:
        """Leaf closure: freeze the workers, pump until every block
        drains (death verdicts → re-dispatch → local fallback), then
        require exactly one completion per block."""
        ticks = 0
        while state.coord.blocks and ticks < _QUIESCE_TICKS:
            self.execute(state, "tick")
            self.check(state, schedule)
            ticks += 1
        if state.coord.blocks:
            raise InterleaveViolation(
                f"reply DROPPED: blocks {sorted(state.coord.blocks)} "
                f"still outstanding after {_QUIESCE_TICKS} quiescence "
                f"ticks; schedule={list(schedule)}"
            )
        done: dict[str, int] = {}
        for label, _rows in state.recorder.demuxed:
            done[label] = done.get(label, 0) + 1
        for label in state.recorder.local:
            done[label] = done.get(label, 0) + 1
        for bid in state.bids:
            if done.get(bid, 0) != 1:
                raise InterleaveViolation(
                    f"block {bid} completed {done.get(bid, 0)} times at "
                    f"quiescence (want exactly 1); "
                    f"schedule={list(schedule)}"
                )

    # -- independence (sleep-set pruning) ----------------------------------

    def _actor(self, ev: str) -> str:
        return "coord" if ev == "tick" else ev.split(".", 1)[0]

    def _footprint(self, ev: str):
        if ev == "tick":
            return {"*"}
        _w, verb = ev.split(".", 1)
        if verb == "beat":
            return {f"hb/{_w}"}
        return {"blk"}  # claim/post/stale all race on the block's keys

    def independent(self, a: str, b: str) -> bool:
        if self._actor(a) == self._actor(b):
            return False
        fa, fb = self._footprint(a), self._footprint(b)
        if "*" in fa or "*" in fb:
            return False
        return not (fa & fb)


class _FailoverState:
    """One failover replay's world: the board, every coordinator that
    has ever led (the original plus each takeover's successor), the
    standby leases, and the invariant ledgers."""

    def __init__(self):
        self.board = None
        self.coords = []  # [{coord, rec, lease, gen, halted, answered}]
        self.standbys = []  # [{lease, ticks, taken (coord entry | None)}]
        self.workers = []
        self.crashed = False  # the original leader was killed
        self.gen_winners = {}  # gen -> winning lid (single-leader ledger)
        self.seen_done = {}  # request id -> completion count, cumulative


class FleetFailoverScenario:
    """Coordinator failover (PR 16) under exploration: the REAL
    :class:`~..resilience.membership.LeaderLease`,
    checkpoint/:func:`~..resilience.membership.read_checkpoint` replay,
    and generation fencing, with a leader ``crash`` event in the
    alphabet and TWO standbys racing ``try_acquire`` so the
    single-leader invariant is a genuine race, not a tautology.

    One request (id ``r1``) flows through: the original leader offers
    its superblock and checkpoints (the post-ingest checkpoint the serve
    loop writes before its first tick); any schedule may then kill the
    leader, starve it (the zombie shape — a leader the scheduler never
    runs again is indistinguishable from a hung one), or let it finish.
    A standby whose watch verdict lands claims the next generation,
    replays the predecessor's checkpoint (skipping answered ids), and
    re-offers.  Block labels are REQUEST ids, not bids, so completions
    aggregate across generations — the duplicate check spans every
    coordinator that ever led.
    """

    #: The admitted-request journal this run would checkpoint.
    REQUESTS = ({"id": "r1"},)
    #: Standby watch deadline (ticks) — matches lease_s/poll_s below.
    DEADLINE_TICKS = 2

    def __init__(self, name: str = "fleet-failover", *, standbys: int = 2):
        self.name = name
        self.n_standbys = int(standbys)
        self.invariants = (
            "single-leader-per-generation",
            "no-reply-duplicated",
            "no-reply-dropped",
        )

    # -- world construction ------------------------------------------------

    def _new_leader(self, state: _FailoverState, lease) -> dict:
        rec = _Recorder()
        coord = FleetCoordinator(
            state.board,
            local_score=rec.local_score,
            demux=rec.demux,
            clock=VirtualClock(),
            lease_s=2.0,
            poll_s=1.0,  # lease_ticks = 2, same window as DEADLINE_TICKS
            leader=lease,
        )
        return {
            "coord": coord, "rec": rec, "lease": lease,
            "gen": lease.gen, "halted": False, "answered": set(),
        }

    def fresh(self) -> _FailoverState:
        state = _FailoverState()
        state.board = MemoryBoard()
        lease = LeaderLease(state.board, "lead", self.DEADLINE_TICKS)
        gen = lease.acquire()  # virgin board: wins generation 0
        state.gen_winners[gen] = lease.lid
        cx = self._new_leader(state, lease)
        state.coords.append(cx)
        state.workers = [_ModelWorker(0)]
        for w in state.workers:
            state.board.post(worker_key(w.wid), json.dumps({"wid": w.wid}))
            w.beats = 1
            state.board.post(heartbeat_key(w.wid), str(w.beats))
        cx["coord"].pump(idle=True)  # tick 1: the worker joins
        self._offer_requests(cx, set())
        self._ckpt(cx)  # the post-ingest checkpoint, pre first tick
        state.standbys = [
            {
                "lease": LeaderLease(
                    state.board, f"sb{i}", self.DEADLINE_TICKS
                ),
                "ticks": 0,
                "taken": None,
            }
            for i in range(self.n_standbys)
        ]
        return state

    def _offer_requests(self, cx: dict, answered: set) -> None:
        for raw in self.REQUESTS:
            if raw["id"] in answered:
                continue
            block = _ModelBlock()
            cx["coord"].offer(block)
            block.label = raw["id"]

    def _ckpt(self, cx: dict) -> None:
        unanswered = [
            dict(raw) for raw in self.REQUESTS
            if raw["id"] not in cx["answered"]
        ]
        cx["coord"].checkpoint(unanswered, sorted(cx["answered"]))

    # -- per-coordinator steps ---------------------------------------------

    def _leader_tick(self, state: _FailoverState, cx: dict) -> None:
        """One serve tick of an incumbent: pump (which self-deposes on a
        higher generation BEFORE collecting anything), fold this tick's
        completions into the answered set, checkpoint.  Pump + checkpoint
        are one event — the model's atomicity grain is the tick boundary,
        exactly the exactly-once boundary ARCHITECTURE §8.6 documents."""
        try:
            cx["coord"].pump(idle=True)
        except LeadershipLostError:
            cx["halted"] = True
            return
        rec = cx["rec"]
        for label, _rows in rec.demuxed:
            cx["answered"].add(label)
        for label in rec.local:
            cx["answered"].add(label)
        self._ckpt(cx)

    def _sb_tick(self, state: _FailoverState, i: int, schedule) -> None:
        """One standby watch tick; after this standby has taken over, its
        ticks ARE the successor coordinator's serve ticks."""
        sb = state.standbys[i]
        if sb["taken"] is not None:
            self._leader_tick(state, sb["taken"])
            return
        sb["ticks"] += 1
        lease = sb["lease"]
        if not lease.observe(sb["ticks"]):
            return
        watched = lease.watched_gen()
        if watched is None or not lease.try_acquire(watched + 1):
            return  # a rival won this generation; the watch restarts
        gen = lease.gen
        if gen in state.gen_winners:
            raise InterleaveViolation(
                f"TWO leaders for generation {gen}: "
                f"{state.gen_winners[gen]} and {lease.lid} — the claim "
                f"primitive must admit exactly one; "
                f"schedule={list(schedule)}"
            )
        state.gen_winners[gen] = lease.lid
        cx = self._new_leader(state, lease)
        ckpt = read_checkpoint(state.board, watched)
        if ckpt is not None:
            cx["answered"] = set(ckpt["answered"])
        state.coords.append(cx)
        sb["taken"] = cx
        cx["coord"].pump(idle=True)  # tick 1: workers re-join
        self._offer_requests(cx, cx["answered"])
        self._ckpt(cx)  # re-checkpoint under the successor's generation

    def _active(self, state: _FailoverState) -> dict | None:
        live = [cx for cx in state.coords if not cx["halted"]]
        return max(live, key=lambda cx: cx["gen"]) if live else None

    def _completions(self, state: _FailoverState) -> dict:
        done: dict[str, int] = {}
        for cx in state.coords:
            for label, _rows in cx["rec"].demuxed:
                done[label] = done.get(label, 0) + 1
            for label in cx["rec"].local:
                done[label] = done.get(label, 0) + 1
        return done

    def _offers(self, board) -> list:
        out = []
        for key in sorted(board.keys(OFFER_PREFIX)):
            offer = board_read_json(board, key)
            if (
                offer is not None
                and isinstance(offer.get("bid"), str)
                and isinstance(offer.get("epoch"), int)
            ):
                out.append(offer)
        return out

    # -- the event alphabet ------------------------------------------------

    def enabled(self, state: _FailoverState):
        evs = []
        original = state.coords[0]
        if not state.crashed and not original["halted"]:
            evs.append("tick")
            evs.append("crash")
        for i, sb in enumerate(state.standbys):
            if sb["taken"] is None or not sb["taken"]["halted"]:
                evs.append(f"sb{i}.tick")
        board = state.board
        for w in state.workers:
            evs.append(f"w{w.idx}.beat")
            can_claim = can_post = False
            for offer in self._offers(board):
                bid, epoch = offer["bid"], int(offer["epoch"])
                if (
                    w.claimed.get(bid) != epoch
                    and board.get(claim_key(bid, epoch)) is None
                    and board.get(result_key(bid, epoch)) is None
                ):
                    can_claim = True
                if (
                    w.claimed.get(bid) is not None
                    and board.get(result_key(bid, w.claimed[bid])) is None
                ):
                    can_post = True
            if can_claim:
                evs.append(f"w{w.idx}.claim")
            if can_post:
                evs.append(f"w{w.idx}.post")
        return evs

    def execute(self, state: _FailoverState, ev: str, schedule=()) -> None:
        if ev == "tick":
            self._leader_tick(state, state.coords[0])
            return
        if ev == "crash":
            # kill -9: the original leader stops mid-run.  Its board
            # state (offer, claim, beat, checkpoint) stays exactly as
            # posted — that debris is what fencing and GC exist for.
            state.crashed = True
            state.coords[0]["halted"] = True
            return
        actor, verb = ev.split(".", 1)
        if actor.startswith("sb"):
            self._sb_tick(state, int(actor[2:]), schedule)
            return
        w = state.workers[int(actor[1:])]
        board = state.board
        if verb == "beat":
            w.beats += 1
            board.post(heartbeat_key(w.wid), str(w.beats))
        elif verb == "claim":
            # First eligible offer in key order — deterministic, and
            # recomputed here so enabled() and execute() agree.
            for offer in self._offers(board):
                bid, epoch = offer["bid"], int(offer["epoch"])
                if (
                    w.claimed.get(bid) != epoch
                    and board.get(claim_key(bid, epoch)) is None
                    and board.get(result_key(bid, epoch)) is None
                ):
                    if board.claim(
                        claim_key(bid, epoch),
                        json.dumps({"wid": w.wid, "epoch": epoch}),
                    ):
                        w.claimed[bid] = epoch
                    return
        elif verb == "post":
            for bid, epoch in sorted(w.claimed.items()):
                if board.get(result_key(bid, epoch)) is None:
                    board.post(
                        result_key(bid, epoch),
                        json.dumps({
                            "bid": bid, "epoch": epoch, "wid": w.wid,
                            "rows": [[w.idx, epoch, 0]],
                        }),
                    )
                    return
        else:
            raise InterleaveViolation(f"unknown event {ev!r} (model bug)")

    # -- invariants --------------------------------------------------------

    def check(self, state: _FailoverState, schedule) -> None:
        done = self._completions(state)
        for label, n in done.items():
            if n > 1:
                raise InterleaveViolation(
                    f"reply DUPLICATED: request {label} completed {n} "
                    f"times across leader generations — the answered-id "
                    f"replay filter or generation fencing is broken; "
                    f"schedule={list(schedule)}"
                )
        state.seen_done = done

    def finish(self, state: _FailoverState, schedule) -> None:
        """Leaf closure: freeze the worker, then drive whoever should be
        driving — the highest-generation live coordinator if one exists,
        else the next standby's watch — until the request completes and
        the active coordinator drains.  Hitting the bound IS the
        dropped-reply violation; a world with every coordinator halted
        and no standby left is the (worse) leaderless violation."""
        ticks = 0
        while ticks < _QUIESCE_TICKS:
            done = self._completions(state)
            active = self._active(state)
            if (
                all(done.get(raw["id"], 0) == 1 for raw in self.REQUESTS)
                and (active is None or not active["coord"].blocks)
            ):
                return
            if active is not None:
                self._leader_tick(state, active)
            else:
                idle = next(
                    (
                        i for i, sb in enumerate(state.standbys)
                        if sb["taken"] is None
                    ),
                    None,
                )
                if idle is None:
                    raise InterleaveViolation(
                        f"LEADERLESS: every coordinator halted and no "
                        f"standby remains to take over; "
                        f"schedule={list(schedule)}"
                    )
                self._sb_tick(state, idle, schedule)
            self.check(state, schedule)
            ticks += 1
        done = self._completions(state)
        raise InterleaveViolation(
            f"reply DROPPED: completions {done} after {_QUIESCE_TICKS} "
            f"quiescence ticks (want exactly one per request); "
            f"schedule={list(schedule)}"
        )

    # -- independence (sleep-set pruning) ----------------------------------

    def _actor(self, ev: str) -> str:
        if ev in ("tick", "crash"):
            return "lead"
        return ev.split(".", 1)[0]

    def _footprint(self, ev: str):
        if ev == "crash":
            # The crash flips only the original leader's halted flag —
            # it writes nothing to the board, so it commutes with every
            # event except that leader's own tick (actor rule).
            return {"lead"}
        if ev == "tick" or ev.startswith("sb"):
            return {"*"}  # board polls read everything
        _w, verb = ev.split(".", 1)
        if verb == "beat":
            return {f"hb/{_w}"}
        return {"blk"}

    def independent(self, a: str, b: str) -> bool:
        if self._actor(a) == self._actor(b):
            return False
        fa, fb = self._footprint(a), self._footprint(b)
        if "*" in fa or "*" in fb:
            return False
        return not (fa & fb)


class QueueScenario:
    """The RequestQueue under exploration: three submitting clients, the
    popping loop, drain close, and source close, interleaved every way.
    Invariants: every admitted request is delivered exactly once (pop or
    drain), rejected requests never appear, sequence ids are unique,
    depth never exceeds ``max_depth``, and a submit after ``close()``
    is always verdict ``closed``."""

    MAX_DEPTH = 2
    CLIENTS = 3

    def __init__(self, name: str = "request-queue"):
        self.name = name
        self.invariants = (
            "admitted-delivered-exactly-once",
            "rejected-never-delivered",
            "seq-unique",
            "depth-bounded",
            "closed-means-closed",
        )

    def fresh(self):
        state = {
            "queue": RequestQueue(self.MAX_DEPTH, VirtualClock()),
            "tokens": [object() for _ in range(self.CLIENTS)],
            "verdicts": {},  # client idx -> ADMIT_* verdict
            "popped": [],
            "closed": False,
            "close_src_done": False,
        }
        state["queue"].open_source()
        return state

    def enabled(self, state):
        evs = []
        for i in range(self.CLIENTS):
            if i not in state["verdicts"]:
                evs.append(f"s{i}.submit")
        evs.append("pop")
        if not state["closed"]:
            evs.append("close")
        if not state["close_src_done"]:
            evs.append("close_src")
        return evs

    def execute(self, state, ev: str) -> None:
        q = state["queue"]
        if ev == "pop":
            state["popped"].extend(q.pop_ready(0.0, 0.0))
        elif ev == "close":
            state["closed"] = True
            q.close()
        elif ev == "close_src":
            state["close_src_done"] = True
            q.close_source()
        else:
            i = int(ev.split(".", 1)[0][1:])
            was_closed = state["closed"]
            verdict = q.submit({"id": f"c{i}"}, state["tokens"][i])
            state["verdicts"][i] = verdict
            if was_closed and verdict != ADMIT_CLOSED:
                raise InterleaveViolation(
                    f"submit after close() returned {verdict!r}, want "
                    f"{ADMIT_CLOSED!r}"
                )

    def check(self, state, schedule) -> None:
        depth = state["queue"].depth()
        if depth > self.MAX_DEPTH:
            raise InterleaveViolation(
                f"queue depth {depth} exceeds max_depth "
                f"{self.MAX_DEPTH}; schedule={list(schedule)}"
            )

    def finish(self, state, schedule) -> None:
        drained = state["queue"].drain_pending()
        out = list(state["popped"]) + list(drained)
        seqs = [r.seq for r in out]
        if len(set(seqs)) != len(seqs):
            raise InterleaveViolation(
                f"duplicate sequence ids {sorted(seqs)}; "
                f"schedule={list(schedule)}"
            )
        by_token = {}
        for r in out:
            by_token[id(r.responder)] = by_token.get(id(r.responder), 0) + 1
        for i, verdict in state["verdicts"].items():
            n = by_token.get(id(state["tokens"][i]), 0)
            want = 1 if verdict == ADMIT_OK else 0
            if n != want:
                raise InterleaveViolation(
                    f"client {i} verdict {verdict!r} delivered {n} "
                    f"time(s), want {want}; schedule={list(schedule)}"
                )

    def independent(self, a: str, b: str) -> bool:
        return False  # one shared queue: every pair of events conflicts


# -- the explorer ----------------------------------------------------------


def explore(scenario, depth: int) -> dict:
    """Exhaustive sleep-set DFS over ``scenario`` to ``depth`` events.

    Stateless replay: every node rebuilds the world from scratch and
    re-executes its prefix, so the real classes mutate real state with
    no copying.  Returns the stats dict (schedules / transitions /
    pruned / violations); exploration stops at the FIRST violating
    schedule — a model checker's job is the counterexample."""
    stats = {
        "name": scenario.name,
        "depth": int(depth),
        "schedules": 0,
        "transitions": 0,
        "pruned": 0,
        "violations": [],
        "invariants": list(scenario.invariants),
    }

    def recurse(prefix, sleep):
        state = scenario.fresh()
        for ev in prefix:
            scenario.execute(state, ev)
            stats["transitions"] += 1
            scenario.check(state, prefix)
        enabled = scenario.enabled(state)
        if len(prefix) >= depth or not enabled:
            scenario.finish(state, prefix)
            stats["schedules"] += 1
            return
        explored = []
        for ev in enabled:
            if ev in sleep:
                stats["pruned"] += 1
                continue
            child_sleep = {
                s for s in (sleep | set(explored))
                if scenario.independent(s, ev)
            }
            recurse(prefix + [ev], child_sleep)
            explored.append(ev)

    try:
        # The coordinator narrates joins/deaths/redispatches on stderr
        # (obs.events.log_line); thousands of replays must not flood the
        # terminal — the bus itself stays unarmed, nothing else changes.
        with contextlib.redirect_stderr(io.StringIO()):
            recurse([], set())
    except InterleaveViolation as exc:
        stats["violations"].append(str(exc))
    return stats


#: The committed exploration matrix (golden-pinned, >1000 schedules).
#: fleet-races: two workers racing one offer — claim exclusivity,
#:   exactly-once under death/expiry re-dispatch.
#: fleet-fencing: one worker with the adversarial stale re-post enabled
#:   and lease_ticks=1, deep enough that claim → expiry → re-offer →
#:   stale post → collect all fit inside the depth bound.
#: fleet-failover: leader crash/starvation with two standbys racing the
#:   next generation — single-leader-per-generation, checkpoint-replay
#:   exactly-once, takeover within the watch deadline (PR 16).
#: request-queue: admission/pop/close/close-source interleavings.
def scenarios():
    return [
        (FleetScenario("fleet-races", workers=2), 6),
        (
            FleetScenario(
                "fleet-fencing", workers=1, stale=True, lease_ticks=1
            ),
            8,
        ),
        (FleetFailoverScenario(), 6),
        (QueueScenario(), 6),
    ]


def run_all() -> dict:
    """Explore every committed scenario; the concurrency-audit report's
    ``interleave`` section."""
    rows = [explore(scn, depth) for scn, depth in scenarios()]
    return {
        "scenarios": rows,
        "total_schedules": sum(r["schedules"] for r in rows),
        "total_transitions": sum(r["transitions"] for r in rows),
    }


def run_or_raise() -> dict:
    """Driver entry: explore, raise :class:`InterleaveViolation` on any
    violating schedule, return the report section when clean."""
    report = run_all()
    bad = [
        f"[{r['name']}] {v}"
        for r in report["scenarios"]
        for v in r["violations"]
    ]
    if bad:
        raise InterleaveViolation(
            "interleave: protocol invariant violated:\n  "
            + "\n  ".join(bad)
        )
    return report
