"""Value-range certifier: abstract interpretation over the scoring jaxprs.

Every exactness promise the TPU port makes — f32 prefix partials below
2^24, HIGHEST-matmul operands below 2^16, the packed argmax inside
int32 — used to rest on hand-derived constants asserted at dispatch
time.  This pass *proves* them: each entry point (the five
``contracts.ENTRY_CONTRACTS`` plus every resolved production-bucket
body) is lowered to a jaxpr and abstractly interpreted in an interval
domain seeded from the contract's input envelopes (sequence codes in
[0, 26], lengths in [0, L], weights in [-maxv, maxv]).  The transfer
functions cover the scoring vocabulary:

* arithmetic (add/sub/mul/div/min/max/clamp/select) on exact integer
  endpoints, with a **sentinel band**: constants at or below
  ``-(2^29)`` (the kernels' masked-lane floors ``-2^40``, ``-(2^30)``,
  ``-(2^31 - 1)``, ``INT32_MIN``) are tracked out-of-band, so one
  masked lane does not smear the live score interval;
* ``dot_general`` with the accumulator bound ``K * max|a| * max|b|``
  and a **one-hot refinement**: operands built from ``codes == iota``
  are partition-of-unity along the compared axis, so contracting over
  that axis bounds the result by the OTHER operand's range — exactly
  the hand argument for ``V = onehot(seq2) @ (val @ onehot(seq1).T)``;
* ``convert_element_type`` as a containment check — the target dtype's
  window (exact-integer window for floats) must contain the operand's
  live band, else a typed ``lossy-narrowing`` finding; sentinel bands
  discharge to the full target window (they are masked by construction
  and the window covers every wrap/saturate outcome);
* ``scan`` / ``while`` by bounded abstract iteration when a static trip
  bound is visible (the lowered ``fori_loop`` pattern), falling back to
  widening-to-fixpoint; float loop carries are recorded as
  accumulators;
* ``pallas_call`` by recursing into the kernel jaxpr: refs become
  join-cells, the grid is a fixpoint over the cell state, and the
  in-kernel ``get``/``swap`` state primitives read/update the cells;
* a **congruence refinement** (value = stride * q + r) threaded through
  ``mul``-by-constant and ``add``, proving the packed-argmax decode
  (``// 4096`` and ``& 4095``) lossless;
* unknown primitives fail closed: the result is the dtype's full
  window and an ``unknown-primitive`` finding is emitted.

The emitted ``RangeCert`` carries, per entry/bucket/envelope, the
proved accumulator interval against the dtype and f32 exact-integer
windows plus a verdict, then *re-derives* every hand constant
(``max_exact_value(l2p)``, the 4095/32767 ceilings, the 2^19 rowpack
gate, the 4096 argmax radix and its 2^31 bound, the feed thresholds)
and diffs each against its wired value in ``ops/bounds.py`` — drift is
a ``constant-drift`` finding.  A ``signed_weights`` section runs the
same entries under the full int16 envelope ``[-32768, 32767]`` and
documents which paths survive negative weights (the ROADMAP item 4
BLOSUM/PAM prerequisite).  ``scripts/ranges_audit.py`` diffs the cert
against ``tests/golden/ranges_cert.json``; ``run_or_raise`` backs the
``make analyze`` pass.
"""

from __future__ import annotations

import dataclasses
import math

from . import RangeCertError

#: Bands wholly at or below this are "sentinel": deliberate out-of-band
#: masked-lane floors, not live scores.  Every kernel sentinel (-2^40,
#: -(2^30), -(2^31 - 1), INT32_MIN) sits below it, and every live score
#: (bounded by l2p * max|v| <= 2048 * 32767 < 2^27) sits far above.
_SENTINEL_FLOOR = -(1 << 29)

#: Loops with a visible static trip bound at or below this are iterated
#: abstractly step by step (exact accumulation bounds); longer or
#: unbounded loops take widening-to-fixpoint.
_MAX_TRIP_UNROLL = 512

#: Hard budget on abstractly evaluated equations per entry row — a
#: runaway recursion aborts the row instead of hanging the audit.
_EQN_BUDGET = 2_000_000

_INF = math.inf


# --------------------------------------------------------------------------
# Interval domain
# --------------------------------------------------------------------------


def _mulc(a, b):
    """inf-safe product with 0 * inf == 0."""
    if a == 0 or b == 0:
        return 0
    return a * b


@dataclasses.dataclass(frozen=True)
class Interval:
    """A closed interval with exact (Python int / float) endpoints."""

    lo: float
    hi: float

    def join(self, o: "Interval") -> "Interval":
        return Interval(min(self.lo, o.lo), max(self.hi, o.hi))

    def add(self, o: "Interval") -> "Interval":
        return Interval(self.lo + o.lo, self.hi + o.hi)

    def sub(self, o: "Interval") -> "Interval":
        return Interval(self.lo - o.hi, self.hi - o.lo)

    def neg(self) -> "Interval":
        return Interval(-self.hi, -self.lo)

    def mul(self, o: "Interval") -> "Interval":
        cands = [
            _mulc(self.lo, o.lo),
            _mulc(self.lo, o.hi),
            _mulc(self.hi, o.lo),
            _mulc(self.hi, o.hi),
        ]
        return Interval(min(cands), max(cands))

    def max_(self, o: "Interval") -> "Interval":
        return Interval(max(self.lo, o.lo), max(self.hi, o.hi))

    def min_(self, o: "Interval") -> "Interval":
        return Interval(min(self.lo, o.lo), min(self.hi, o.hi))

    def scale_sum(self, n: int) -> "Interval":
        """Bound on a sum of up to ``n`` terms each drawn from self
        (prefix-sum semantics: any count from 0 to n)."""
        return Interval(min(0, _mulc(n, self.lo)), max(0, _mulc(n, self.hi)))

    def contains(self, o: "Interval") -> bool:
        return self.lo <= o.lo and o.hi <= self.hi

    def max_abs(self) -> float:
        return max(abs(self.lo), abs(self.hi))

    def is_const(self) -> bool:
        return self.lo == self.hi


def _iv(lo, hi) -> Interval:
    return Interval(lo, hi)


# --------------------------------------------------------------------------
# dtype windows
# --------------------------------------------------------------------------

#: mantissa bits INCLUDING the implicit leading bit: integers with
#: |x| <= 2^bits are exactly representable.
_MANTISSA_BITS = {
    "float64": 53,
    "float32": 24,
    "bfloat16": 8,
    "float16": 11,
}

_FLOAT_MAX = {
    "float64": 1.7976931348623157e308,
    "float32": 3.4028234663852886e38,
    "bfloat16": 3.3895313892515355e38,
    "float16": 65504.0,
}


def dtype_window(dtype) -> Interval:
    """The representable window of a dtype (ints: exact integer bounds;
    floats: finite range; bool: [0, 1])."""
    import numpy as np

    name = str(np.dtype(dtype)) if str(dtype) != "bfloat16" else "bfloat16"
    if name == "bool":
        return _iv(0, 1)
    if name in _FLOAT_MAX:
        m = _FLOAT_MAX[name]
        return _iv(-m, m)
    info = np.iinfo(np.dtype(dtype))
    return _iv(int(info.min), int(info.max))


def exact_window(dtype) -> Interval:
    """The window in which integer VALUES survive this dtype exactly:
    for floats the 2^mantissa exact-integer window (2^24 for f32 — the
    window every accumulation verdict is checked against), for ints the
    full representable range."""
    import numpy as np

    name = str(np.dtype(dtype)) if str(dtype) != "bfloat16" else "bfloat16"
    if name in _MANTISSA_BITS:
        m = 1 << _MANTISSA_BITS[name]
        return _iv(-m, m)
    return dtype_window(dtype)


def _is_float(dtype) -> bool:
    name = str(dtype)
    return name.startswith(("float", "bfloat"))


def _is_int(dtype) -> bool:
    name = str(dtype)
    return name.startswith(("int", "uint"))


# --------------------------------------------------------------------------
# Abstract values
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class AbsVal:
    """Abstract value: a live interval band, an optional sentinel band
    (entirely at or below ``_SENTINEL_FLOOR``), and refinements — axes
    along which AT MOST ONE element is nonzero (``onehot``; a partition
    of unity when the bands also sit in [0, 1]), the iota axis (value
    == index), and a congruence (value = stride*q + r, r in ``rem``)
    for packed encodings."""

    iv: Interval | None
    sent: Interval | None = None
    onehot: frozenset = frozenset()
    iota_axis: int | None = None
    stride: int | None = None
    rem: Interval | None = None
    #: identity tag linking a broadcast ``reduce_max``/``reduce_min``
    #: back to its operand, so ``eq(x, broadcast(reduce_max(x)))`` is
    #: recognisable as a mask with AT LEAST ONE hit per reduced slice.
    origin: tuple | None = dataclasses.field(default=None, compare=False)
    #: axes along which at least one element provably comes from
    #: ``pick``'s interval (set on ``where(argmax_mask, v, default)``) —
    #: lets reduce_min/reduce_max ignore the never-chosen default.
    hasone: frozenset = dataclasses.field(default=frozenset(), compare=False)
    pick: Interval | None = dataclasses.field(default=None, compare=False)

    def bands(self):
        out = []
        if self.iv is not None:
            out.append(self.iv)
        if self.sent is not None:
            out.append(self.sent)
        return out

    def flat(self) -> Interval:
        """Live and sentinel merged — for transfer rules where the
        separation carries no benefit."""
        bs = self.bands()
        if not bs:
            return _iv(0, 0)
        out = bs[0]
        for b in bs[1:]:
            out = out.join(b)
        return out

    def join(self, o: "AbsVal") -> "AbsVal":
        stride, rem = None, None
        if self.stride is not None and self.stride == o.stride:
            stride = self.stride
            rem = self.rem.join(o.rem) if (self.rem and o.rem) else None
            if rem is None or not _iv(0, stride - 1).contains(rem):
                stride, rem = None, None
        return _mk(
            self.bands() + o.bands(),
            onehot=self.onehot & o.onehot,
            iota_axis=self.iota_axis if self.iota_axis == o.iota_axis else None,
            stride=stride,
            rem=rem,
        )


def _mk(intervals, onehot=frozenset(), iota_axis=None, stride=None, rem=None):
    live, sent = None, None
    for it in intervals:
        if it.hi <= _SENTINEL_FLOOR:
            sent = it if sent is None else sent.join(it)
        else:
            live = it if live is None else live.join(it)
    return AbsVal(live, sent, onehot, iota_axis, stride, rem)


def _const_val(x) -> AbsVal:
    import numpy as np

    arr = np.asarray(x)
    if arr.size == 0:
        return AbsVal(_iv(0, 0))
    if arr.dtype == bool:
        return AbsVal(_iv(int(arr.min()), int(arr.max())))
    lo, hi = arr.min(), arr.max()
    if np.issubdtype(arr.dtype, np.integer):
        return _mk([_iv(int(lo), int(hi))])
    return _mk([_iv(float(lo), float(hi))])


def _top(aval) -> AbsVal:
    inner = getattr(aval, "inner_aval", aval)
    return AbsVal(dtype_window(inner.dtype))


class _RefCell:
    """Mutable join-cell standing for one pallas ref: ``get`` reads the
    cell, ``swap``/``addupdate`` join into it.  Cell identity flows
    through nested jaxprs like any other abstract value."""

    __slots__ = ("val", "aval")

    def __init__(self, val, aval):
        self.val = val
        self.aval = aval


@dataclasses.dataclass(frozen=True)
class RangeFinding:
    """One typed certifier finding."""

    kind: str  # unknown-primitive | lossy-narrowing | int-overflow |
    #            float-overflow | exactness-regression | constant-drift
    where: str
    detail: str

    def to_dict(self) -> dict:
        return {"kind": self.kind, "where": self.where, "detail": self.detail}


# --------------------------------------------------------------------------
# The interpreter
# --------------------------------------------------------------------------

_SHAPE_PASSTHRU = {
    "copy",
    "copy_p",
    "rev",
    "stop_gradient",
    "real",
    "reduce_precision",
    "optimization_barrier",
    "roll",
    "tpu_roll",
}

_CMP = {"eq", "ne", "lt", "le", "gt", "ge"}


class _Interp:
    """One abstract interpretation run over a closed jaxpr tree."""

    def __init__(self, where: str):
        self.where = where
        self.findings: list[RangeFinding] = []
        self.unknown: set[str] = set()
        self.float_accs: list[tuple[str, Interval]] = []
        self.int_accs: list[tuple[str, Interval]] = []
        self.widened = False
        self.decodes_proved = 0
        self.sentinel_casts = 0
        self.axis_sizes: dict = {}
        self.stack: list[str] = []
        self._budget = _EQN_BUDGET

    # -- plumbing ----------------------------------------------------------

    def _find(self, kind: str, detail: str) -> None:
        where = self.where
        if self.stack:
            where += " @ " + "/".join(self.stack)
        self.findings.append(RangeFinding(kind, where, detail))

    def _run_tagged(self, tag, jaxpr, consts, ins):
        self.stack.append(tag)
        try:
            return self.run(jaxpr, consts, ins)
        finally:
            self.stack.pop()

    def _read(self, env, v):
        from jax.core import Literal

        if isinstance(v, Literal):
            return _const_val(v.val)
        return env[v]

    def run(self, jaxpr, consts, invals):
        """Interpret a (raw) jaxpr given constvar and invar values."""
        env = {}
        for var, c in zip(jaxpr.constvars, consts):
            env[var] = c if isinstance(c, (AbsVal, _RefCell)) else _const_val(c)
        for var, v in zip(jaxpr.invars, invals):
            env[var] = v
        for eqn in jaxpr.eqns:
            self._budget -= 1
            if self._budget <= 0:
                raise RangeCertError(
                    f"{self.where}: abstract interpretation exceeded the "
                    f"{_EQN_BUDGET} equation budget — a loop failed to "
                    "converge; widen analysis/ranges.py's loop handling"
                )
            outs = self._eval_eqn(eqn, [self._read(env, v) for v in eqn.invars])
            for var, out in zip(eqn.outvars, outs):
                env[var] = self._check_window(eqn, var, out)
        return [self._read(env, v) for v in jaxpr.outvars]

    def _check_window(self, eqn, var, out):
        """Clamp raw result bands to the output dtype's window; a live
        band that escapes it is an overflow finding (ints can wrap,
        floats can lose everything)."""
        if isinstance(out, _RefCell):
            return out
        aval = getattr(var, "aval", None)
        if aval is None or not hasattr(aval, "dtype"):
            return out
        dt = getattr(aval, "dtype", None)
        if dt is None or not (_is_int(dt) or _is_float(dt)):
            return out
        win = dtype_window(dt)
        if out.iv is not None and not win.contains(out.iv):
            kind = "int-overflow" if _is_int(dt) else "float-overflow"
            self._find(
                kind,
                f"{eqn.primitive.name} -> {dt}: proved interval "
                f"[{out.iv.lo}, {out.iv.hi}] escapes the representable "
                f"window [{win.lo}, {win.hi}]",
            )
            out = dataclasses.replace(
                out,
                iv=_iv(max(out.iv.lo, win.lo), min(out.iv.hi, win.hi)),
                stride=None,
                rem=None,
            )
        return out

    def _sub_jaxpr(self, params, *keys):
        for k in keys:
            sub = params.get(k)
            if sub is None:
                continue
            if hasattr(sub, "jaxpr"):  # ClosedJaxpr
                return sub.jaxpr, list(sub.consts)
            if hasattr(sub, "eqns"):  # raw Jaxpr
                return sub, []
        return None, None

    # -- equation dispatch -------------------------------------------------

    def _eval_eqn(self, eqn, ins):
        name = eqn.primitive.name
        params = eqn.params
        out_aval = eqn.outvars[0].aval if eqn.outvars else None

        handler = getattr(self, "_p_" + name.replace("-", "_"), None)
        if handler is not None:
            return handler(eqn, ins)

        if name in _SHAPE_PASSTHRU:
            a = ins[0]
            return [
                dataclasses.replace(a, iota_axis=None, stride=None, rem=None)
            ] * len(eqn.outvars)
        if name in _CMP:
            return [self._cmp(name, eqn, ins)]

        jx, consts = self._sub_jaxpr(
            params, "jaxpr", "call_jaxpr", "fun_jaxpr", "cond_jaxpr"
        )
        if jx is not None and len(jx.invars) == len(ins):
            return self.run(jx, consts, ins)

        # Fail closed: dtype-window top for every output + typed finding.
        self.unknown.add(name)
        self._find(
            "unknown-primitive",
            f"no transfer function for primitive {name!r}: result assumed "
            "to span its dtype window (fail closed) — teach "
            "analysis/ranges.py this primitive",
        )
        return [_top(v.aval) for v in eqn.outvars]

    # -- comparisons / logicals -------------------------------------------

    def _cmp(self, name, eqn, ins):
        a, b = ins
        onehot = frozenset()
        hasone = frozenset()
        if name == "eq":
            if a.iota_axis is not None and b.iota_axis is None:
                onehot = frozenset({a.iota_axis})
            elif b.iota_axis is not None and a.iota_axis is None:
                onehot = frozenset({b.iota_axis})
            # eq(x, broadcast(reduce_max(x))): the max is attained, so
            # each slice along the reduced axes has at least one hit
            # (keepdims / [None, :] broadcasts put the residual axes
            # back at their original positions, making the reduced-axis
            # indices valid in the mask's frame).
            for x, y in ((a, b), (b, a)):
                o = y.origin
                if o is not None and o[0] == "rmax" and o[1] == id(x):
                    hasone = frozenset(o[2])
                    break
        return AbsVal(_iv(0, 1), onehot=onehot, hasone=hasone)

    # -- elementwise arithmetic -------------------------------------------

    def _binop(self, ins, f):
        a, b = ins
        return _mk([f(x, y) for x in a.bands() for y in b.bands()])

    def _p_add(self, eqn, ins):
        a, b = ins
        out = self._binop(ins, Interval.add)
        stride, rem = self._cong_add(a, b)
        return [dataclasses.replace(out, stride=stride, rem=rem)]

    def _p_sub(self, eqn, ins):
        return [self._binop(ins, Interval.sub)]

    def _p_mul(self, eqn, ins):
        a, b = ins
        out = self._binop(ins, Interval.mul)
        stride, rem = None, None
        dt = eqn.outvars[0].aval.dtype
        if _is_int(dt):
            for x, y in ((a, b), (b, a)):
                fy = y.flat()
                if fy.is_const() and fy.lo == int(fy.lo) and fy.lo > 1:
                    stride, rem = int(fy.lo), _iv(0, 0)
                    break
        return [dataclasses.replace(out, stride=stride, rem=rem)]

    def _cong_add(self, a, b):
        """stride/rem of a sum: a packed value plus a bounded key keeps
        its stride when the combined remainder still fits one field."""
        for x, y in ((a, b), (b, a)):
            if x.stride is None or y.iv is None:
                continue
            xr = x.rem if x.rem is not None else _iv(0, 0)
            yr = y.rem if (y.stride == x.stride and y.rem is not None) else y.iv
            if y.stride not in (None, x.stride):
                continue
            rem = xr.add(yr)
            if _iv(0, x.stride - 1).contains(rem):
                return x.stride, rem
        return None, None

    def _p_neg(self, eqn, ins):
        return [_mk([b.neg() for b in ins[0].bands()])]

    def _p_abs(self, eqn, ins):
        f = ins[0].flat()
        lo = 0 if f.lo <= 0 <= f.hi else min(abs(f.lo), abs(f.hi))
        return [AbsVal(_iv(lo, f.max_abs()))]

    def _p_sign(self, eqn, ins):
        return [AbsVal(_iv(-1, 1))]

    def _p_max(self, eqn, ins):
        a, b = ins
        out = self._binop(ins, Interval.max_)
        stride, rem = None, None
        if a.stride is not None and a.stride == b.stride and a.rem and b.rem:
            stride, rem = a.stride, a.rem.join(b.rem)
        return [dataclasses.replace(out, stride=stride, rem=rem)]

    def _p_min(self, eqn, ins):
        return [self._binop(ins, Interval.min_)]

    def _p_div(self, eqn, ins):
        a, b = ins
        fb = b.flat()
        if fb.lo <= 0 <= fb.hi:
            return [_top(eqn.outvars[0].aval)]
        fa = a.flat()
        cands = []
        for x in (fa.lo, fa.hi):
            for y in (fb.lo, fb.hi):
                q = x / y
                cands += [math.floor(q), math.ceil(q)]
        if a.stride is not None and fb.is_const() and fb.lo == a.stride:
            self.decodes_proved += 1
        return [AbsVal(_iv(min(cands), max(cands)))]

    def _p_rem(self, eqn, ins):
        a, b = ins
        fb = b.flat()
        if fb.lo > 0:
            d = fb.hi - 1
            lo = 0 if a.flat().lo >= 0 else -d
            return [AbsVal(_iv(lo, d))]
        return [_top(eqn.outvars[0].aval)]

    def _p_floor(self, eqn, ins):
        f = ins[0].flat()
        return [AbsVal(_iv(math.floor(f.lo), math.floor(f.hi)))]

    def _p_ceil(self, eqn, ins):
        f = ins[0].flat()
        return [AbsVal(_iv(math.ceil(f.lo), math.ceil(f.hi)))]

    def _p_round(self, eqn, ins):
        f = ins[0].flat()
        return [AbsVal(_iv(math.floor(f.lo), math.ceil(f.hi)))]

    def _p_integer_pow(self, eqn, ins):
        y = eqn.params["y"]
        f = ins[0].flat()
        if y % 2 == 0:
            return [AbsVal(_iv(0, f.max_abs() ** y))]
        return [AbsVal(_iv(f.lo**y, f.hi**y))]

    def _p_square(self, eqn, ins):
        f = ins[0].flat()
        lo = 0 if f.lo <= 0 <= f.hi else min(f.lo**2, f.hi**2)
        return [AbsVal(_iv(lo, f.max_abs() ** 2))]

    def _p_clamp(self, eqn, ins):
        lo_op, x, hi_op = ins
        fl, fx, fh = lo_op.flat(), x.flat(), hi_op.flat()
        lo = min(max(fx.lo, fl.lo), fh.hi)
        hi = min(max(fx.hi, fl.lo), fh.hi)
        return [AbsVal(_iv(lo, hi))]

    # -- bitwise / shifts --------------------------------------------------

    def _p_and(self, eqn, ins):
        a, b = ins
        dt = eqn.outvars[0].aval.dtype
        if str(dt) == "bool":
            oh = a.onehot | b.onehot
            return [AbsVal(_iv(0, 1), onehot=oh)]
        for x, y in ((a, b), (b, a)):
            fy = y.flat()
            if fy.is_const() and fy.lo >= 0:
                mask = int(fy.lo)
                if x.stride is not None and x.stride == mask + 1:
                    # Packed-field extraction: x = stride*q + r, and the
                    # mask keeps exactly r — the decode is lossless.
                    self.decodes_proved += 1
                    r = x.rem if x.rem is not None else _iv(0, mask)
                    return [AbsVal(r)]
                return [AbsVal(_iv(0, mask))]
        fa, fb = a.flat(), b.flat()
        if fa.lo >= 0 and fb.lo >= 0:
            return [AbsVal(_iv(0, min(fa.hi, fb.hi)))]
        return [_top(eqn.outvars[0].aval)]

    def _p_or(self, eqn, ins):
        a, b = ins
        dt = eqn.outvars[0].aval.dtype
        if str(dt) == "bool":
            return [AbsVal(_iv(0, 1))]
        fa, fb = a.flat(), b.flat()
        if fa.lo >= 0 and fb.lo >= 0:
            hi = max(fa.hi, fb.hi)
            bits = int(hi).bit_length() if hi == int(hi) else 63
            return [AbsVal(_iv(0, (1 << bits) - 1))]
        return [_top(eqn.outvars[0].aval)]

    def _p_xor(self, eqn, ins):
        return self._p_or(eqn, ins)

    def _p_not(self, eqn, ins):
        dt = eqn.outvars[0].aval.dtype
        if str(dt) == "bool":
            return [AbsVal(_iv(0, 1))]
        return [_top(eqn.outvars[0].aval)]

    def _p_shift_left(self, eqn, ins):
        a, b = ins
        fb = b.flat()
        if fb.is_const() and fb.lo >= 0:
            k = 1 << int(fb.lo)
            out = _mk([x.mul(_iv(k, k)) for x in a.bands()])
            return [dataclasses.replace(out, stride=k, rem=_iv(0, 0))]
        return [_top(eqn.outvars[0].aval)]

    def _shift_right(self, eqn, ins):
        a, b = ins
        fb = b.flat()
        if fb.is_const() and fb.lo >= 0:
            k = 1 << int(fb.lo)
            if a.stride is not None and a.stride == k:
                self.decodes_proved += 1
            f = a.flat()
            return [AbsVal(_iv(math.floor(f.lo / k), math.floor(f.hi / k)))]
        return [_top(eqn.outvars[0].aval)]

    def _p_shift_right_arithmetic(self, eqn, ins):
        return self._shift_right(eqn, ins)

    def _p_shift_right_logical(self, eqn, ins):
        if ins[0].flat().lo >= 0:
            return self._shift_right(eqn, ins)
        return [_top(eqn.outvars[0].aval)]

    # -- shape ops (tag-aware) --------------------------------------------

    def _remap(self, a, mapping):
        """Remap axis tags through an old-axis -> new-axis mapping."""
        onehot = frozenset(
            mapping[ax] for ax in a.onehot if mapping.get(ax) is not None
        )
        hasone = frozenset(
            mapping[ax] for ax in a.hasone if mapping.get(ax) is not None
        )
        iota = mapping.get(a.iota_axis) if a.iota_axis is not None else None
        return dataclasses.replace(
            a,
            onehot=onehot,
            iota_axis=iota,
            stride=a.stride,
            rem=a.rem,
            hasone=hasone,
            pick=a.pick if hasone else None,
        )

    def _p_broadcast_in_dim(self, eqn, ins):
        bd = eqn.params["broadcast_dimensions"]
        mapping = {i: d for i, d in enumerate(bd)}
        return [self._remap(ins[0], mapping)]

    def _p_reshape(self, eqn, ins):
        old = tuple(eqn.invars[0].aval.shape)
        new = tuple(eqn.outvars[0].aval.shape)
        old_core = [(i, d) for i, d in enumerate(old) if d != 1]
        new_core = [(i, d) for i, d in enumerate(new) if d != 1]
        if [d for _, d in old_core] == [d for _, d in new_core]:
            mapping = {oi: ni for (oi, _), (ni, _) in zip(old_core, new_core)}
            return [self._remap(ins[0], mapping)]
        return [
            dataclasses.replace(
                ins[0], onehot=frozenset(), iota_axis=None
            )
        ]

    def _p_squeeze(self, eqn, ins):
        dims = set(eqn.params["dimensions"])
        old = range(len(eqn.invars[0].aval.shape))
        mapping, j = {}, 0
        for i in old:
            if i in dims:
                mapping[i] = None
            else:
                mapping[i] = j
                j += 1
        return [self._remap(ins[0], mapping)]

    def _p_expand_dims(self, eqn, ins):
        dims = set(eqn.params["dimensions"])
        n_out = len(eqn.outvars[0].aval.shape)
        mapping, i = {}, 0
        for j in range(n_out):
            if j not in dims:
                mapping[i] = j
                i += 1
        return [self._remap(ins[0], mapping)]

    def _p_transpose(self, eqn, ins):
        perm = eqn.params["permutation"]
        mapping = {old: new for new, old in enumerate(perm)}
        # A permuted layout invalidates the frame the rmax origin's
        # reduced-axis indices were recorded in.
        return [dataclasses.replace(self._remap(ins[0], mapping), origin=None)]

    def _p_slice(self, eqn, ins):
        a = ins[0]
        starts = eqn.params["start_indices"]
        iota = a.iota_axis
        if iota is not None and starts[iota] != 0:
            a = dataclasses.replace(a, iota_axis=None)
        # Slicing can cut away the guaranteed-hit lane.
        return [dataclasses.replace(a, hasone=frozenset(), pick=None)]

    def _p_dynamic_slice(self, eqn, ins):
        return [
            dataclasses.replace(
                ins[0], iota_axis=None, hasone=frozenset(), pick=None
            )
        ]

    def _p_dynamic_update_slice(self, eqn, ins):
        return [ins[0].join(ins[1])]

    def _p_concatenate(self, eqn, ins):
        out = ins[0]
        for o in ins[1:]:
            out = out.join(o)
        return [dataclasses.replace(out, iota_axis=None, stride=None, rem=None)]

    def _p_pad(self, eqn, ins):
        a, padval = ins
        out = a.join(padval)
        keep_onehot = a.onehot if padval.flat() == _iv(0, 0) else frozenset()
        return [
            dataclasses.replace(
                out, onehot=keep_onehot, iota_axis=None, stride=None, rem=None
            )
        ]

    def _p_iota(self, eqn, ins):
        dim = eqn.params["dimension"]
        shape = eqn.params["shape"]
        return [AbsVal(_iv(0, max(shape[dim] - 1, 0)), iota_axis=dim)]

    def _p_select_n(self, eqn, ins):
        cond, cases = ins[0], ins[1:]
        bands = [b for c in cases for b in c.bands()]
        nonzero = []
        onehot = None
        for c in cases:
            if c.iv is not None and c.iv == _iv(0, 0) and c.sent is None:
                continue  # a literal zero branch keeps partitions intact
            nonzero.append(c)
            onehot = c.onehot if onehot is None else (onehot & c.onehot)
        if len(nonzero) <= 1 and cond.onehot:
            # where(onehot_mask, x, 0): at most one lane along the
            # mask's axes survives — the select result inherits the
            # at-most-one-nonzero structure whatever x's values are.
            onehot = (onehot or frozenset()) | cond.onehot
        hasone, pick = frozenset(), None
        if cond.hasone and len(cases) == 2:
            # where(argmax_mask, v, default): at least one lane along
            # the mask's axes holds a v-element — min/max reductions
            # over those axes may ignore the default.
            hasone, pick = cond.hasone, cases[1].flat()
        stride, rem = None, None
        strides = {c.stride for c in cases}
        if len(strides) == 1 and None not in strides:
            stride = strides.pop()
            rem = None
            for c in cases:
                r = c.rem if c.rem is not None else _iv(0, stride - 1)
                rem = r if rem is None else rem.join(r)
        out = _mk(
            bands,
            onehot=onehot or frozenset(),
            stride=stride,
            rem=rem,
        )
        if hasone:
            out = dataclasses.replace(out, hasone=hasone, pick=pick)
        return [out]

    def _p_gather(self, eqn, ins):
        return [
            dataclasses.replace(
                ins[0],
                onehot=frozenset(),
                iota_axis=None,
                stride=None,
                rem=None,
            )
        ]

    def _p_scatter(self, eqn, ins):
        return [ins[0].join(ins[-1])]

    _p_scatter_add = _p_scatter

    def _p_convert_element_type(self, eqn, ins):
        a = ins[0]
        src = eqn.invars[0].aval.dtype
        dst = eqn.outvars[0].aval.dtype
        if _is_float(src) and _is_float(dst):
            sm = _MANTISSA_BITS.get(str(src), 53)
            dm = _MANTISSA_BITS.get(str(dst), 53)
            if dm >= sm and dtype_window(dst).contains(
                dtype_window(src)
            ):
                # Same-or-wider float: every value crosses losslessly
                # (the exact-integer window only gates INTEGER-valued
                # data entering a float pipeline, i.e. int -> float and
                # narrowing float casts).
                return [a]
        dwin = dtype_window(dst)
        xwin = exact_window(dst)
        out_bands = []
        for band in a.bands():
            target = xwin if _is_float(dst) else dwin
            if target.contains(band):
                out_bands.append(band)
            elif band.hi <= _SENTINEL_FLOOR:
                # Masked-lane sentinel discharged through a cast: the
                # true cast result is wrap/saturate garbage on lanes the
                # program provably discards; the full target window
                # covers every outcome, so this stays finding-free but
                # is counted in the cert row.
                self.sentinel_casts += 1
                out_bands.append(dwin)
            else:
                self._find(
                    "lossy-narrowing",
                    f"convert_element_type {src} -> {dst}: operand band "
                    f"[{band.lo}, {band.hi}] escapes the target "
                    f"{'exact-integer ' if _is_float(dst) else ''}window "
                    f"[{target.lo}, {target.hi}] — values would round or "
                    "wrap",
                )
                out_bands.append(dwin)
        if not out_bands:
            out_bands = [_iv(0, 0)]
        return [
            _mk(
                out_bands,
                onehot=a.onehot,
                iota_axis=a.iota_axis,
                stride=a.stride,
                rem=a.rem,
            )
        ]

    # -- reductions & contractions ----------------------------------------

    def _axes_count(self, eqn) -> int:
        n = 1
        shape = eqn.invars[0].aval.shape
        for ax in eqn.params["axes"]:
            n *= shape[ax]
        return n

    def _p_reduce_sum(self, eqn, ins):
        a = ins[0]
        axes = eqn.params["axes"]
        shape = eqn.invars[0].aval.shape
        f = a.flat()
        hot = set(a.onehot) & set(axes)
        if hot:
            # At most one nonzero lane along each onehot axis: the sum
            # collapses those axes to a single term (join zero for the
            # all-masked slice).
            n = 1
            for ax in axes:
                if ax not in hot:
                    n *= shape[ax]
            out = f.scale_sum(n) if n > 1 else _iv(min(0, f.lo), max(0, f.hi))
        else:
            n = self._axes_count(eqn)
            out = _iv(_mulc(n, f.lo), _mulc(n, f.hi))
        if n > 1:
            # A one-hot-collapsed "sum" (n == 1) is an extraction, not
            # an accumulation: no rounding is introduced beyond what the
            # operand's own producers were already checked for.
            self._record_acc(eqn, out)
        # Surviving onehot axes renumber past the removed ones.
        keep = frozenset(
            ax - sum(1 for r in axes if r < ax)
            for ax in a.onehot
            if ax not in axes
        )
        return [_mk([out], onehot=keep)]

    def _p_reduce_max(self, eqn, ins):
        a = ins[0]
        axes = tuple(eqn.params["axes"])
        if set(axes) & a.hasone and a.pick is not None:
            # At least one reduced lane holds a pick-element, so the
            # max can't sink below pick.lo — the never-chosen default
            # (e.g. the -1 miss marker) drops out.
            f = a.flat()
            return [AbsVal(_iv(max(f.lo, a.pick.lo), f.hi))]
        return [
            dataclasses.replace(
                a,
                onehot=frozenset(),
                iota_axis=None,
                origin=("rmax", id(a), axes),
                hasone=frozenset(),
                pick=None,
            )
        ]

    def _p_reduce_min(self, eqn, ins):
        a = ins[0]
        axes = tuple(eqn.params["axes"])
        if set(axes) & a.hasone and a.pick is not None:
            # Dual: the min can't rise above pick.hi — the BIG-row miss
            # default never survives the reduction.
            f = a.flat()
            return [AbsVal(_iv(f.lo, min(f.hi, a.pick.hi)))]
        return [
            dataclasses.replace(
                a,
                onehot=frozenset(),
                iota_axis=None,
                origin=("rmax", id(a), axes),
                hasone=frozenset(),
                pick=None,
            )
        ]

    def _p_reduce_and(self, eqn, ins):
        return [AbsVal(_iv(0, 1))]

    _p_reduce_or = _p_reduce_and

    def _p_argmax(self, eqn, ins):
        shape = eqn.invars[0].aval.shape
        n = 1
        for ax in eqn.params["axes"]:
            n *= shape[ax]
        return [AbsVal(_iv(0, max(n - 1, 0)))]

    _p_argmin = _p_argmax

    def _p_cumsum(self, eqn, ins):
        ax = eqn.params["axis"]
        n = eqn.invars[0].aval.shape[ax]
        f = ins[0].flat()
        out = f.scale_sum(n).join(f)
        self._record_acc(eqn, out)
        return [_mk([out])]

    def _p_cummax(self, eqn, ins):
        return [dataclasses.replace(ins[0], onehot=frozenset(), iota_axis=None)]

    _p_cummin = _p_cummax

    def _record_acc(self, eqn, interval: Interval) -> None:
        dt = eqn.outvars[0].aval.dtype
        label = f"{eqn.primitive.name}:{tuple(eqn.outvars[0].aval.shape)}"
        if _is_float(dt):
            self.float_accs.append((label, interval))
        elif _is_int(dt):
            self.int_accs.append((label, interval))

    def _p_dot_general(self, eqn, ins):
        a, b = ins
        (lc, rc), _ = eqn.params["dimension_numbers"]
        lsh = eqn.invars[0].aval.shape
        k = 1
        for d in lc:
            k *= lsh[d]
        fa, fb = a.flat(), b.flat()
        unit = _iv(0, 1)
        lhs_onehot = len(lc) == 1 and lc[0] in a.onehot
        rhs_onehot = len(rc) == 1 and rc[0] in b.onehot
        if lhs_onehot and unit.contains(fa):
            # Partition of unity contracted away: a convex selection of
            # the other operand's entries.
            out = _iv(min(0, fb.lo), max(0, fb.hi))
        elif rhs_onehot and unit.contains(fb):
            out = _iv(min(0, fa.lo), max(0, fa.hi))
        elif lhs_onehot or rhs_onehot:
            # At most one nonzero term in the contraction.
            p = fa.mul(fb)
            out = _iv(min(0, p.lo), max(0, p.hi))
        else:
            p = fa.mul(fb)
            out = _iv(_mulc(k, p.lo), _mulc(k, p.hi))
        self._record_acc(eqn, out)
        return [_mk([out])]

    # -- control flow ------------------------------------------------------

    def _p_cond(self, eqn, ins):
        branches = eqn.params["branches"]
        operands = ins[1:]
        outs = None
        for br in branches:
            res = self.run(br.jaxpr, list(br.consts), list(operands))
            if outs is None:
                outs = res
            else:
                outs = [self._join_any(x, y) for x, y in zip(outs, res)]
        return outs

    def _join_any(self, x, y):
        if isinstance(x, _RefCell) or isinstance(y, _RefCell):
            return x  # refs are aliased cells, not joinable values
        return x.join(y)

    def _loop_fixpoint(self, body, consts, pre, carry0, xs, trip):
        """Abstractly iterate a loop body whose invars are ``[*pre,
        *carry, *xs]``.  ``trip`` bounds the dynamic iteration count
        when known (result = prefix-join over 0..trip steps, exact for
        accumulate-by-add carries); None means unknown —
        join-until-stable with widening."""
        acc = list(carry0)
        cur = list(carry0)
        ys_join = None
        rounds = trip if (trip is not None and trip <= _MAX_TRIP_UNROLL) else (
            _MAX_TRIP_UNROLL
        )
        widen_at = rounds if trip is not None else 8
        for it in range(rounds):
            outs = self.run(body, consts, pre + cur + xs)
            ncarry = outs[: len(carry0)]
            ys = outs[len(carry0):]
            if ys_join is None:
                ys_join = list(ys)
            else:
                ys_join = [self._join_any(a, b) for a, b in zip(ys_join, ys)]
            nxt = []
            stable = True
            for c, n in zip(cur, ncarry):
                if isinstance(c, _RefCell) or isinstance(n, _RefCell):
                    nxt.append(n)
                    continue
                if it >= widen_at:
                    n = self._widen(c, n)
                    self.widened = True
                j = n if trip is not None else c.join(n)
                if j != c:
                    stable = False
                nxt.append(j)
            acc = [self._join_any(a, b) for a, b in zip(acc, nxt)]
            cur = nxt
            if stable:
                break
        result = acc if trip is not None else cur
        return result, (ys_join if ys_join is not None else [])

    def _widen(self, old, new):
        if old.iv is None or new.iv is None:
            return new
        lo, hi = new.iv.lo, new.iv.hi
        if lo < old.iv.lo:
            lo = -_INF
        if hi > old.iv.hi:
            hi = _INF
        return dataclasses.replace(
            new, iv=_iv(lo, hi), stride=None, rem=None
        )

    def _p_while(self, eqn, ins):
        p = eqn.params
        cn, bn = p["cond_nconsts"], p["body_nconsts"]
        cond, body = p["cond_jaxpr"], p["body_jaxpr"]
        cond_consts = ins[:cn]
        body_consts = ins[cn: cn + bn]
        carry0 = ins[cn + bn:]
        trip = self._while_trip_bound(cond, cond_consts, carry0)
        body_env_consts = list(body.consts) if hasattr(body, "consts") else []
        carry, _ = self._loop_fixpoint(
            body.jaxpr,
            body_env_consts,
            list(body_consts),
            list(carry0),
            [],
            trip,
        )
        self._record_loop_carries(carry, eqn.outvars)
        return carry

    def _while_trip_bound(self, cond, cond_consts, carry0):
        """Recognise the lowered fori pattern — cond is a single
        ``lt i n`` over carry slots — and bound the trip count by the
        abstract ranges of ``i``'s start and ``n``."""
        try:
            cj = cond.jaxpr
            if len(cj.eqns) != 1 or cj.eqns[0].primitive.name != "lt":
                return None
            eq = cj.eqns[0]
            if list(cj.outvars) != list(eq.outvars):
                return None
            ncc = len(cj.constvars)
            slots = {v: i for i, v in enumerate(cj.invars)}

            def resolve(v):
                from jax.core import Literal

                if isinstance(v, Literal):
                    return _const_val(v.val)
                if v in slots:
                    idx = slots[v]
                    pool = list(cond_consts) + list(carry0)
                    return pool[idx] if idx < len(pool) else None
                return None

            del ncc
            a = resolve(eq.invars[0])
            b = resolve(eq.invars[1])
            if a is None or b is None or a.iv is None or b.iv is None:
                return None
            trip = b.iv.hi - a.iv.lo
            if trip != trip or trip == _INF:  # NaN / unbounded
                return None
            trip = int(max(0, trip))
            return trip if trip <= _MAX_TRIP_UNROLL else None
        except Exception:  # noqa: BLE001
            # advisory: trip-bound recognition only — an unrecognised
            # loop shape falls back to widening: wider, never wrong.
            return None

    def _record_loop_carries(self, carry, outvars):
        for c, var in zip(carry, outvars):
            if isinstance(c, _RefCell) or c.iv is None:
                continue
            dt = getattr(getattr(var, "aval", None), "dtype", None)
            if dt is None:
                continue
            if _is_float(dt):
                self.float_accs.append(("loop-carry", c.iv))
            elif _is_int(dt):
                self.int_accs.append(("loop-carry", c.iv))

    def _p_scan(self, eqn, ins):
        p = eqn.params
        nconsts, ncarry = p["num_consts"], p["num_carry"]
        length = p["length"]
        closed = p["jaxpr"]
        consts = ins[:nconsts]
        carry0 = ins[nconsts: nconsts + ncarry]
        xs = ins[nconsts + ncarry:]

        def slice_x(x):
            onehot = frozenset(t - 1 for t in x.onehot if t > 0)
            iota = (
                x.iota_axis - 1
                if (x.iota_axis is not None and x.iota_axis > 0)
                else None
            )
            return dataclasses.replace(x, onehot=onehot, iota_axis=iota)

        xslices = [slice_x(x) for x in xs]
        jx_consts = [_const_val(c) for c in closed.consts]
        trip = length if length <= _MAX_TRIP_UNROLL else None
        carry, ys = self._loop_fixpoint(
            closed.jaxpr, jx_consts, list(consts), list(carry0), xslices, trip
        )
        if trip is None:
            self.widened = True
        self._record_loop_carries(carry, eqn.outvars[: len(carry)])

        def stack_y(y):
            if isinstance(y, _RefCell):
                return y
            onehot = frozenset(t + 1 for t in y.onehot)
            return dataclasses.replace(y, onehot=onehot, iota_axis=None)

        return list(carry) + [stack_y(y) for y in ys]

    def _p_pjit(self, eqn, ins):
        closed = eqn.params["jaxpr"]
        tag = eqn.params.get("name") or "pjit"
        return self._run_tagged(tag, closed.jaxpr, list(closed.consts), list(ins))

    def _p_closed_call(self, eqn, ins):
        closed = eqn.params["call_jaxpr"]
        return self.run(closed.jaxpr, list(closed.consts), list(ins))

    def _p_custom_jvp_call(self, eqn, ins):
        closed = eqn.params["call_jaxpr"]
        return self.run(closed.jaxpr, list(closed.consts), list(ins))

    def _p_custom_vjp_call(self, eqn, ins):
        closed = eqn.params.get("call_jaxpr") or eqn.params.get("fun_jaxpr")
        return self.run(closed.jaxpr, list(closed.consts), list(ins))

    def _p_remat2(self, eqn, ins):
        jx = eqn.params["jaxpr"]
        return self.run(jx, [], list(ins))

    _p_checkpoint = _p_remat2

    # -- sharding / collectives -------------------------------------------

    def _p_shard_map(self, eqn, ins):
        mesh = eqn.params.get("mesh")
        if mesh is not None and hasattr(mesh, "shape"):
            try:
                self.axis_sizes.update(dict(mesh.shape))
            except Exception:  # noqa: BLE001
                # advisory: mesh introspection only — unknown axis sizes
                # widen the collective results instead of failing the cert.
                pass
        jx, consts = self._sub_jaxpr(eqn.params, "jaxpr")
        if jx is None or len(jx.invars) != len(ins):
            self.unknown.add("shard_map")
            self._find(
                "unknown-primitive",
                "shard_map body jaxpr not introspectable — fail closed",
            )
            return [_top(v.aval) for v in eqn.outvars]
        return self.run(jx, consts, list(ins))

    def _axis_prod(self, axes) -> int:
        n = 1
        if isinstance(axes, (str, int)):
            axes = (axes,)
        for ax in axes or ():
            n *= int(self.axis_sizes.get(ax, 8))
        return n

    def _p_psum(self, eqn, ins):
        n = self._axis_prod(eqn.params.get("axes") or eqn.params.get("axis_name"))
        outs = []
        for a, v in zip(ins, eqn.outvars):
            f = a.flat()
            out = _iv(_mulc(n, min(f.lo, 0)) + max(f.lo, 0),
                      _mulc(n, max(f.hi, 0)) + min(f.hi, 0))
            self.float_accs.append((f"psum:{tuple(v.aval.shape)}", out)) if _is_float(
                v.aval.dtype
            ) else self.int_accs.append((f"psum:{tuple(v.aval.shape)}", out))
            outs.append(_mk([out]))
        return outs

    def _p_all_gather(self, eqn, ins):
        return [
            dataclasses.replace(
                a, onehot=frozenset(), iota_axis=None, stride=None, rem=None
            )
            for a in ins
        ]

    _p_ppermute = _p_all_gather
    _p_all_to_all = _p_all_gather
    _p_pbroadcast = _p_all_gather

    def _p_axis_index(self, eqn, ins):
        n = self._axis_prod(eqn.params.get("axis_name"))
        return [AbsVal(_iv(0, max(n - 1, 0)))]

    def _p_pmax(self, eqn, ins):
        return [dataclasses.replace(a, onehot=frozenset(), iota_axis=None) for a in ins]

    _p_pmin = _p_pmax

    # -- pallas ------------------------------------------------------------

    def _p_pallas_call(self, eqn, ins):
        jx, consts = self._sub_jaxpr(eqn.params, "jaxpr")
        n_out = len(eqn.outvars)
        if jx is None or len(jx.invars) < len(ins) + n_out:
            self.unknown.add("pallas_call")
            self._find(
                "unknown-primitive",
                "pallas_call kernel jaxpr not introspectable — fail closed",
            )
            return [_top(v.aval) for v in eqn.outvars]
        cells = []
        for i, var in enumerate(jx.invars):
            if i < len(ins):
                cells.append(_RefCell(ins[i], var.aval))
            else:
                cells.append(_RefCell(None, var.aval))
        # The grid re-runs the kernel over cell state: fixpoint with a
        # small round bound, then widening (cells joined to dtype top).
        tag = eqn.params.get("name") or "kernel"
        for rounds in range(8):
            before = [c.val for c in cells]
            self._run_tagged(f"pallas:{tag}", jx, consts, list(cells))
            if all(
                self._cell_eq(b, c.val) for b, c in zip(before, cells)
            ):
                break
        else:
            for c in cells[len(ins):]:
                c.val = _top(c.aval)
            self.widened = True
        del rounds
        outs = []
        for c in cells[len(ins): len(ins) + n_out]:
            outs.append(c.val if c.val is not None else _top(c.aval))
        return outs

    def _cell_eq(self, a, b) -> bool:
        return a == b

    def _p_get(self, eqn, ins):
        cell = ins[0]
        if not isinstance(cell, _RefCell):
            return [_top(eqn.outvars[0].aval)]
        if cell.val is None:
            return [_top(cell.aval)]
        return [cell.val]

    _p_masked_load = _p_get

    def _p_swap(self, eqn, ins):
        cell, new = ins[0], ins[1]
        if not isinstance(cell, _RefCell):
            return [_top(eqn.outvars[0].aval)]
        old = cell.val if cell.val is not None else new
        cell.val = old.join(new) if old is not new else new
        return [old]

    _p_masked_store = _p_swap

    def _p_addupdate(self, eqn, ins):
        cell, add = ins[0], ins[1]
        if isinstance(cell, _RefCell):
            base = cell.val if cell.val is not None else AbsVal(_iv(0, 0))
            cell.val = base.join(
                _mk([x.add(y) for x in base.bands() for y in add.bands()])
            )
        return []

    def _p_program_id(self, eqn, ins):
        return [AbsVal(_iv(0, 1 << 20))]

    def _p_num_programs(self, eqn, ins):
        return [AbsVal(_iv(1, 1 << 20))]

    def _p_multiple_of(self, eqn, ins):
        return [ins[0]]

    # -- misc --------------------------------------------------------------

    def _p_is_finite(self, eqn, ins):
        return [AbsVal(_iv(0, 1))]

    def _p_split(self, eqn, ins):
        a = dataclasses.replace(
            ins[0], onehot=frozenset(), iota_axis=None, stride=None, rem=None
        )
        return [a] * len(eqn.outvars)

    def _p_sort(self, eqn, ins):
        return [
            dataclasses.replace(
                a, onehot=frozenset(), iota_axis=None, stride=None, rem=None
            )
            for a in ins
        ]

    def _p_device_put(self, eqn, ins):
        return list(ins)


# --------------------------------------------------------------------------
# Row analysis
# --------------------------------------------------------------------------


@dataclasses.dataclass
class RowResult:
    """Proved result for one (entry, bucket, envelope) row."""

    verdict: str  # exact | representable | unproven
    float_acc: Interval | None
    int_acc: Interval | None
    findings: list
    unknown: list
    widened: bool
    sentinel_casts: int
    decodes_proved: int

    def to_dict(self) -> dict:
        def ivl(x):
            return None if x is None else [x.lo, x.hi]

        return {
            "verdict": self.verdict,
            "float_acc": ivl(self.float_acc),
            "int_acc": ivl(self.int_acc),
            "findings": [f.to_dict() for f in self.findings],
            "unknown_primitives": sorted(self.unknown),
            "widened": self.widened,
            "sentinel_casts": self.sentinel_casts,
            "decodes_proved": self.decodes_proved,
        }


def _join_accs(accs):
    out = None
    for _, it in accs:
        out = it if out is None else out.join(it)
    return out


def analyze_jaxpr(closed, seeds, where: str) -> RowResult:
    """Abstractly interpret one closed jaxpr under seeded input
    envelopes and compute the row verdict."""
    interp = _Interp(where)
    consts = [_const_val(c) for c in closed.consts]
    interp.run(closed.jaxpr, consts, list(seeds))

    f32_window = _iv(-(1 << 24), 1 << 24)
    float_acc = _join_accs(interp.float_accs)
    int_acc = _join_accs(interp.int_accs)

    if interp.unknown or interp.widened:
        verdict = "unproven" if interp.unknown else "representable"
    else:
        verdict = "exact"
    if verdict == "exact" and float_acc is not None and not f32_window.contains(
        float_acc
    ):
        verdict = "representable"
    if any(f.kind in ("int-overflow", "float-overflow") for f in interp.findings):
        verdict = "unproven"

    return RowResult(
        verdict=verdict,
        float_acc=float_acc,
        int_acc=int_acc,
        findings=list(interp.findings),
        unknown=sorted(interp.unknown),
        widened=interp.widened,
        sentinel_casts=interp.sentinel_casts,
        decodes_proved=interp.decodes_proved,
    )


def entry_seeds(args, l1p: int, l2p: int, w_lo: int, w_hi: int):
    """Input envelopes for the canonical 5-operand chunk/pair signature:
    (seq1ext codes, len1, rows codes, lens, val_flat)."""
    if len(args) != 5:
        return [AbsVal(dtype_window(a.dtype)) for a in args]
    return [
        AbsVal(_iv(0, 26)),
        AbsVal(_iv(0, l1p)),
        AbsVal(_iv(0, 26)),
        AbsVal(_iv(0, l2p)),
        AbsVal(_iv(w_lo, w_hi)),
    ]


def analyze_entry(fn, args, seeds, where: str) -> RowResult:
    """Lower ``fn`` at abstract ``args`` and analyze under ``seeds``."""
    import jax

    try:
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as exc:  # noqa: BLE001 - re-raise with context
        raise RangeCertError(f"{where}: failed to lower: {exc!r}") from exc
    return analyze_jaxpr(closed, seeds, where)


# --------------------------------------------------------------------------
# Derived constants — the machine re-derivation of every hand bound
# --------------------------------------------------------------------------


def _derive_operand_cap() -> int:
    """Largest max|v| whose delta operand |d0 - d1| = 2*max|v| fits the
    16 mantissa bits the HIGHEST multi-pass matmul resolves."""
    budget = (1 << 16) - 1
    v = Interval(0, 0)
    cap = 0
    while True:
        nxt = cap + 1
        v = _iv(-nxt, nxt)
        if v.sub(v).max_abs() > budget:
            return cap
        cap = nxt
        if cap > budget:  # pragma: no cover - safety rail
            return cap


def _derive_max_exact(l2p: int) -> int:
    """Largest max|v| for which the interval engine's own accumulator
    bound for the delta formulation at bucket width ``l2p`` stays inside
    the f32 exact-integer window (and the operand inside the HIGHEST
    budget) — binary search over a monotone admissibility predicate."""
    window = exact_window("float32")
    strict = _iv(window.lo + 1, window.hi - 1)  # 2*l2p*maxv <= 2^24 - 1
    cap = _derive_operand_cap()

    def admissible(v: int) -> bool:
        if v > cap:
            return False
        val = _iv(-v, v)
        delta = val.sub(val)  # the dot operand: |d0 - d1| <= 2v
        prefix = delta.scale_sum(l2p)  # G partials over <= l2p rows
        return strict.contains(prefix)

    lo, hi = 0, 1 << 25
    while lo < hi:
        mid = (lo + hi + 1) // 2
        if admissible(mid):
            lo = mid
        else:
            hi = mid - 1
    return lo


def _pack_capacity(radix: int, ceiling: int) -> int:
    """Largest |payload| with payload * radix + (radix - 1) <= ceiling —
    the generic int32 packing budget behind the 2^19 rowpack gate and
    the 2^31 argmax bound."""
    return (ceiling - (radix - 1)) // radix


def _derive_pack_radix(kappa_max: int) -> int:
    """Smallest power of two strictly above every packable kappa, so the
    low field masks/divides out exactly."""
    r = 1
    while r <= kappa_max:
        r <<= 1
    return r


def _padded_bucket_cap() -> int:
    from ..utils.constants import BUF_SIZE_SEQ2

    return ((BUF_SIZE_SEQ2 + 127) // 128) * 128


def derive_constants(wired: dict | None = None):
    """Re-derive every hand numeric bound with the interval machinery
    and diff each against its wired source value.  ``wired`` overrides
    the imported sources (tests inject drift).  Returns (rows,
    findings)."""
    from ..ops import bounds as B
    from ..ops.dispatch import pack_classes
    from ..ops.matmul_scorer import MAX_NATIVE_PRECISION_WEIGHT
    from ..ops.pallas_scorer import MAX_BF16_EXACT_WEIGHT, MAX_I8_EXACT_WEIGHT

    w = {
        "f32-exact-window": B.F32_EXACT_WINDOW,
        "operand-cap": B.OPERAND_CAP,
        "static-weight-ceiling": B.MAX_EXACT_WEIGHT,
        "rowpack-epilogue-limit": B.ROWPACK_EPILOGUE_LIMIT,
        "superblock-key-budget": B.SUPERBLOCK_CAP,
        "argmax-pack-radix": B.PACK_RADIX,
        "argmax-pack-bound": B.PACKED_L2P_CEILING,
        "int32-packed-sentinel": B.INT32_PACKED_SENTINEL,
        "i8-feed-ceiling": MAX_I8_EXACT_WEIGHT,
        "bf16-feed-ceiling": MAX_BF16_EXACT_WEIGHT,
        "native-precision-ceiling": MAX_NATIVE_PRECISION_WEIGHT,
    }
    for l2p in (128, 256, 512, 1024, 2048):
        w[f"max-exact-value-{l2p}"] = B.max_exact_value(l2p)
    w["rowpack-classes-static"] = list(pack_classes("f32", B.MAX_EXACT_WEIGHT))
    if wired:
        w.update(wired)

    int32_max = (1 << 31) - 1
    bucket_cap = _padded_bucket_cap()
    i8_max = int(dtype_window("int8").hi)  # 127

    rows = []

    def row(name, derived, relation="==", note=""):
        wv = w.get(name)
        if relation == "==":
            ok = derived == wv
        elif relation == "<=":  # wired must not exceed the derived bound
            ok = wv is not None and wv <= derived
        else:  # pragma: no cover - defensive
            ok = False
        rows.append(
            {
                "name": name,
                "derived": derived,
                "wired": wv,
                "relation": relation,
                "ok": bool(ok),
                "note": note,
            }
        )

    row(
        "f32-exact-window",
        int(exact_window("float32").hi),
        note="2^(f32 mantissa bits): integers to here survive f32 exactly",
    )
    row(
        "operand-cap",
        _derive_operand_cap(),
        note="largest max|v| with delta operand 2*max|v| <= 2^16 - 1",
    )
    for l2p in (128, 256, 512, 1024, 2048):
        row(
            f"max-exact-value-{l2p}",
            _derive_max_exact(l2p),
            note=f"engine-derived exact-weight ceiling at l2p={l2p}",
        )
    row(
        "static-weight-ceiling",
        _derive_max_exact(bucket_cap),
        note=f"max-exact-value at the padded BUF_SIZE_SEQ2 cap ({bucket_cap})",
    )
    # Rowpack epilogue: key field = 2^SUPERBLOCK_KEY_BITS lanes, packed
    # payload must fit int32 -> payload < 2^(31 - key_bits) = 2^19.
    key_bits = w.get("superblock-key-bits", B.SUPERBLOCK_KEY_BITS)
    rowpack_limit = _pack_capacity(1 << key_bits, int32_max) + 1
    row(
        "rowpack-epilogue-limit",
        rowpack_limit,
        note="packed epilogue payload bound: payload*2^12 + (2^12-1) "
        "<= 2^31 - 1",
    )
    # Largest sb whose lane key still fits the 12-bit field.
    sb = 1
    while ((sb + 1) * 128 - 1).bit_length() <= key_bits:
        sb += 1
    row(
        "superblock-key-budget",
        sb,
        relation="<=",
        note="derived admissible sb cap from klb <= 12; the wired 24 is "
        "the measured perf plateau and must only stay at or below it",
    )
    radix = _derive_pack_radix(bucket_cap)
    row(
        "argmax-pack-radix",
        radix,
        note=f"smallest pow2 > kappa_max = {bucket_cap}",
    )
    # Packed argmax admission: |g| <= 2 * 127 * l2p must pack into int32.
    g_budget = _pack_capacity(radix, int32_max)
    l2p_cap = (g_budget // (2 * i8_max)) // 128 * 128
    row(
        "argmax-pack-bound",
        l2p_cap,
        note=f"largest 128-aligned l2p with 2*{i8_max}*l2p*{radix} + "
        f"{radix - 1} <= 2^31 - 1 (g_budget={g_budget})",
    )
    row(
        "int32-packed-sentinel",
        -int32_max,
        note="largest-magnitude int32 whose negation is representable",
    )
    row(
        "i8-feed-ceiling",
        i8_max,
        note="int8 dtype window",
    )
    bf16_exact = int(exact_window("bfloat16").hi)  # 256
    row(
        "bf16-feed-ceiling",
        bf16_exact // 2,
        note="largest max|v| with delta operand 2*max|v| inside bf16's "
        "exact-integer window",
    )
    row(
        "native-precision-ceiling",
        bf16_exact // 2,
        note="single-pass f32 MXU multiplies at bf16 precision: same "
        "2*max|v| <= 2^8 bound",
    )
    row(
        "rowpack-classes-static",
        [
            s
            for s in (8, 16, 32, 64)
            if 3 * s * _derive_max_exact(bucket_cap) < rowpack_limit
        ],
        note="classes admitted at the static weight ceiling, recomputed "
        "from derived bounds",
    )
    # Congruence corollary: the packed argmax decode is lossless — the
    # remainder field spans exactly [0, radix - 1].
    g = _iv(-(2 * i8_max * l2p_cap), 2 * i8_max * l2p_cap)
    packed = g.mul(_iv(radix, radix)).add(_iv(0, radix - 1))
    rows.append(
        {
            "name": "pack-decode-lossless",
            "derived": bool(
                _iv(-(int32_max), int32_max).contains(packed)
            ),
            "wired": True,
            "relation": "==",
            "ok": bool(_iv(-(int32_max), int32_max).contains(packed)),
            "note": f"g*{radix} + r, r in [0, {radix - 1}]: packed band "
            f"[{packed.lo}, {packed.hi}] inside int32 and rem width "
            "< stride, so // and & recover (g, r) exactly",
        }
    )

    findings = [
        RangeFinding(
            "constant-drift",
            f"derived_constants/{r['name']}",
            f"derived {r['derived']!r} {r['relation']} wired {r['wired']!r} "
            "does not hold — the wired constant drifted from its "
            "machine-derived value",
        )
        for r in rows
        if not r["ok"]
    ]
    return rows, findings


# --------------------------------------------------------------------------
# Cert assembly
# --------------------------------------------------------------------------

#: The int16 envelope the BLOSUM/PAM roadmap item needs: substitution
#: matrices carry NEGATIVE entries, and int16 is the widest table the
#: serialized weight format admits.
SIGNED_ENVELOPE = (-32768, 32767)


def audit_entry_ranges(buckets=None):
    """Analyze every entry contract at every audit bucket under the
    CERTIFIED weight envelope (max_exact_value(l2p)) — these rows must
    prove exact."""
    from ..ops import bounds as B
    from .contracts import _AUDIT_BUCKETS, ENTRY_CONTRACTS

    if buckets is None:
        buckets = _AUDIT_BUCKETS
    rows = []
    findings = []
    for contract in ENTRY_CONTRACTS:
        for bucket in buckets:
            b, nc, l1p, l2p = bucket
            maxv = B.max_exact_value(l2p)
            fn, args = contract.make(b, nc, l1p, l2p)
            where = f"entry={contract.name}/bucket={b}x{nc}x{l1p}x{l2p}"
            seeds = entry_seeds(args, l1p, l2p, -maxv, maxv)
            res = analyze_entry(fn, args, seeds, where)
            findings.extend(res.findings)
            if res.verdict != "exact":
                findings.append(
                    RangeFinding(
                        "exactness-regression",
                        where,
                        f"verdict {res.verdict!r} under the certified "
                        f"envelope |v| <= {maxv}: float accumulator "
                        f"{res.float_acc and [res.float_acc.lo, res.float_acc.hi]} "
                        "must stay inside the f32 exact-integer window",
                    )
                )
            rows.append(
                {
                    "entry": contract.name,
                    "bucket": list(bucket),
                    "envelope": f"certified|v|<={maxv}",
                    "maxv": maxv,
                    **res.to_dict(),
                }
            )
    return rows, findings


def audit_signed_entries(buckets=None):
    """The signed_weights envelope rows: every entry analyzed under the
    full int16 window.  Documentation, not a gate — ``survives`` is the
    per-path answer ROADMAP item 4 needs."""
    from .contracts import _AUDIT_BUCKETS, ENTRY_CONTRACTS

    if buckets is None:
        buckets = _AUDIT_BUCKETS
    lo, hi = SIGNED_ENVELOPE
    rows = []
    for contract in ENTRY_CONTRACTS:
        for bucket in buckets:
            b, nc, l1p, l2p = bucket
            fn, args = contract.make(b, nc, l1p, l2p)
            where = (
                f"signed/entry={contract.name}/bucket={b}x{nc}x{l1p}x{l2p}"
            )
            seeds = entry_seeds(args, l1p, l2p, lo, hi)
            res = analyze_entry(fn, args, seeds, where)
            rows.append(
                {
                    "entry": contract.name,
                    "bucket": list(bucket),
                    "envelope": f"signed[{lo},{hi}]",
                    "survives": res.verdict == "exact"
                    and not res.findings,
                    **res.to_dict(),
                }
            )
    return rows


def signed_weight_paths():
    """Static per-path signed-weight survival table, derived from the
    certified ceilings (pure interval arithmetic, no jaxpr needed)."""
    from ..ops import bounds as B
    from ..ops.dispatch import pack_classes

    lo, hi = SIGNED_ENVELOPE
    amax = max(abs(lo), abs(hi))
    rows = []
    for l2p in (128, 2048):
        ceil = B.max_exact_value(l2p)
        rows.append(
            {
                "path": "mm-f32",
                "l2p": l2p,
                "survives": amax <= ceil,
                "ceiling": ceil,
                "note": "sign-symmetric: every bound is on |v|; the "
                f"int16 envelope max |v| = {amax} vs ceiling {ceil}",
            }
        )
    int32 = dtype_window("int32")
    gather_acc = _iv(-amax, amax).scale_sum(_padded_bucket_cap())
    rows.append(
        {
            "path": "xla-gather-int32",
            "l2p": _padded_bucket_cap(),
            "survives": int32.contains(gather_acc),
            "ceiling": int(int32.hi // _padded_bucket_cap()),
            "note": f"int32 prefix sums: |acc| <= {int(gather_acc.hi)} "
            "< 2^31 — the gather path survives the full signed envelope",
        }
    )
    for feed, ceil in (("i8", 127), ("bf16", 128)):
        rows.append(
            {
                "path": f"pallas-{feed}",
                "l2p": None,
                "survives": amax <= ceil,
                "ceiling": ceil,
                "note": "feed threshold",
            }
        )
    rows.append(
        {
            "path": "rowpack",
            "l2p": 128,
            "survives": bool(pack_classes("f32", amax)),
            "ceiling": (B.ROWPACK_EPILOGUE_LIMIT // 3 - 1) // 8,
            "note": "classes admitted at the signed envelope magnitude: "
            f"{list(pack_classes('f32', amax))}",
        }
    )
    return rows


def audit_schedule_ranges(problem, backend: str = "pallas"):
    """Analyze every resolved production-bucket body at its production
    chunk shape under the problem's ACTUAL value-table envelope."""
    import jax
    import numpy as np

    from ..ops.schedule import production_schedule
    from ..ops.values import max_abs_value, value_table

    _, sched = production_schedule(problem, backend)
    val = value_table(problem.weights)
    maxv = int(max_abs_value(np.asarray(val).reshape(-1)))
    rows = []
    findings = []
    for i, part in enumerate(sched):
        batch = part["batch"]
        body = part["body"]
        nc, cb, l2p = np.asarray(part["rows"]).shape
        args = (
            jax.ShapeDtypeStruct(
                np.asarray(batch.seq1ext).shape,
                np.asarray(batch.seq1ext).dtype,
            ),
            jax.ShapeDtypeStruct((), np.int32),
            jax.ShapeDtypeStruct((1, cb, l2p), np.int32),
            jax.ShapeDtypeStruct((1, cb), np.int32),
            jax.ShapeDtypeStruct((27 * 27,), np.int32),
        )
        where = f"schedule[{i}]/l1p={batch.l1p}/l2p={batch.l2p}/cb={cb}"
        seeds = entry_seeds(args, batch.l1p, batch.l2p, -maxv, maxv)
        res = analyze_entry(body, args, seeds, where)
        findings.extend(res.findings)
        if res.verdict != "exact":
            findings.append(
                RangeFinding(
                    "exactness-regression",
                    where,
                    f"production bucket verdict {res.verdict!r} at the "
                    f"problem's actual envelope |v| <= {maxv}",
                )
            )
        rows.append(
            {
                "bucket": i,
                "l1p": int(batch.l1p),
                "l2p": int(batch.l2p),
                "cb": int(cb),
                "maxv": maxv,
                **res.to_dict(),
            }
        )
    return rows, findings


def build_cert(problem=None, backend: str = "pallas") -> dict:
    """Assemble the full RangeCert body (JSON-ready dict)."""
    const_rows, const_findings = derive_constants()
    entry_rows, entry_findings = audit_entry_ranges()
    signed_rows = audit_signed_entries()
    path_rows = signed_weight_paths()
    sched_rows: list = []
    sched_findings: list = []
    if problem is not None:
        sched_rows, sched_findings = audit_schedule_ranges(problem, backend)

    findings = [
        f.to_dict() for f in (*const_findings, *entry_findings, *sched_findings)
    ]
    f32w = exact_window("float32")
    return {
        "engine": {
            "domain": "interval+sentinel+onehot+congruence",
            "sentinel_floor": _SENTINEL_FLOOR,
            "max_trip_unroll": _MAX_TRIP_UNROLL,
        },
        "windows": {
            "f32_exact": [int(f32w.lo), int(f32w.hi)],
            "int32": [
                int(dtype_window("int32").lo),
                int(dtype_window("int32").hi),
            ],
        },
        "derived_constants": const_rows,
        "entries": entry_rows,
        "production": sched_rows,
        "signed_weights": {"entries": signed_rows, "paths": path_rows},
        "findings": findings,
        "counts": {
            "constants": len(const_rows),
            "constants_ok": sum(1 for r in const_rows if r["ok"]),
            "entries": len(entry_rows),
            "entries_exact": sum(
                1 for r in entry_rows if r["verdict"] == "exact"
            ),
            "production_buckets": len(sched_rows),
            "signed_survivors": sum(
                1 for r in signed_rows if r["survives"]
            ),
            "findings": len(findings),
        },
    }


def run_or_raise(problem=None, backend: str = "pallas") -> dict:
    """Build the cert and raise :class:`RangeCertError` on any finding —
    the ``make analyze`` / CI entry point."""
    cert = build_cert(problem=problem, backend=backend)
    if cert["findings"]:
        head = cert["findings"][:8]
        lines = "; ".join(
            f"[{f['kind']}] {f['where']}: {f['detail']}" for f in head
        )
        more = len(cert["findings"]) - len(head)
        raise RangeCertError(
            f"value-range certification failed with "
            f"{len(cert['findings'])} finding(s): {lines}"
            + (f" (+{more} more)" if more else "")
        )
    return cert
