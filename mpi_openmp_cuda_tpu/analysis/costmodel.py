"""Static cost sheet for the fused kernel and the composed schedule.

BENCH_r05's headline gap — 0.31–0.40 single-program MFU vs 0.217 across
the real bucketed schedule — lives *between* kernels, where neither the
AST lint nor the per-kernel chooser model can see it.  This pass makes
the schedule-level number a statically derivable quantity: every fact
it prices (FLOPs issued, launches, executables, modelled kernel wall,
minimum HBM traffic) is host arithmetic over the SAME derivations the
production dispatch runs (``ops.schedule.kernel_configs``) and the SAME
calibrated iteration model the chooser minimises
(``pallas_scorer.superblock_model_cost`` + ``model_constants``), so it
runs on CPU with zero devices in milliseconds and is golden-pinnable.

Three products:

* :func:`config_cost` / :func:`sweep_config_costs` — a per-config sheet
  over every emittable kernel configuration
  (``pallas_scorer.emittable_superblocks``, the chooser's own candidate
  enumeration): FLOPs, modelled wall, and an MFU bound per canonical
  work unit (one fully-live pair, or one packed tile).
* :func:`schedule_cost_sheet` — the composed bucketed schedule priced
  bucket by bucket, chunk by chunk: launch count, distinct executables,
  ``predicted_mfu_vs_feed_roofline`` (the number bench.py emits next to
  the measured one, so the gap is a quantified regression-gated
  quantity), and the hot-config ranking an AOT compile cache should
  warm first (ROADMAP item 5).
* :func:`predicted_mfu_vs_feed_roofline` — the single scalar for
  bench.py's record.
* :func:`ici_collective_wall_s` + the sheet's ``comms`` section — the
  ICI comms model (ring-algorithm bytes x link bandwidth + hop
  latency): a comms roofline next to the feed roofline, priced into
  ``predicted_scaling_efficiency`` rows for 2x/4x/8x meshes on both
  partition axes, which the collective audit
  (``analysis/collectives.py``) golden-pins and MULTICHIP_r*.json can
  later be audited against.

Model scope (documented, deliberately): the kernel wall is the
calibrated per-iteration model (log-err 0.025–0.038 vs measured kernel
walls); launches are priced at a nominal in-program cost
(:data:`LAUNCH_OVERHEAD_S`); bytes are the *minimum* HBM traffic (each
operand crosses HBM<->VMEM once per launch — re-streaming can only add).
The prediction is NOT fitted to the measured schedule number: the
measured-vs-predicted difference is the unexplained between-kernel loss
ROADMAP item 2's megakernel work must drive down.
"""

from __future__ import annotations

import dataclasses

from . import CostModelError

_BLK = 128

#: Nominal per-feed MXU roofline, matching bench.py's denominator
#: conventions: the bf16 quiet-probe reference is ~197 TFLOP/s on the
#: reference chip (bench.QUIET_BF16_BY_KIND), the i8 feed drives the
#: MXU at the architectural 2x of that (bench's "2x_bf16_probe" roof),
#: and f32 issues at ~1/4 the bf16 rate.  Static stand-ins for the
#: measured probes so the prediction exists with zero devices.
FEED_ROOFLINE_TFLOPS = {"i8": 394.0, "bf16": 197.0, "f32": 49.2}

#: Nominal cost of one kernel launch *inside* a compiled program
#: (scalar prologue, grid setup, semaphore round-trip) — NOT the ~40 us
#: host-dispatch floor, which the steady-state harness amortises away.
#: A deliberate model constant, not a fit: the schedule prediction must
#: stay independent of the measurement it is gauged against.
LAUNCH_OVERHEAD_S = 2.0e-6

#: Traffic the value table contributes per launch (27*27 int32).
_VAL_BYTES = 27 * 27 * 4

#: Nominal per-link ICI bandwidth (one direction of one ring link) and
#: per-hop latency for the comms model.  Deliberate model constants in
#: the :data:`LAUNCH_OVERHEAD_S` tradition — NOT fitted to a measured
#: multi-chip record, so the modelled ``predicted_scaling_efficiency``
#: stays an independent prediction MULTICHIP_r*.json can be audited
#: against.  45 GB/s is the order of one v4/v5e ICI link direction.
ICI_LINK_GBYTES_S = 45.0
ICI_HOP_LATENCY_S = 1.0e-6

#: Mesh sizes the scaling sheet prices (ISSUE 14: 2x/4x/8x).
SCALING_MESH_SIZES = (2, 4, 8)


def ici_collective_wall_s(
    op: str, payload_bytes: int, axis_size: int
) -> float:
    """Modelled wall of one collective over an ``axis_size``-member ring
    (the ICI topology both the TPU interconnect and ``parallel/ring.py``
    assume): standard ring-algorithm costs in bytes x link bandwidth
    plus hop latency.

    - ``ppermute``: one neighbour hop — ``b/bw + hop``.
    - ``all_gather``: N-1 ring steps each moving the payload —
      ``(N-1) * (b/bw + hop)``.
    - ``psum`` (all-reduce): reduce-scatter + all-gather —
      ``2(N-1)/N * b/bw + 2(N-1) * hop``.
    - ``all_to_all`` / ``reduce_scatter``: ``(N-1)/N * b/bw +
      (N-1) * hop``.
    """
    if axis_size <= 1:
        return 0.0
    bw = ICI_LINK_GBYTES_S * 1e9
    n = axis_size
    b = float(payload_bytes)
    if op in ("ppermute", "pshuffle"):
        return b / bw + ICI_HOP_LATENCY_S
    if op == "all_gather":
        return (n - 1) * (b / bw + ICI_HOP_LATENCY_S)
    if op in ("psum", "pmax", "pmin"):
        return 2 * (n - 1) / n * b / bw + 2 * (n - 1) * ICI_HOP_LATENCY_S
    if op in ("all_to_all", "reduce_scatter", "psum_scatter"):
        return (n - 1) / n * b / bw + (n - 1) * ICI_HOP_LATENCY_S
    raise CostModelError(f"no ICI cost rule for collective {op!r}")


def _lens_hist(lens) -> tuple:
    """128-rounded length histogram, the exact key shape
    ``choose_superblock`` feeds the iteration model (zero-length padding
    rows carry no live char-blocks and are dropped, matching the
    chooser; the packed walk re-adds their super-block-0 cost via
    ``kernel_mxu_flops``'s padded-tile accounting)."""
    hist: dict[int, int] = {}
    for l2 in lens:
        l2 = int(l2)
        if l2 <= 0:
            continue
        l2r = -(-l2 // _BLK) * _BLK
        hist[l2r] = hist.get(l2r, 0) + 1
    return tuple(sorted(hist.items()))


def _packed_model_wall_s(
    flops: int, feed: str, sb: int
) -> float:
    """Modelled kernel wall of a row-packed walk that issued ``flops``:
    the packed kernel runs one one-hot plus one full-W prefix matmul
    per executed tile (``kernel_mxu_flops``'s packed arm), so the tile
    count falls out of the FLOP total, and each tile pays the larger of
    the calibrated iteration floor and its MAC issue time — the same
    max(floor, macs/rate) structure ``superblock_model_cost`` applies
    to the unpacked walk (packed buckets are nbi == 1, i.e. 1-wide)."""
    from ..ops.pallas_scorer import model_constants

    base, per_sb, rate = model_constants(feed)
    per_tile_macs = 2 * _BLK * _BLK * (sb * _BLK + _BLK)
    tiles = flops // (2 * per_tile_macs)
    t_tile = max(base + sb * per_sb, per_tile_macs / rate)
    return tiles * t_tile


@dataclasses.dataclass(frozen=True)
class ConfigCost:
    """Static cost of one emittable kernel configuration, per canonical
    work unit — one fully-live pair (unpacked) or one fully-packed tile
    of p = 128/l2s pairs (packed)."""

    kind: str  # 'unpacked' | 'packed'
    feed: str
    nbn: int
    nbi: int
    sb: int
    l2s: int | None
    flops: int  # MXU FLOPs per work unit
    model_wall_s: float  # calibrated-model kernel time per work unit
    vmem_bytes: int  # modelled resident footprint (analysis.vmem)
    mfu_bound: float  # flops / model_wall_s / feed roofline

    def describe(self) -> str:
        return (
            f"{self.kind:<8s} feed={self.feed:<4s} nbn={self.nbn:>2d} "
            f"nbi={self.nbi:>2d} sb={self.sb:>2d} "
            f"l2s={self.l2s or '-':>2} "
            f"flops={self.flops:>12d} "
            f"model={self.model_wall_s * 1e6:8.2f}us "
            f"mfu<={self.mfu_bound:5.3f}"
        )


def config_cost(
    nbn: int, nbi: int, feed: str, sb: int, l2s: int | None = None
) -> ConfigCost:
    """Price one kernel configuration (see :class:`ConfigCost`)."""
    from ..ops.pallas_scorer import (
        kernel_mxu_flops,
        model_constants,
        superblock_model_cost,
    )
    from .vmem import estimate_packed, estimate_unpacked

    len1 = nbn * _BLK
    l1p = nbn * _BLK
    if l2s is not None:
        l2p = _BLK
        p = _BLK // l2s
        lens = [l2s] * p  # one fully-packed tile
        flops = kernel_mxu_flops(len1, lens, l1p, l2p, feed, sb=sb, l2s=l2s)
        wall = _packed_model_wall_s(flops, feed, sb)
        vmem = estimate_packed(nbn, feed, sb, l2s).total_bytes
    else:
        l2p = nbi * _BLK
        lens = [l2p]  # one fully-live pair
        flops = kernel_mxu_flops(len1, lens, l1p, l2p, feed, sb=sb)
        base, per_sb, rate = model_constants(feed)
        wall = superblock_model_cost(
            nbn, nbi, len1, _lens_hist(lens), sb,
            base=base, per_sb=per_sb, rate=rate,
        )
        vmem = estimate_unpacked(nbn, nbi, feed, sb, pp=2).total_bytes
    if wall <= 0.0:
        raise CostModelError(
            f"modelled wall is non-positive for nbn={nbn} nbi={nbi} "
            f"feed={feed} sb={sb} l2s={l2s}: the iteration model "
            "(pallas_scorer.superblock_model_cost) no longer covers this "
            "configuration"
        )
    roof = FEED_ROOFLINE_TFLOPS[feed] * 1e12
    return ConfigCost(
        kind="packed" if l2s is not None else "unpacked",
        feed=feed,
        nbn=nbn,
        nbi=nbi,
        sb=sb,
        l2s=l2s,
        flops=int(flops),
        model_wall_s=float(wall),
        vmem_bytes=int(vmem),
        mfu_bound=float(flops / wall / roof),
    )


def sweep_config_costs():
    """Yield a :class:`ConfigCost` for every configuration the dispatch
    choosers can emit — the same space ``analysis.vmem.iter_chooser_space``
    sweeps, enumerated through ``pallas_scorer.emittable_superblocks``
    so a chooser change is automatically re-priced."""
    import itertools

    from ..ops.dispatch import pack_classes
    from ..ops.pallas_scorer import emittable_superblocks
    from .vmem import _FEED_MAXV, MAX_NBI, MAX_NBN

    for nbn, nbi in itertools.product(
        range(1, MAX_NBN + 1), range(1, MAX_NBI + 1)
    ):
        for feed in ("i8", "bf16", "f32"):
            for sb in emittable_superblocks(nbn, nbi, feed):
                yield config_cost(nbn, nbi, feed, sb)

    for nbn in range(1, MAX_NBN + 1):
        for feed, maxvs in _FEED_MAXV.items():
            classes: set[int] = set()
            for maxv in maxvs:
                classes.update(pack_classes(feed, maxv))
            for sb in emittable_superblocks(nbn, 1, feed):
                for l2s in sorted(classes):
                    yield config_cost(nbn, 1, feed, sb, l2s=l2s)


def audit_config_space():
    """Sweep the whole emittable space and return ``(n, best)`` where
    ``best`` is the highest-MFU-bound config; raises
    :class:`CostModelError` on any non-finite or non-positive cost
    (a config the iteration model can no longer price)."""
    import math

    n = 0
    best: ConfigCost | None = None
    for cc in sweep_config_costs():
        n += 1
        if not (math.isfinite(cc.model_wall_s) and cc.flops > 0):
            raise CostModelError(
                f"non-finite or empty cost for emittable config: "
                f"{cc.describe()}"
            )
        if best is None or cc.mfu_bound > best.mfu_bound:
            best = cc
    if best is None:
        raise CostModelError("config sweep yielded no configurations")
    return n, best


def _bucket_bytes_moved(cfg, est_a_bytes: int) -> int:
    """Minimum HBM traffic for one LAUNCH of this bucket: the A band,
    the chunk's rows/lens operands, the value table, and the output —
    each crossing HBM<->VMEM once (re-streaming can only add)."""
    rows = cfg.cb * cfg.l2p * 4
    lens = cfg.cb * 4
    out = cfg.cb * 3 * 4
    seq1ext = (cfg.l1p + cfg.l2p + 1) * 4
    return est_a_bytes + rows + lens + out + seq1ext + _VAL_BYTES


def _scaling_rows(
    cfg_costs: list, total_model_s: float, total_launches: int,
    backend: str,
) -> list[dict]:
    """``predicted_scaling_efficiency`` rows for 2x/4x/8x meshes, one
    per (mesh size, partition axis).  Batch partitioning shards each
    chunk's rows across devices (``parallel/sharding.py``): compute
    divides by N, every device still walks the full launch sequence,
    comms is zero.  Seq partitioning is
    the ring (``parallel/ring.py``): compute divides by N, but every
    bucket pays ``ring_plan``'s R neighbour exchanges plus the
    candidate all_gather per chunk — priced by
    :func:`ici_collective_wall_s`, the comms roofline next to the feed
    roofline.  Efficiency is ``T1 / (N * T_N)``."""
    from ..parallel.ring import ring_plan

    t1 = total_model_s + total_launches * LAUNCH_OVERHEAD_S
    rows = []
    for n in SCALING_MESH_SIZES:
        # -- batch axis: rows shard over devices, no collectives --
        tn = total_model_s / n + total_launches * LAUNCH_OVERHEAD_S
        rows.append(
            {
                "mesh": n,
                "axis": "batch",
                "comms_wall_us": 0.0,
                "predicted_wall_us": round(tn * 1e6, 3),
                "predicted_scaling_efficiency": round(t1 / (n * tn), 3),
            }
        )
        # -- seq axis: the ring pays R ppermutes + a candidate gather --
        comms_s = 0.0
        for cfg, _ in cfg_costs:
            bs, r = ring_plan(
                cfg.l1p, cfg.l2p, n, pallas=(backend == "pallas")
            )
            comms_s += cfg.n_chunks * (
                r * ici_collective_wall_s("ppermute", bs * 4, n)
                + ici_collective_wall_s("all_gather", cfg.cb * 4 * 4, n)
            )
        tn = total_model_s / n + total_launches * LAUNCH_OVERHEAD_S + comms_s
        rows.append(
            {
                "mesh": n,
                "axis": "seq",
                "comms_wall_us": round(comms_s * 1e6, 3),
                "predicted_wall_us": round(tn * 1e6, 3),
                "predicted_scaling_efficiency": round(t1 / (n * tn), 3),
            }
        )
    return rows


def schedule_cost_sheet(problem, backend: str = "pallas") -> dict:
    """Price ``problem``'s composed production bucket schedule.

    Returns a JSON-ready dict (see ``scripts/schedule_audit.py`` for the
    enveloped report): per-bucket rows, schedule totals (FLOPs, bytes,
    launches, executables, modelled wall), the
    ``predicted_mfu_vs_feed_roofline`` scalar, and the hot-config
    ranking for the AOT warm set.  Off-kernel schedules (wide weights /
    unaligned buckets) return a sheet with ``"feed": None`` and no
    prediction — counts for work that never runs must not be recorded.
    """
    from ..ops.pallas_scorer import (
        kernel_mxu_flops,
        kernel_vpu_pass_elems,
        model_constants,
        superblock_model_cost,
    )
    from ..ops.schedule import kernel_configs
    from .vmem import estimate_packed, estimate_unpacked

    cfgs = kernel_configs(problem, backend, buckets=True)
    if cfgs is None:
        return {
            "backend": backend,
            "feed": None,
            "buckets": [],
            "totals": None,
            "predicted_mfu_vs_feed_roofline": None,
            "hot_configs": [],
            "fused": None,
            "comms": None,
        }

    feed = cfgs[0].feed
    base, per_sb, rate = model_constants(feed)
    buckets = []
    total_flops = 0
    total_vpu = 0
    total_bytes = 0
    total_launches = 0
    total_model_s = 0.0
    cfg_costs: list = []
    by_key: dict[tuple, dict] = {}
    for cfg in cfgs:
        nbn, nbi = cfg.l1p // _BLK, cfg.l2p // _BLK
        b_flops = 0
        b_vpu = 0
        b_model_s = 0.0
        for chunk_lens in cfg.chunk_lens:
            flops = kernel_mxu_flops(
                cfg.len1, chunk_lens, cfg.l1p, cfg.l2p, cfg.feed,
                sb=cfg.sb, l2s=cfg.l2s,
            )
            b_flops += flops
            b_vpu += sum(
                kernel_vpu_pass_elems(
                    cfg.len1, chunk_lens, cfg.l1p, cfg.l2p, cfg.feed,
                    sb=cfg.sb, l2s=cfg.l2s,
                ).values()
            )
            if cfg.l2s is not None:
                b_model_s += _packed_model_wall_s(flops, cfg.feed, cfg.sb)
            else:
                b_model_s += superblock_model_cost(
                    nbn, nbi, cfg.len1, _lens_hist(chunk_lens), cfg.sb,
                    base=base, per_sb=per_sb, rate=rate,
                )
        if cfg.l2s is not None:
            a_bytes = estimate_packed(nbn, cfg.feed, cfg.sb, cfg.l2s).a_bytes
        else:
            a_bytes = estimate_unpacked(
                nbn, nbi, cfg.feed, cfg.sb, pp=2
            ).a_bytes
        b_bytes = cfg.n_chunks * _bucket_bytes_moved(cfg, a_bytes)
        row = {
            "l1p": cfg.l1p,
            "l2p": cfg.l2p,
            "cb": cfg.cb,
            "launches": cfg.n_chunks,
            "formulation": cfg.formulation,
            "feed": cfg.feed,
            "sb": cfg.sb,
            "l2s": cfg.l2s,
            "mxu_flops": int(b_flops),
            "vpu_pass_elems": int(b_vpu),
            "bytes_moved_min": int(b_bytes),
            "model_kernel_us": round(b_model_s * 1e6, 3),
        }
        buckets.append(row)
        total_flops += b_flops
        total_vpu += b_vpu
        total_bytes += b_bytes
        total_launches += cfg.n_chunks
        total_model_s += b_model_s
        cfg_costs.append((cfg, b_model_s))
        agg = by_key.setdefault(
            cfg.cache_key,
            {
                "formulation": cfg.formulation,
                "feed": cfg.feed,
                "l1p": cfg.l1p,
                "l2p": cfg.l2p,
                "cb": cfg.cb,
                "sb": cfg.sb,
                "l2s": cfg.l2s,
                "launches": 0,
                "model_kernel_s": 0.0,
            },
        )
        agg["launches"] += cfg.n_chunks
        agg["model_kernel_s"] += b_model_s

    predicted_wall_s = total_model_s + total_launches * LAUNCH_OVERHEAD_S
    roof = FEED_ROOFLINE_TFLOPS[feed]
    predicted_tflops = total_flops / predicted_wall_s / 1e12
    hot = sorted(
        by_key.values(), key=lambda r: -r["model_kernel_s"]
    )
    hot_rows = []
    for rank, r in enumerate(hot, start=1):
        hot_rows.append(
            {
                "rank": rank,
                "formulation": r["formulation"],
                "feed": r["feed"],
                "l1p": r["l1p"],
                "l2p": r["l2p"],
                "cb": r["cb"],
                "sb": r["sb"],
                "l2s": r["l2s"],
                "launches": r["launches"],
                "model_kernel_us": round(r["model_kernel_s"] * 1e6, 3),
                "wall_share": round(r["model_kernel_s"] / total_model_s, 4),
            }
        )
    return {
        "backend": backend,
        "feed": feed,
        "buckets": buckets,
        "totals": {
            "mxu_flops": int(total_flops),
            "vpu_pass_elems": int(total_vpu),
            "bytes_moved_min": int(total_bytes),
            "launches": int(total_launches),
            "executables": len(by_key),
            "model_kernel_us": round(total_model_s * 1e6, 3),
            "launch_overhead_us": round(
                total_launches * LAUNCH_OVERHEAD_S * 1e6, 3
            ),
            "predicted_wall_us": round(predicted_wall_s * 1e6, 3),
        },
        "feed_roofline_tflops": roof,
        "predicted_tflops": round(predicted_tflops, 2),
        "predicted_mfu_vs_feed_roofline": round(
            total_flops / predicted_wall_s / (roof * 1e12), 3
        ),
        "hot_configs": hot_rows,
        # Launch-fusion view (r6): the bucket-key partition the fusion
        # planner chose and the launch count it declares — the same
        # numbers the trace auditor's launch-budget gate enforces on the
        # actual lowering.  The launch_overhead_us total above collapses
        # with the group count (launch count x LAUNCH_OVERHEAD_S).
        "fused": {
            "groups": [list(cfg.bucket_keys) for cfg in cfgs],
            "declared_launches": int(total_launches),
        },
        "comms": {
            "ici_link_gbytes_s": ICI_LINK_GBYTES_S,
            "ici_hop_latency_us": round(ICI_HOP_LATENCY_S * 1e6, 3),
            "scaling": _scaling_rows(
                cfg_costs, total_model_s, total_launches, backend
            ),
        },
    }


def predicted_mfu_vs_feed_roofline(problem, backend: str) -> float | None:
    """The scalar bench.py emits next to the measured
    ``mfu_vs_feed_roofline``; ``None`` when any part of the schedule
    falls off the fused kernel."""
    sheet = schedule_cost_sheet(problem, backend)
    return sheet["predicted_mfu_vs_feed_roofline"]
