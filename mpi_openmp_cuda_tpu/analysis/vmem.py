"""Static per-config VMEM footprint model for the fused Pallas kernel.

PR 2's lesson: the f32 wide-walk rejection ("double-width f32 tiles
spill VMEM") sat unmeasured in the chooser for a full PR cycle.  This
pass makes the memory story a machine-checked artifact: the footprint
of every configuration the dispatch choosers can EMIT is modelled
statically — from the same parameters that build the ``BlockSpec``s in
``_pallas_call`` / ``_pallas_call_packed`` — and
:func:`audit_chooser_space` fails CI if any emitted config exceeds the
per-core budget.  Runs in milliseconds on CPU; no TPU, no tracing.

The model (all byte counts; ``_BLK = 128`` rows throughout):

* **Resident A** — the value-expanded Seq1 band is grid-invariant
  (constant BlockSpec index map), so exactly one copy lives in VMEM for
  the whole grid.  Pre-tiled layout: ``slots * 128 * bandw * itemsize``
  (the literal ``_pretile_ok`` expression, capped at its 8 MiB budget);
  flat fallback: ``128 * wneed * itemsize``.
* **Streamed blocks** — the codes and output blocks vary with the grid
  index, so Pallas double-buffers them: 2x ``pp * nbi * 128 * 4`` in,
  2x ``pp * 128 * 4`` out.
* **Kernel working set** — per interleaved tile ("half"), the maximum
  over the stage pipeline: stage 2's rotate holds source + destination
  accumulators (``2 * 128 * bandw * 4``); stage 3 holds the sheared
  accumulator, its feed-dtype copy, and two prefix surfaces
  (``128 * bandw * (4 + item) + 2 * 128 * sbw * 4``).  The flat-A path
  adds the dynamic lane-slice band copy.  Halves run stage-locked
  (stage-major interleave), so the working set is ADDITIVE across
  ``wide``.  ``pp`` pairs are sequential and reuse the working set.

The model is intentionally an upper-bound estimate of *data* in VMEM —
Mosaic's register allocation and op fusion can only shrink it — so a
config passing here has genuine headroom, and the historically measured
spills sit where the model says pressure peaks (the 4-wide f32 walk at
sb >= 8 models at ~2x the 2-wide working set that measured clean).
"""

from __future__ import annotations

import dataclasses
import itertools

from . import VmemBudgetError

_BLK = 128
#: Per-core VMEM capacity (the pallas guide's ~16 MB/core figure).
VMEM_BUDGET_BYTES = 16 << 20

_ITEM = {"i8": 1, "bf16": 2, "f32": 4}

#: Shape caps of the bucketed schedule: BUF_SIZE_SEQ1 = 3000 -> l1p <=
#: 3072 (nbn <= 24), BUF_SIZE_SEQ2 = 2000 -> l2p <= 2048 (nbi <= 16).
MAX_NBN = 24
MAX_NBI = 16

#: Representative weight magnitudes per feed for the rowpack sweep: the
#: feed boundaries plus the f32 exactness milestones (static 4095
#: ceiling, length-aware 32767 cap at l2p = 128).
_FEED_MAXV = {
    "i8": (127,),
    "bf16": (128,),
    "f32": (129, 1000, 4095, 32767),
}


@dataclasses.dataclass(frozen=True)
class VmemEstimate:
    """Modelled footprint of one kernel configuration."""

    kind: str  # 'unpacked' | 'packed'
    feed: str
    nbn: int
    nbi: int
    sb: int
    pp: int  # pairs per grid cell (unpacked) / p pairs per tile (packed)
    l2s: int | None  # rowpack class (packed only)
    pretiled: bool
    a_bytes: int
    stream_bytes: int
    working_bytes: int

    @property
    def total_bytes(self) -> int:
        return self.a_bytes + self.stream_bytes + self.working_bytes

    @property
    def headroom_bytes(self) -> int:
        return VMEM_BUDGET_BYTES - self.total_bytes

    def describe(self) -> str:
        mib = self.total_bytes / (1 << 20)
        return (
            f"{self.kind:<8s} feed={self.feed:<4s} nbn={self.nbn:>2d} "
            f"nbi={self.nbi:>2d} sb={self.sb:>2d} pp={self.pp} "
            f"l2s={self.l2s or '-':>2} "
            f"{'pretiled' if self.pretiled else 'flat':>8s} "
            f"total={mib:6.2f} MiB "
            f"(A={self.a_bytes >> 10} KiB, stream="
            f"{self.stream_bytes >> 10} KiB, work="
            f"{self.working_bytes >> 10} KiB)"
        )


def estimate_unpacked(
    nbn: int, nbi: int, feed: str, sb: int, pp: int
) -> VmemEstimate:
    """Model one ``_pallas_call`` configuration (the [B, L2P] kernel)."""
    from ..ops.pallas_scorer import _pretile_ok

    item = _ITEM[feed]
    sbw = sb * _BLK
    bandw = sbw + _BLK
    wneed = (nbn + nbi) * _BLK
    pretiled = _pretile_ok(nbn, nbi, feed, sb)

    if pretiled:
        slots = (nbn // sb) * nbi
        a_bytes = slots * _BLK * bandw * item
    else:
        a_bytes = _BLK * wneed * item

    # Double-buffered streamed blocks (grid-varying index maps).
    codes = pp * nbi * _BLK * 1 * 4
    out = pp * 1 * _BLK * 4
    stream_bytes = 2 * (codes + out)

    # Per-half stage peak (see module docstring); halves are additive.
    wide = 1 if nbi == 1 else 2
    flat_copy = 0 if pretiled else _BLK * bandw * item
    stage2 = 2 * _BLK * bandw * 4
    stage3 = _BLK * bandw * (4 + item) + 2 * _BLK * sbw * 4
    working_bytes = wide * (max(stage2, stage3) + flat_copy)

    return VmemEstimate(
        kind="unpacked",
        feed=feed,
        nbn=nbn,
        nbi=nbi,
        sb=sb,
        pp=pp,
        l2s=None,
        pretiled=pretiled,
        a_bytes=a_bytes,
        stream_bytes=stream_bytes,
        working_bytes=working_bytes,
    )


def estimate_packed(nbn: int, feed: str, sb: int, l2s: int) -> VmemEstimate:
    """Model one ``_pallas_call_packed`` configuration (nbi == 1,
    p = 128 // l2s pairs per tile)."""
    from ..ops.pallas_scorer import _pretile_ok

    item = _ITEM[feed]
    sbw = sb * _BLK
    w = sbw + _BLK
    wneed = (nbn + 1) * _BLK
    p = _BLK // l2s
    pretiled = _pretile_ok(nbn, 1, feed, sb)

    if pretiled:
        slots = nbn // sb
        a_bytes = slots * _BLK * w * item
    else:
        a_bytes = _BLK * wneed * item

    codes = 1 * 1 * _BLK * 1 * 4
    out = p * 1 * _BLK * 4
    stream_bytes = 2 * (codes + out)

    # Packed pipeline peak: P, rollP, g, gpack coexist as full-W int32
    # surfaces after the prefix matmul; the rotate's src/dst pair and the
    # feed-dtype narrowed copy peak lower.
    flat_copy = 0 if pretiled else _BLK * w * item
    rotate = 2 * _BLK * w * 4
    epilogue = 4 * _BLK * w * 4
    working_bytes = max(rotate + _BLK * w * item, epilogue) + flat_copy

    return VmemEstimate(
        kind="packed",
        feed=feed,
        nbn=nbn,
        nbi=1,
        sb=sb,
        pp=p,
        l2s=l2s,
        pretiled=pretiled,
        a_bytes=a_bytes,
        stream_bytes=stream_bytes,
        working_bytes=working_bytes,
    )


def fits_budget(
    nbn: int,
    nbi: int,
    feed: str,
    sb: int,
    pp: int = 2,
    budget: int = VMEM_BUDGET_BYTES,
) -> bool:
    """Feasibility predicate consumed by the chooser's candidate filter
    (``pallas_scorer.emittable_superblocks``): does the worst-case
    (pp = 2) modelled footprint of this unpacked config fit the per-core
    budget?  The packed kernel needs no gate: at nbi == 1 every sb <= 24
    models under budget for all feeds and classes (verified by the
    exhaustive sweep)."""
    return estimate_unpacked(nbn, nbi, feed, sb, pp).total_bytes <= budget


def iter_chooser_space():
    """Yield a :class:`VmemEstimate` for every configuration the
    dispatch choosers can emit across the bucketed schedule's shape caps
    (all feeds, packed and unpacked, both pp parities).  The emittable
    super-block set comes from the chooser's own candidate enumeration
    (``pallas_scorer.emittable_superblocks``), so a chooser change that
    widens the space is automatically re-audited."""
    from ..ops.dispatch import pack_classes
    from ..ops.pallas_scorer import emittable_superblocks

    for nbn, nbi in itertools.product(
        range(1, MAX_NBN + 1), range(1, MAX_NBI + 1)
    ):
        for feed in ("i8", "bf16", "f32"):
            for sb in emittable_superblocks(nbn, nbi, feed):
                for pp in (1, 2):
                    yield estimate_unpacked(nbn, nbi, feed, sb, pp)

    # Row-packed kernel: single char-block buckets only (l2p == 128).
    for nbn in range(1, MAX_NBN + 1):
        for feed, maxvs in _FEED_MAXV.items():
            classes = set()
            for maxv in maxvs:
                classes.update(pack_classes(feed, maxv))
            for sb in emittable_superblocks(nbn, 1, feed):
                for l2s in sorted(classes):
                    yield estimate_packed(nbn, feed, sb, l2s)


def audit_chooser_space(budget: int = VMEM_BUDGET_BYTES):
    """Exhaustively sweep the chooser space against ``budget``.

    Returns ``(n_configs, worst)`` where ``worst`` is the
    :class:`VmemEstimate` with the least headroom; raises
    :class:`VmemBudgetError` listing every over-budget config (capped at
    20 rows) if the sweep finds any."""
    over: list[VmemEstimate] = []
    worst: VmemEstimate | None = None
    n = 0
    for est in iter_chooser_space():
        n += 1
        if worst is None or est.total_bytes > worst.total_bytes:
            worst = est
        if est.total_bytes > budget:
            over.append(est)
    if worst is None:
        raise VmemBudgetError("chooser sweep yielded no configurations")
    if over:
        over.sort(key=lambda e: -e.total_bytes)
        rows = "\n  ".join(e.describe() for e in over[:20])
        more = f"\n  ... and {len(over) - 20} more" if len(over) > 20 else ""
        raise VmemBudgetError(
            f"{len(over)} of {n} emittable kernel configs exceed the "
            f"{budget >> 20} MiB per-core VMEM budget:\n  {rows}{more}\n"
            "Shrink the offending config's superblock/pretile footprint "
            "or gate it out in ops/dispatch (choose_superblock / "
            "pack_classes) before it reaches hardware."
        )
    return n, worst


def audit_fused_configs(
    problem, backend: str = "pallas", budget: int = VMEM_BUDGET_BYTES
):
    """Audit the FUSED production schedule's concrete launch-group
    configs against the VMEM budget (r6): the fusion planner widens
    member buckets to the group L2P, so every emitted (nbn, nbi, feed,
    sb, l2s) pair is re-modelled here at the chunk parity the dispatch
    actually picks.  Returns JSON-ready rows (one per launch group);
    raises :class:`VmemBudgetError` on any over-budget group.  The
    groups live inside :func:`iter_chooser_space`'s swept envelope, so
    this is a pointed re-check of the live schedule, not a new pass."""
    from ..ops.schedule import kernel_configs

    cfgs = kernel_configs(problem, backend, buckets=True)
    rows = []
    for cfg in cfgs or []:
        if cfg.formulation != "pallas":
            continue
        est = check_config(
            nbn=cfg.l1p // 128,
            nbi=cfg.l2p // 128,
            feed=cfg.feed,
            sb=cfg.sb,
            pp=2 if cfg.cb % 2 == 0 else 1,
            l2s=cfg.l2s,
            budget=budget,
        )
        rows.append(
            {
                "bucket_keys": list(cfg.bucket_keys),
                "l1p": cfg.l1p,
                "l2p": cfg.l2p,
                "sb": cfg.sb,
                "l2s": cfg.l2s,
                "feed": cfg.feed,
                "total_bytes": est.total_bytes,
                "headroom_bytes": est.headroom_bytes,
            }
        )
    return rows


def check_config(
    *,
    nbn: int,
    nbi: int,
    feed: str,
    sb: int,
    pp: int = 2,
    l2s: int | None = None,
    budget: int = VMEM_BUDGET_BYTES,
) -> VmemEstimate:
    """Model ONE concrete config (the ``--check`` dispatch hook) and
    raise :class:`VmemBudgetError` if it exceeds ``budget``."""
    if l2s is not None:
        est = estimate_packed(nbn, feed, sb, l2s)
    else:
        est = estimate_unpacked(nbn, nbi, feed, sb, pp)
    if est.total_bytes > budget:
        raise VmemBudgetError(
            f"dispatch emitted a kernel config over the {budget >> 20} MiB "
            f"per-core VMEM budget: {est.describe()}"
        )
    return est
