"""Repo-specific AST lint (``seqlint``).

Generic linters cannot know that ``.item()`` inside a traced scoring
body forces a device sync, that env reads outside the platform registry
fragment configuration, or that a wall-clock read inside the resilience
decision paths breaks replay determinism.  These rules encode THIS
repo's conventions:

=======  ==================================================================
SEQ001   no host-sync (``.item()`` / ``np.asarray`` / ``np.array`` /
         ``float()``/``int()`` on expressions) inside traced scoring
         paths (ops/ and parallel/ kernel & body functions) — each one
         stalls the device pipeline per call.
SEQ002   no ``os.environ`` / ``os.getenv`` outside ``utils/platform.py``
         — all knobs go through the typed env registry so ``--help`` and
         the docs can enumerate them (PR 3 satellite).
SEQ003   no Python ``if``/``while`` on traced intermediates inside
         traced scoring paths — tracing turns them into
         ``TracerBoolConversionError`` at best, silent per-shape
         recompiles at worst; use ``lax.cond``/``jnp.where``.
SEQ004   no bare ``assert`` in runtime paths (the package) — asserts
         vanish under ``python -O``; raise ``RuntimeError`` with an
         actionable message instead (codifies PR 1's migration).
SEQ005   no wall-clock reads (``time.time``/``monotonic``/
         ``perf_counter`` / ``datetime.now``) in the deterministic
         resilience / journal decision paths — fault injection and
         replay must be time-independent (``time.sleep`` is fine: it
         delays, it does not decide).
SEQ006   no direct ``print(..., file=sys.stderr)`` in the instrumented
         modules (resilience/, journal, dispatch, distributed) — route
         diagnostics through ``obs.events.log_line`` so an armed
         observability plane sees every line the operator sees (PR 5).
SEQ007   no bare blocking waits (``time.sleep`` / ``Condition.wait`` /
         ``wait_for``) in ``serve/`` outside ``serve/clock.py`` — every
         serve-loop wait must ride the injectable
         ``ServeClock.block_until`` so tests drive a fake clock and a
         drain signal is noticed within one bounded wait (PR 6).
=======  ==================================================================

Suppression: append ``# seqlint: disable=SEQ00N`` to the offending line
(multiple codes comma-separated).  A file-level
``# seqlint: disable-file=SEQ00N`` in the first ten lines suppresses a
rule for the whole file.  ``analysis/`` itself must stay
suppression-free (ISSUE 3 acceptance).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path

from . import LintError

#: Functions considered "traced scoring paths" for SEQ001/SEQ003: the
#: kernel bodies, the chunked-batch bodies, and the nested shard_map /
#: loop-body callables in ops/ and parallel/.
_TRACED_NAME_RE = re.compile(
    r"^(_kernel\w*|_pair|\w*_body|local_fn|fn|cands|ibody\w*|nbody|"
    r"prologue|step|combine|inner)$"
)

#: Modules whose traced functions SEQ001/SEQ003 police.
_TRACED_DIRS = ("ops", "parallel")

#: Modules whose DECISIONS must be wall-clock-free (SEQ005).
_DETERMINISTIC_PATHS = ("resilience/", "utils/journal.py", "serve/queue.py")

#: The serving plane's single legal home for blocking waits (SEQ007).
_SERVE_CLOCK_HOME = "serve/clock.py"

#: The single legal home for environment reads (SEQ002).
_ENV_HOME = "utils/platform.py"

#: Modules whose stderr diagnostics must flow through the event bus so
#: an armed observability plane mirrors them (SEQ006); ``obs/events.py``
#: itself holds the one blessed ``print`` (the log_line seam).
_INSTRUMENTED_PATHS = (
    "resilience/",
    "utils/journal.py",
    "ops/dispatch.py",
    "parallel/distributed.py",
)

_WALLCLOCK_ATTRS = {
    ("time", "time"),
    ("time", "monotonic"),
    ("time", "perf_counter"),
    ("time", "process_time"),
    ("time", "time_ns"),
    ("time", "monotonic_ns"),
    ("datetime", "now"),
    ("datetime", "utcnow"),
    ("date", "today"),
}

_SUPPRESS_RE = re.compile(r"#\s*seqlint:\s*disable=([A-Z0-9, ]+)")
_SUPPRESS_FILE_RE = re.compile(r"#\s*seqlint:\s*disable-file=([A-Z0-9, ]+)")


@dataclasses.dataclass(frozen=True)
class LintFinding:
    code: str
    path: str
    line: int
    message: str

    def describe(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


def _suppressions(source: str):
    """Per-line and file-level rule suppressions from comments."""
    per_line: dict[int, set[str]] = {}
    file_level: set[str] = set()
    for i, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if m:
            per_line[i] = {c.strip() for c in m.group(1).split(",")}
        if i <= 10:
            m = _SUPPRESS_FILE_RE.search(text)
            if m:
                file_level |= {c.strip() for c in m.group(1).split(",")}
    return per_line, file_level


class _Scope:
    """One function scope: whether it is a traced scoring path, and
    which local names hold traced intermediates (assigned from jnp/lax/
    pl/pltpu calls or from the function's array-like parameters)."""

    def __init__(self, name: str, traced: bool):
        self.name = name
        self.traced = traced
        self.traced_names: set[str] = set()


_TRACED_MODULES = {"jnp", "lax", "pl", "pltpu", "jax", "checkify"}


def _is_traced_expr(node: ast.AST, scope: _Scope) -> bool:
    """Conservatively: does this expression involve a traced value —
    a jnp/lax/... call, or a name previously assigned from one?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in scope.traced_names:
            return True
        if isinstance(sub, ast.Call):
            root = sub.func
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and root.id in _TRACED_MODULES:
                return True
    return False


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.findings: list[LintFinding] = []
        self.per_line, self.file_level = _suppressions(source)
        self.scopes: list[_Scope] = []
        parts = Path(rel).parts
        self.in_traced_dir = len(parts) > 1 and parts[1] in _TRACED_DIRS
        self.is_env_home = rel.endswith(_ENV_HOME)
        self.in_deterministic = any(
            p in rel for p in _DETERMINISTIC_PATHS
        )
        self.in_instrumented = any(
            p in rel for p in _INSTRUMENTED_PATHS
        )
        # Path-segment match, not substring: "serve/" would also match
        # a hypothetical "observe/" module.
        self.in_serve = (
            len(parts) > 1
            and parts[1] == "serve"
            and not rel.endswith(_SERVE_CLOCK_HOME)
        )

    # -- bookkeeping -------------------------------------------------------

    def _emit(self, code: str, node: ast.AST, message: str):
        line = getattr(node, "lineno", 0)
        if code in self.file_level or code in self.per_line.get(line, ()):
            return
        self.findings.append(LintFinding(code, self.rel, line, message))

    def _enter_function(self, node):
        traced = self.in_traced_dir and bool(
            _TRACED_NAME_RE.match(node.name)
        )
        self.scopes.append(_Scope(node.name, traced))
        self.generic_visit(node)
        self.scopes.pop()

    visit_FunctionDef = _enter_function
    visit_AsyncFunctionDef = _enter_function

    @property
    def scope(self) -> _Scope | None:
        for s in reversed(self.scopes):
            if s.traced:
                return s
        return None

    # -- SEQ004: bare assert ----------------------------------------------

    def visit_Assert(self, node: ast.Assert):
        self._emit(
            "SEQ004",
            node,
            "bare assert in a runtime path vanishes under python -O; "
            "raise RuntimeError with an actionable message",
        )
        self.generic_visit(node)

    # -- SEQ003 state: track traced intermediates --------------------------

    def visit_Assign(self, node: ast.Assign):
        scope = self.scope
        if scope is not None and _is_traced_expr(node.value, scope):
            for tgt in node.targets:
                for sub in ast.walk(tgt):
                    if isinstance(sub, ast.Name):
                        scope.traced_names.add(sub.id)
        self.generic_visit(node)

    # -- SEQ003: Python branch on traced values ----------------------------

    def _check_branch(self, node):
        scope = self.scope
        if scope is not None and _is_traced_expr(node.test, scope):
            self._emit(
                "SEQ003",
                node,
                f"Python branch on a traced value in `{scope.name}`: "
                "tracing cannot follow host control flow — use lax.cond "
                "/ lax.select / jnp.where",
            )
        self.generic_visit(node)

    visit_If = _check_branch
    visit_While = _check_branch

    # -- SEQ001 / SEQ002 / SEQ005: calls -----------------------------------

    def visit_Call(self, node: ast.Call):
        func = node.func
        scope = self.scope

        # SEQ001: host-sync inside traced scoring paths.
        if scope is not None:
            if isinstance(func, ast.Attribute) and func.attr == "item":
                self._emit(
                    "SEQ001",
                    node,
                    f".item() in traced path `{scope.name}` forces a "
                    "device->host sync per call; keep the value on device",
                )
            if (
                isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "np"
                and func.attr in ("asarray", "array")
            ):
                self._emit(
                    "SEQ001",
                    node,
                    f"np.{func.attr}() in traced path `{scope.name}` "
                    "materialises the operand on host; use jnp",
                )
            if (
                isinstance(func, ast.Name)
                and func.id in ("float", "int")
                and node.args
                and not isinstance(node.args[0], ast.Constant)
                and _is_traced_expr(node.args[0], scope)
            ):
                self._emit(
                    "SEQ001",
                    node,
                    f"{func.id}() on a traced value in `{scope.name}` "
                    "forces a host sync; use .astype()/jnp casts",
                )

        # SEQ002: env reads outside the registry.
        if not self.is_env_home:
            is_environ = (
                isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Attribute)
                and isinstance(func.value.value, ast.Name)
                and func.value.value.id == "os"
                and func.value.attr == "environ"
            )  # os.environ.get(...)
            is_getenv = (
                isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "os"
                and func.attr == "getenv"
            ) or (isinstance(func, ast.Name) and func.id == "getenv")
            if is_environ or is_getenv:
                self._emit(
                    "SEQ002",
                    node,
                    "environment read outside utils/platform.py; add the "
                    "variable to the env registry (utils.platform) and "
                    "use its typed accessor",
                )

        # SEQ005: wall-clock in deterministic paths.
        if self.in_deterministic and isinstance(func, ast.Attribute):
            base = func.value
            if (
                isinstance(base, ast.Name)
                and (base.id, func.attr) in _WALLCLOCK_ATTRS
            ) or (
                isinstance(base, ast.Attribute)
                and (base.attr, func.attr) in _WALLCLOCK_ATTRS
            ):
                self._emit(
                    "SEQ005",
                    node,
                    "wall-clock read in a deterministic resilience/"
                    "journal path; decisions must replay identically — "
                    "derive from the seeded policy state instead",
                )

        # SEQ006: direct stderr prints in instrumented modules.
        if (
            self.in_instrumented
            and isinstance(func, ast.Name)
            and func.id == "print"
        ):
            for kw in node.keywords:
                v = kw.value
                if (
                    kw.arg == "file"
                    and isinstance(v, ast.Attribute)
                    and v.attr == "stderr"
                ):
                    self._emit(
                        "SEQ006",
                        node,
                        "direct stderr print in an instrumented module "
                        "bypasses the observability plane; emit through "
                        "obs.events.log_line (same bytes on stderr, plus "
                        "a `log` event when the bus is armed)",
                    )

        # SEQ007: bare blocking waits in the serving plane.
        if self.in_serve:
            is_sleep = (
                isinstance(func, ast.Attribute)
                and func.attr == "sleep"
                and isinstance(func.value, ast.Name)
                and func.value.id == "time"
            ) or (isinstance(func, ast.Name) and func.id == "sleep")
            is_wait = isinstance(func, ast.Attribute) and func.attr in (
                "wait",
                "wait_for",
            )
            if is_sleep or is_wait:
                self._emit(
                    "SEQ007",
                    node,
                    "bare blocking wait in the serving plane; route the "
                    "wait through the injectable ServeClock.block_until "
                    "(serve/clock.py) so tests drive a fake clock and "
                    "drain signals stay bounded",
                )
        self.generic_visit(node)

    # -- SEQ002: os.environ subscripts / membership ------------------------

    def visit_Subscript(self, node: ast.Subscript):
        if not self.is_env_home:
            v = node.value
            if (
                isinstance(v, ast.Attribute)
                and isinstance(v.value, ast.Name)
                and v.value.id == "os"
                and v.attr == "environ"
            ):
                self._emit(
                    "SEQ002",
                    node,
                    "environment read outside utils/platform.py; add the "
                    "variable to the env registry (utils.platform) and "
                    "use its typed accessor",
                )
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare):
        # `"X" in os.environ` membership probes count as reads too.
        if not self.is_env_home:
            for cmp_node, op in zip(node.comparators, node.ops):
                if (
                    isinstance(op, (ast.In, ast.NotIn))
                    and isinstance(cmp_node, ast.Attribute)
                    and isinstance(cmp_node.value, ast.Name)
                    and cmp_node.value.id == "os"
                    and cmp_node.attr == "environ"
                ):
                    self._emit(
                        "SEQ002",
                        node,
                        "os.environ membership probe outside "
                        "utils/platform.py; use the env registry's typed "
                        "accessor (utils.platform)",
                    )
        self.generic_visit(node)


def lint_file(path: str | Path, package_root: str | Path) -> list[LintFinding]:
    path = Path(path)
    rel = str(path.relative_to(Path(package_root).parent))
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as exc:
        return [
            LintFinding("SEQ000", rel, exc.lineno or 0, f"syntax error: {exc}")
        ]
    linter = _Linter(str(path), rel, source)
    linter.visit(tree)
    return sorted(linter.findings, key=lambda f: (f.path, f.line, f.code))


def lint_package(package_root: str | Path | None = None) -> list[LintFinding]:
    """Lint every module of the installed package tree.  scripts/ and
    tests/ are host-side tooling, outside the runtime rules' scope."""
    if package_root is None:
        package_root = Path(__file__).resolve().parent.parent
    package_root = Path(package_root)
    findings: list[LintFinding] = []
    for path in sorted(package_root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        findings.extend(lint_file(path, package_root))
    return findings


def run_or_raise(package_root: str | Path | None = None) -> int:
    """Driver entry: lint the package, raise :class:`LintError` listing
    every finding, return the number of files checked when clean."""
    if package_root is None:
        package_root = Path(__file__).resolve().parent.parent
    findings = lint_package(package_root)
    if findings:
        rows = "\n  ".join(f.describe() for f in findings)
        raise LintError(
            f"seqlint: {len(findings)} violation(s):\n  {rows}\n"
            "Fix the violation or suppress a justified case with "
            "`# seqlint: disable=<code>` (see ARCHITECTURE.md §9)."
        )
    return sum(
        1
        for p in Path(package_root).rglob("*.py")
        if "__pycache__" not in p.parts
    )
