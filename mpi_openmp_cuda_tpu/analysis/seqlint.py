"""Repo-specific AST lint (``seqlint``).

Generic linters cannot know that ``.item()`` inside a traced scoring
body forces a device sync, that env reads outside the platform registry
fragment configuration, or that a wall-clock read inside the resilience
decision paths breaks replay determinism.  These rules encode THIS
repo's conventions:

=======  ==================================================================
SEQ001   no host-sync (``.item()`` / ``np.asarray`` / ``np.array`` /
         ``float()``/``int()`` on expressions) inside traced scoring
         paths (ops/ and parallel/ kernel & body functions) — each one
         stalls the device pipeline per call.
SEQ002   no ``os.environ`` / ``os.getenv`` outside ``utils/platform.py``
         — all knobs go through the typed env registry so ``--help`` and
         the docs can enumerate them (PR 3 satellite).
SEQ003   no Python ``if``/``while`` on traced intermediates inside
         traced scoring paths — tracing turns them into
         ``TracerBoolConversionError`` at best, silent per-shape
         recompiles at worst; use ``lax.cond``/``jnp.where``.
SEQ004   no bare ``assert`` in runtime paths (the package) — asserts
         vanish under ``python -O``; raise ``RuntimeError`` with an
         actionable message instead (codifies PR 1's migration).
SEQ005   no wall-clock reads (``time.time``/``monotonic``/
         ``perf_counter`` / ``datetime.now``) in the deterministic
         resilience / journal decision paths — fault injection and
         replay must be time-independent (``time.sleep`` is fine: it
         delays, it does not decide).
SEQ006   no direct ``print(..., file=sys.stderr)`` in the instrumented
         modules (resilience/, journal, dispatch, distributed) — route
         diagnostics through ``obs.events.log_line`` so an armed
         observability plane sees every line the operator sees (PR 5).
SEQ007   no bare blocking waits (``time.sleep`` / ``Condition.wait`` /
         ``wait_for``) in ``serve/`` outside ``serve/clock.py`` — every
         serve-loop wait must ride the injectable
         ``ServeClock.block_until`` so tests drive a fake clock and a
         drain signal is noticed within one bounded wait (PR 6).
SEQ008   serve-plane shared state is mutated only under its owning
         lock: in ``serve/``, a class that declares a
         ``threading.Condition``/``Lock``/``RLock`` attribute is
         *guarded*, and every ``self.*`` mutation outside ``__init__``
         must sit inside ``with self.<guard>:``.  Reader threads
         (socket connections, stdin ingest) may only ``json.loads``
         and enqueue — everything they touch crosses this lock (PR 6's
         threading contract, now machine-checked).
SEQ009   every package module is explicitly classified in the
         ``_MODULE_CLASSES`` registry below (traced / deterministic /
         instrumented / serve-plane / host ...).  A new module that no
         rule list knows about would silently escape SEQ001-008; the
         registry makes that a lint failure instead (the PR 6 drift:
         ``io/pipeline.py`` and ``serve/*`` predated it).
SEQ010   no blocking operation lexically inside a ``with <lock>:`` body
         in serve-plane modules: socket ``accept``/``recv``/``connect``
         (and ``send`` on socket-named receivers), board file I/O
         (``post``/``claim``/``delete`` on board-named receivers,
         ``board_read_json``), ``os`` file ops / ``open()``,
         ``subprocess``, and ``ServeClock.block_until`` on anything but
         the held lock itself (a Condition wait RELEASES its own lock
         while waiting — waiting on a different one keeps the held lock
         pinned through the wait).  A blocking op under a serve lock
         stalls every thread that contends it — the lexical twin of the
         transitive reachability audit in ``analysis/lockgraph.py``
         (rule b), cheap enough to run on every ``make analyze``.
SEQ011   every module-level ``jax.jit(...)`` assignment declares its
         donation policy explicitly: either ``donate_argnums=...``
         (cross-checked against the proven DonationPlan by
         ``analysis/dataflow.py``) or a ``# nodonate: <reason>`` marker
         on the assignment saying why nothing can be donated.  An
         unannotated jit entry is a silent donation-coverage hole — the
         drift that kept the chunk pipeline at zero donation from PR 2
         through PR 12.
SEQ012   raw ``jax.lax`` collectives (``psum`` / ``ppermute`` /
         ``all_gather`` / ``all_to_all`` and friends) are legal only in
         the ``parallel/`` layer — elsewhere they must route through
         the ``parallel/`` wrappers so the collective-safety audit
         (``analysis/collectives.py``) inventories every byte that
         crosses the mesh.  Even inside ``parallel/``, every collective
         call must pass an explicit ``axis_name=`` keyword: a
         positional or implicit axis evades the audit's axis-resolution
         check and is exactly how an unregistered-axis hazard ships.
SEQ013   every numeric-bound literal in traced gate/kernel code (the
         certified overflow constants: ``4095``, ``32767``, ``65535``,
         ``4096``, ``2**19``, ``2**24``, ``2**31`` and their
         ``1 << N`` spellings) carries a ``# cert: <row>`` marker
         naming the RangeCert ``derived_constants`` row that proves it
         (``make ranges-audit``).  A bare ``# cert:`` with no row name
         documents nothing and stays a finding.  An unmarked bound is
         exactly the "hand-derived once, asserted forever" constant
         the value-range certifier (``analysis/ranges.py``) exists to
         retire — wire it through ``ops/bounds.py`` or name its proof.
SEQ014   every broad handler (``except:`` / ``except Exception``) in a
         classified module proves it is not a silent swallow: the body
         re-raises, routes the event through ``log_line``, forwards the
         bound exception into a classifier call (``_block_failed(b, e)``,
         ``_is_resumable(e)`` — the retry/quarantine ladders), or
         carries a reasoned ``# advisory: <why>`` marker saying why
         swallowing is the contract.  A bare ``# advisory:`` with no
         reason text documents nothing and stays a finding.  The lexical
         twin of the exception-flow certifier's ``swallow-unmarked``
         finding (``analysis/exitflow.py``, ``make exitpath-audit``) —
         cheap enough to run on every ``make analyze``, while exitflow
         proves the whole propagation graph behind it.
SEQ015   every WORK-UNIT board post in the serving plane carries trace
         context: a ``json.dumps({...})`` dict literal with both
         ``"bid"`` and ``"rows"`` keys (the fleet offer/result payload
         shape — a superblock crossing a process boundary) must also
         carry a ``"traces"`` key, so the admission-minted trace ids
         survive the hop and the coordinator's merged timeline can link
         remote launches back to their requests.  Control posts
         (claims, heartbeats, checkpoints, registrations) carry no rows
         and are out of scope.
=======  ==================================================================

Suppression: append ``# seqlint: disable=SEQ00N`` to the offending line
(multiple codes comma-separated).  A file-level
``# seqlint: disable-file=SEQ00N`` in the first ten lines suppresses a
rule for the whole file.  ``analysis/`` itself must stay
suppression-free (ISSUE 3 acceptance).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path

from . import LintError

#: Functions considered "traced scoring paths" for SEQ001/SEQ003: the
#: kernel bodies, the chunked-batch bodies, and the nested shard_map /
#: loop-body callables in ops/ and parallel/.
_TRACED_NAME_RE = re.compile(
    r"^(_kernel\w*|_pair|\w*_body|local_fn|fn|cands|ibody\w*|nbody|"
    r"prologue|step|combine|inner)$"
)

#: Module roles.  Each role keys one rule's scope; a module may hold
#: several (resilience/ is both clock-free in its decisions AND routed
#: through the event bus for its diagnostics).
ROLE_TRACED = "traced-scoring"  # SEQ001/SEQ003 police its kernel bodies
ROLE_DETERMINISTIC = "deterministic"  # SEQ005: decisions are clock-free
ROLE_INSTRUMENTED = "instrumented"  # SEQ006: stderr rides the event bus
ROLE_SERVE = "serve-plane"  # SEQ007 waits + SEQ008 shared-state lock
ROLE_WAIT_HOME = "serve-clock-home"  # the one legal blocking-wait seam
ROLE_ENV_HOME = "env-home"  # the one legal os.environ reader
ROLE_COLLECTIVE_HOME = "collective-home"  # SEQ012: raw lax collectives legal
ROLE_HOST = "host"  # plain host-side module; only SEQ002/SEQ004 apply

#: EXHAUSTIVE classification of the package tree.  Exact file entries
#: override their directory's default; ``dir/`` entries classify every
#: module beneath them.  A module matching NEITHER is a SEQ009 finding
#: — new modules must be placed here deliberately, so no rule scope can
#: silently rot again (PR 6 shipped io/pipeline.py and serve/* without
#: touching these lists; this registry turns that into a failure).
_MODULE_CLASSES: dict[str, tuple[str, ...]] = {
    # -- exact files (override the directory default) ----------------------
    # platform.py is also INSTRUMENTED since the AOT plane: its cache-
    # disabled warning rides the event bus (SEQ006), not bare stderr.
    "utils/platform.py": (ROLE_ENV_HOME, ROLE_INSTRUMENTED),
    "utils/journal.py": (ROLE_DETERMINISTIC, ROLE_INSTRUMENTED),
    "ops/dispatch.py": (ROLE_TRACED, ROLE_INSTRUMENTED),
    "parallel/distributed.py": (
        ROLE_TRACED,
        ROLE_INSTRUMENTED,
        ROLE_COLLECTIVE_HOME,
    ),
    "io/pipeline.py": (ROLE_INSTRUMENTED,),
    "serve/clock.py": (ROLE_WAIT_HOME,),
    "serve/queue.py": (ROLE_SERVE, ROLE_DETERMINISTIC),
    "serve/loop.py": (ROLE_SERVE, ROLE_INSTRUMENTED),
    "serve/session.py": (ROLE_SERVE, ROLE_INSTRUMENTED),
    # Fleet coordinator/worker: serve-plane waits (through the clock
    # seam) + bus instrumentation.  Its membership/lease bookkeeping is
    # the DETERMINISTIC resilience/membership.py below — tick-counted
    # decisions, no clock reads.
    "serve/fleet.py": (ROLE_SERVE, ROLE_INSTRUMENTED),
    "resilience/membership.py": (ROLE_DETERMINISTIC, ROLE_INSTRUMENTED),
    # The admission controller's pricing and shed machine are clock-free
    # (waits are handed IN by the loop); the breaker's windows/cooldowns
    # are tick-counted, never wall-clock — both stay under SEQ005.
    "serve/slo.py": (ROLE_SERVE, ROLE_DETERMINISTIC),
    "resilience/breaker.py": (ROLE_DETERMINISTIC, ROLE_INSTRUMENTED),
    # The trace recorder and flight recorder are written to from reader
    # threads, the main loop, AND the watchdog monitor (watchdog.expiry
    # is published off-thread), so they carry the serve-plane lock
    # discipline (SEQ008) even though they live under obs/.
    "obs/trace.py": (ROLE_SERVE,),
    "obs/flightrec.py": (ROLE_SERVE,),
    # The donation-safety dataflow pass: pure host-side AST walking
    # (explicit row because its plan is what SEQ011's annotations are
    # cross-checked against — the pass and the rule land together).
    "analysis/dataflow.py": (ROLE_HOST,),
    # The collective-safety pass: host-side jaxpr walking over the
    # sharded entry points (explicit row because its inventory is what
    # SEQ012's routing rule protects — the pass and the rule land
    # together; it WALKS collectives, it never issues one).
    "analysis/collectives.py": (ROLE_HOST,),
    # The value-range certifier: host-side abstract interpretation over
    # the scoring jaxprs (explicit row because its derived_constants
    # rows are what SEQ013's `# cert:` markers must name — the pass and
    # the rule land together; it PROVES bounds, it never gates on one).
    "analysis/ranges.py": (ROLE_HOST,),
    # The exception-flow certifier: host-side AST walking over the
    # raise/except/finally propagation graph (explicit row because its
    # swallow-unmarked finding is what SEQ014's `# advisory:` markers
    # answer — the pass and the rule land together; it CLASSIFIES
    # handlers, it never swallows in one).
    "analysis/exitflow.py": (ROLE_HOST,),
    # The load plane's one wall-clock module: schedule pacing and
    # socket reads are measurements against a prebuilt open-loop
    # schedule, not decisions, so SEQ005 does not apply to it — while
    # the rest of load/ (arrival/workload/replay/gates/report/refit)
    # is schedule ARITHMETIC and stays deterministic below.
    "load/driver.py": (ROLE_HOST,),
    # -- directory defaults ------------------------------------------------
    # The AOT warm plane is host-side orchestration whose diagnostics
    # ride the event bus; its timers (compile walls) are measurements,
    # not decisions, so SEQ005 does not apply.
    "aot/": (ROLE_INSTRUMENTED,),
    "ops/": (ROLE_TRACED,),
    "parallel/": (ROLE_TRACED, ROLE_COLLECTIVE_HOME),
    "resilience/": (ROLE_DETERMINISTIC, ROLE_INSTRUMENTED),
    "serve/": (ROLE_SERVE,),
    "analysis/": (ROLE_HOST,),
    "io/": (ROLE_HOST,),
    # Open-loop load generation: seeded-RNG schedules, never wall-clock
    # in decision paths — SEQ005 enforces the package docstring's
    # determinism claim (driver.py excepted above).
    "load/": (ROLE_DETERMINISTIC,),
    "models/": (ROLE_HOST,),
    "obs/": (ROLE_HOST,),
    "utils/": (ROLE_HOST,),
    # -- top-level modules -------------------------------------------------
    "__init__.py": (ROLE_HOST,),
    "__main__.py": (ROLE_HOST,),
    "native_bridge.py": (ROLE_HOST,),
}


def module_roles(rel: str | Path) -> tuple[str, ...] | None:
    """Roles for a lint-relative module path (``<pkg>/<inner...>.py``).

    The leading path component is the package directory name (whatever
    it is — the tests lint under ``pkg/``); classification keys on the
    inner path.  Returns ``None`` for an unclassified module (a SEQ009
    finding, not a crash: the linter must keep linting the rest)."""
    parts = Path(rel).parts
    inner = "/".join(parts[1:]) if len(parts) > 1 else parts[0]
    exact = _MODULE_CLASSES.get(inner)
    if exact is not None:
        return exact
    if "/" in inner:
        return _MODULE_CLASSES.get(inner.split("/", 1)[0] + "/")
    return None


#: The serving plane's single legal home for blocking waits (SEQ007)
#: and the single legal home for environment reads (SEQ002) — kept as
#: names because the rule MESSAGES cite them.
_SERVE_CLOCK_HOME = "serve/clock.py"
_ENV_HOME = "utils/platform.py"

#: Guard types whose ``self.X = threading.<T>()`` assignment marks a
#: serve-plane class as lock-guarded (SEQ008).
_GUARD_TYPES = ("Condition", "Lock", "RLock")

#: In-place mutator methods: a call ``self.attr.<m>(...)`` mutates the
#: shared container exactly like an assignment does (SEQ008).
_MUTATOR_METHODS = {
    "append", "extend", "insert", "pop", "popleft", "appendleft",
    "remove", "clear", "add", "discard", "update", "setdefault",
    "popitem", "sort", "reverse",
}

_WALLCLOCK_ATTRS = {
    ("time", "time"),
    ("time", "monotonic"),
    ("time", "perf_counter"),
    ("time", "process_time"),
    ("time", "time_ns"),
    ("time", "monotonic_ns"),
    ("datetime", "now"),
    ("datetime", "utcnow"),
    ("date", "today"),
}

#: SEQ010's blocking-operation tables — the lexical mirror of the
#: reachability sets in ``analysis/lockgraph.py`` (keep in sync).
#: ``.write``/``.flush`` on a locked stream are deliberately absent:
#: they are bounded by SO_SNDTIMEO and serialising them is the lock's
#: purpose (Responder.send).
_SEQ010_SOCKET_ATTRS = ("accept", "recv", "recvfrom", "connect", "listen")
_SEQ010_SOCKETISH_SEND = ("send", "sendall")
_SEQ010_BOARD_ATTRS = ("post", "claim", "delete")
_SEQ010_OS_ATTRS = (
    "replace", "fsync", "link", "unlink", "makedirs", "rename",
    "remove", "rmdir", "listdir", "walk",
)

#: SEQ012's collective set — keep in sync with
#: ``analysis.collectives.COLLECTIVE_PRIMS`` (the jaxpr-level mirror).
_COLLECTIVE_NAMES = {
    "psum", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter",
}

_SUPPRESS_RE = re.compile(r"#\s*seqlint:\s*disable=([A-Z0-9, ]+)")
_SUPPRESS_FILE_RE = re.compile(r"#\s*seqlint:\s*disable-file=([A-Z0-9, ]+)")

#: SEQ011's explicit opt-out: the marker must carry a non-empty reason
#: (a bare ``# nodonate:`` documents nothing and stays a finding).
_NODONATE_RE = re.compile(r"#\s*nodonate:\s*(\S.*)?$")

#: SEQ013's proof marker: must name a RangeCert ``derived_constants``
#: row (a bare ``# cert:`` proves nothing and stays a finding).
_CERT_RE = re.compile(r"#\s*cert:\s*(\S+)?")

#: SEQ014's swallow marker: must carry a non-empty reason (a bare
#: ``# advisory:`` documents nothing and stays a finding).  Keep in
#: sync with ``analysis.exitflow._ADVISORY_RE`` — the propagation-graph
#: certifier reads the SAME markers when classifying handler sinks.
_ADVISORY_RE = re.compile(r"#\s*advisory:\s*(\S.*)?$")

#: SEQ013's certified numeric-bound set — every hand overflow constant
#: the value-range certifier re-derives (analysis/ranges.py
#: derive_constants; keep in sync).  ``2**N`` / ``1 << N`` spellings of
#: these values match too.
_CERT_LITERALS = {
    4095,  # static-weight-ceiling (max_exact_value at the padded cap)
    4096,  # argmax-pack-radix (2^12)
    32767,  # operand-cap (HIGHEST matmul 16-mantissa-bit operand)
    65535,  # operand-cap's 2^16 - 1 numerator
    524288,  # rowpack-epilogue-limit (2^19)
    16777216,  # f32-exact-window (2^24)
    2147483647,  # argmax-pack-bound / int32-packed-sentinel (2^31 - 1)
    2147483648,  # 2^31 itself (the 2**31 - 1 spelling's inner literal)
}


@dataclasses.dataclass(frozen=True)
class LintFinding:
    code: str
    path: str
    line: int
    message: str

    def describe(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


def _suppressions(source: str):
    """Per-line and file-level rule suppressions from comments."""
    per_line: dict[int, set[str]] = {}
    file_level: set[str] = set()
    for i, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if m:
            per_line[i] = {c.strip() for c in m.group(1).split(",")}
        if i <= 10:
            m = _SUPPRESS_FILE_RE.search(text)
            if m:
                file_level |= {c.strip() for c in m.group(1).split(",")}
    return per_line, file_level


class _Scope:
    """One function scope: whether it is a traced scoring path, and
    which local names hold traced intermediates (assigned from jnp/lax/
    pl/pltpu calls or from the function's array-like parameters)."""

    def __init__(self, name: str, traced: bool):
        self.name = name
        self.traced = traced
        self.traced_names: set[str] = set()


_TRACED_MODULES = {"jnp", "lax", "pl", "pltpu", "jax", "checkify"}


def _is_traced_expr(node: ast.AST, scope: _Scope) -> bool:
    """Conservatively: does this expression involve a traced value —
    a jnp/lax/... call, or a name previously assigned from one?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in scope.traced_names:
            return True
        if isinstance(sub, ast.Call):
            root = sub.func
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and root.id in _TRACED_MODULES:
                return True
    return False


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.findings: list[LintFinding] = []
        self.per_line, self.file_level = _suppressions(source)
        # SEQ011 reads the source text of multi-line jit assignments
        # for the `# nodonate:` marker — AST nodes drop comments.
        self._lines = source.splitlines()
        self.scopes: list[_Scope] = []
        # Every rule's scope derives from the one classification
        # registry — path predicates may not be re-derived ad hoc here
        # (that is exactly the drift SEQ009 exists to prevent).
        roles = module_roles(rel)
        self.unclassified = roles is None
        roles = roles or ()
        self.in_traced_dir = ROLE_TRACED in roles
        self.is_env_home = ROLE_ENV_HOME in roles
        self.in_deterministic = ROLE_DETERMINISTIC in roles
        self.in_instrumented = ROLE_INSTRUMENTED in roles
        self.in_serve = ROLE_SERVE in roles
        self.in_collective_home = ROLE_COLLECTIVE_HOME in roles
        # SEQ010 lexical state: the guard attrs of each enclosing class,
        # the local guard names of each enclosing function, and the
        # stack of guards currently held by enclosing `with` bodies.
        self._class_guard_stack: list[set[str]] = []
        self._local_guard_stack: list[set[str]] = []
        self._held_guards: list[tuple[str, str]] = []

    # -- bookkeeping -------------------------------------------------------

    def _emit(self, code: str, node: ast.AST, message: str):
        line = getattr(node, "lineno", 0)
        if code in self.file_level or code in self.per_line.get(line, ()):
            return
        self.findings.append(LintFinding(code, self.rel, line, message))

    def _enter_function(self, node):
        traced = self.in_traced_dir and bool(
            _TRACED_NAME_RE.match(node.name)
        )
        self.scopes.append(_Scope(node.name, traced))
        # SEQ010: a nested def inside a `with lock:` body runs LATER,
        # not under the lock — lexical held state does not cross a
        # function boundary.
        held, self._held_guards = self._held_guards, []
        self._local_guard_stack.append(self._local_guards(node))
        self.generic_visit(node)
        self._local_guard_stack.pop()
        self._held_guards = held
        self.scopes.pop()

    @staticmethod
    def _is_guard_ctor(value: ast.AST) -> bool:
        if not isinstance(value, ast.Call):
            return False
        func = value.func
        return (
            isinstance(func, ast.Attribute)
            and func.attr in _GUARD_TYPES
            and isinstance(func.value, ast.Name)
            and func.value.id == "threading"
        ) or (isinstance(func, ast.Name) and func.id in _GUARD_TYPES)

    @classmethod
    def _local_guards(cls, node) -> set[str]:
        """Plain local names assigned ``threading.Lock()/Condition()/
        RLock()`` anywhere in this function (SEQ010)."""
        out: set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and cls._is_guard_ctor(sub.value):
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
        return out

    visit_FunctionDef = _enter_function
    visit_AsyncFunctionDef = _enter_function

    @property
    def scope(self) -> _Scope | None:
        for s in reversed(self.scopes):
            if s.traced:
                return s
        return None

    # -- SEQ009: unclassified module ---------------------------------------

    def visit_Module(self, node: ast.Module):
        if self.unclassified:
            self._emit(
                "SEQ009",
                node,
                "module is not classified in the seqlint _MODULE_CLASSES "
                "registry; add it (traced / deterministic / instrumented "
                "/ serve-plane / host) so the rule scopes cover it",
            )
        for stmt in node.body:
            self._check_jit_donation(stmt)
        if self.in_traced_dir:
            self._scan_cert_literals(node, None)
        self.generic_visit(node)

    # -- SEQ013: numeric-bound literals name their cert row ----------------

    @staticmethod
    def _cert_literal_value(node: ast.AST) -> int | None:
        """The certified-bound value this expression spells, else None:
        a plain int literal, ``B ** N`` or ``B << N`` of literals."""
        if isinstance(node, ast.Constant):
            v = node.value
            if isinstance(v, int) and not isinstance(v, bool):
                return v if v in _CERT_LITERALS else None
            return None
        if (
            isinstance(node, ast.BinOp)
            and isinstance(node.op, (ast.Pow, ast.LShift))
            and isinstance(node.left, ast.Constant)
            and isinstance(node.right, ast.Constant)
            and isinstance(node.left.value, int)
            and isinstance(node.right.value, int)
            and 0 <= node.right.value <= 64
        ):
            v = (
                node.left.value**node.right.value
                if isinstance(node.op, ast.Pow)
                else node.left.value << node.right.value
            )
            return v if v in _CERT_LITERALS else None
        return None

    def _scan_cert_literals(self, node: ast.AST, stmt: ast.stmt | None):
        """Walk the tree tracking the smallest enclosing statement; any
        certified-bound literal must find a named ``# cert:`` marker on
        one of that statement's source lines (SEQ013)."""
        if isinstance(node, ast.stmt):
            stmt = node
        val = None if stmt is None else self._cert_literal_value(node)
        if val is not None:
            self._check_cert_marker(stmt, node, val)
            return  # the spelled value is claimed; 2/31 inside 2**31
            # are not independent bounds, and the statement's marker
            # check already ran once for this literal
        for child in ast.iter_child_nodes(node):
            self._scan_cert_literals(child, stmt)

    def _check_cert_marker(self, stmt: ast.stmt, node: ast.AST, val: int):
        end = getattr(stmt, "end_lineno", stmt.lineno)
        for text in self._lines[stmt.lineno - 1 : end]:
            m = _CERT_RE.search(text)
            if m is None:
                continue
            if m.group(1):
                return  # named marker: the bound cites its proof row
            self._emit(
                "SEQ013",
                node,
                f"bare `# cert:` marker on numeric bound {val} names no "
                "RangeCert row — cite the derived_constants row that "
                "proves it (make ranges-audit; see ops/bounds.py)",
            )
            return
        self._emit(
            "SEQ013",
            node,
            f"numeric overflow bound {val} in traced gate/kernel code "
            "carries no `# cert: <row>` marker; wire it through "
            "ops/bounds.py or name the RangeCert derived_constants row "
            "that proves it (analysis/ranges.py, make ranges-audit)",
        )

    # -- SEQ014: broad handlers prove they are not silent swallows ---------

    @staticmethod
    def _seq014_broad(node: ast.ExceptHandler) -> bool:
        """``except:`` / ``except Exception`` — the handler shapes wide
        enough to swallow ANYTHING the body raises."""
        t = node.type
        if t is None:
            return True
        if isinstance(t, ast.Attribute):
            t = ast.Name(id=t.attr)
        return isinstance(t, ast.Name) and t.id in (
            "Exception",
            "BaseException",
        )

    @staticmethod
    def _seq014_own_stmts(node: ast.ExceptHandler):
        """The handler's OWN statements — nested def/lambda bodies run
        later, not in the except arm, so a raise or log_line inside one
        proves nothing about this handler."""
        todo = list(node.body)
        while todo:
            stmt = todo.pop()
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield stmt
            todo.extend(
                child
                for child in ast.iter_child_nodes(stmt)
                if isinstance(child, ast.stmt)
            )

    def visit_ExceptHandler(self, node: ast.ExceptHandler):
        if self.unclassified or not self._seq014_broad(node):
            self.generic_visit(node)
            return
        routed = False
        for stmt in self._seq014_own_stmts(node):
            if isinstance(stmt, ast.Raise):
                self.generic_visit(node)
                return  # re-raise (or typed replacement): not a swallow
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Call):
                    continue
                f = sub.func
                name = (
                    f.id
                    if isinstance(f, ast.Name)
                    else f.attr
                    if isinstance(f, ast.Attribute)
                    else None
                )
                if name == "log_line":
                    routed = True
                # Forwarding the BOUND exception into a call hands the
                # event to a classifier (the retry/quarantine ladders:
                # `_block_failed(block, e)`, `_is_resumable(e)`) — a
                # direct Name argument, not an f-string mention, which
                # merely formats the message.
                if node.name is not None and any(
                    isinstance(a, ast.Name) and a.id == node.name
                    for a in [*sub.args, *(k.value for k in sub.keywords)]
                ):
                    routed = True
        if routed:
            self.generic_visit(node)
            return
        end = node.body[-1].end_lineno or node.lineno
        for text in self._lines[node.lineno - 1 : end]:
            m = _ADVISORY_RE.search(text)
            if m is None:
                continue
            if m.group(1):
                self.generic_visit(node)
                return  # reasoned marker: swallowing IS the contract
            self._emit(
                "SEQ014",
                node,
                "bare `# advisory:` marker on a broad except arm gives "
                "no reason — say WHY swallowing is the contract here "
                "(latency optimisation, best-effort diagnostic, ...) so "
                "the exception-flow certifier can audit the swallow "
                "(analysis/exitflow.py, make exitpath-audit)",
            )
            self.generic_visit(node)
            return
        self._emit(
            "SEQ014",
            node,
            "broad `except Exception` handler neither re-raises, routes "
            "through log_line, nor carries a reasoned `# advisory: "
            "<why>` marker — a silent swallow is exactly the failure "
            "path the exception-flow certifier exists to retire "
            "(analysis/exitflow.py, make exitpath-audit)",
        )
        self.generic_visit(node)

    # -- SEQ011: module-level jit entries declare donation -----------------

    @staticmethod
    def _is_jit_call(value: ast.AST) -> bool:
        """``jax.jit(...)`` or bare ``jit(...)`` — the module-level
        entry-point shape analysis/dataflow.py plans donation for."""
        if not isinstance(value, ast.Call):
            return False
        func = value.func
        if isinstance(func, ast.Name):
            return func.id == "jit"
        return (
            isinstance(func, ast.Attribute)
            and func.attr == "jit"
            and isinstance(func.value, ast.Name)
            and func.value.id == "jax"
        )

    def _check_jit_donation(self, stmt: ast.stmt):
        if not (
            isinstance(stmt, ast.Assign)
            and self._is_jit_call(stmt.value)
        ):
            return
        if any(
            kw.arg == "donate_argnums" for kw in stmt.value.keywords
        ):
            return
        end = getattr(stmt, "end_lineno", stmt.lineno)
        for text in self._lines[stmt.lineno - 1 : end]:
            m = _NODONATE_RE.search(text)
            if m is None:
                continue
            if m.group(1):
                return  # marker with a reason: explicit opt-out
            self._emit(
                "SEQ011",
                stmt,
                "bare `# nodonate:` marker with no reason — say WHY "
                "this jit entry cannot donate (aliasing hazard, "
                "scalar-only operands, ...) so the opt-out is auditable",
            )
            return
        self._emit(
            "SEQ011",
            stmt,
            "module-level jax.jit assignment declares no donation "
            "policy: wire donate_argnums=... from the DonationPlan "
            "(analysis/dataflow.py) or mark the assignment "
            "`# nodonate: <reason>`",
        )

    # -- SEQ008: serve-plane shared state under its lock -------------------

    def visit_ClassDef(self, node: ast.ClassDef):
        guards = self._class_guards(node) if self.in_serve else set()
        if guards:
            for stmt in node.body:
                if (
                    isinstance(
                        stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
                    )
                    and stmt.name != "__init__"
                ):
                    for child in stmt.body:
                        self._scan_guarded(
                            child, node.name, guards, held=False
                        )
        self._class_guard_stack.append(guards)
        self.generic_visit(node)
        self._class_guard_stack.pop()

    @classmethod
    def _class_guards(cls, node: ast.ClassDef) -> set[str]:
        """Attribute names assigned ``threading.Condition()/Lock()/
        RLock()`` (or a bare imported ``Lock()`` etc.) anywhere in the
        class: the class's owning guards."""
        guards: set[str] = set()
        for sub in ast.walk(node):
            if not (
                isinstance(sub, ast.Assign) and cls._is_guard_ctor(sub.value)
            ):
                continue
            for tgt in sub.targets:
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    guards.add(tgt.attr)
        return guards

    @staticmethod
    def _self_attr_root(node: ast.AST) -> str | None:
        """The ``X`` of a ``self.X`` / ``self.X[...]`` chain, else None."""
        while isinstance(node, ast.Subscript):
            node = node.value
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return node.attr
        return None

    def _is_guard_enter(self, expr: ast.AST, guards: set[str]) -> bool:
        """``with self.<guard>:`` — the context expression IS a guard
        attribute (Condition/Lock are their own context managers)."""
        return self._self_attr_root(expr) in guards

    def _scan_guarded(self, node, cls: str, guards: set[str], held: bool):
        """Walk one guarded class's method body tracking whether a
        ``with self.<guard>:`` is lexically held, flagging every
        ``self.*`` mutation reached without it (SEQ008)."""
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held or any(
                self._is_guard_enter(item.context_expr, guards)
                for item in node.items
            )
            for child in node.body:
                self._scan_guarded(child, cls, guards, inner)
            return
        if not held:
            mutated = None
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    elts = tgt.elts if isinstance(tgt, (ast.Tuple, ast.List)) else [tgt]
                    for e in elts:
                        e = e.value if isinstance(e, ast.Starred) else e
                        mutated = mutated or self._self_attr_root(e)
            elif isinstance(node, ast.AugAssign):
                mutated = self._self_attr_root(node.target)
            elif isinstance(node, ast.Call):
                func = node.func
                if (
                    isinstance(func, ast.Attribute)
                    and func.attr in _MUTATOR_METHODS
                ):
                    mutated = self._self_attr_root(func.value)
            if mutated is not None:
                self._emit(
                    "SEQ008",
                    node,
                    f"`self.{mutated}` of guarded serve-plane class "
                    f"`{cls}` is mutated outside `with self.<guard>:`; "
                    "reader threads may only json.loads and enqueue — "
                    "every shared-state mutation crosses the owning "
                    "Condition/Lock",
                )
        for child in ast.iter_child_nodes(node):
            self._scan_guarded(child, cls, guards, held)

    # -- SEQ010: blocking ops lexically under a serve lock -----------------

    def _guard_token(self, expr: ast.AST) -> tuple[str, str] | None:
        """``self.X`` where X is an enclosing class's guard, or a local
        name assigned a guard constructor — the lock a ``with`` on this
        expression holds."""
        attr = self._self_attr_root(expr)
        if (
            attr is not None
            and self._class_guard_stack
            and attr in self._class_guard_stack[-1]
        ):
            return ("self", attr)
        if (
            isinstance(expr, ast.Name)
            and self._local_guard_stack
            and expr.id in self._local_guard_stack[-1]
        ):
            return ("local", expr.id)
        return None

    def _enter_with(self, node):
        pushed = 0
        if self.in_serve:
            for item in node.items:
                token = self._guard_token(item.context_expr)
                if token is not None:
                    self._held_guards.append(token)
                    pushed += 1
        self.generic_visit(node)
        del self._held_guards[len(self._held_guards) - pushed:]

    visit_With = _enter_with
    visit_AsyncWith = _enter_with

    @staticmethod
    def _receiver_name(func: ast.Attribute) -> str:
        """The receiver's last name segment, lowercased: ``x`` for
        ``x.post``, ``_board`` for ``self._board.post``."""
        base = func.value
        if isinstance(base, ast.Attribute):
            return base.attr.lower()
        if isinstance(base, ast.Name):
            return base.id.lower()
        return ""

    def _seq010_blocking(self, node: ast.Call) -> str | None:
        """Classify one call as a blocking op for SEQ010 (None = not
        blocking).  ``block_until`` is handled separately — it is legal
        on the held lock itself."""
        func = node.func
        if isinstance(func, ast.Name):
            if func.id == "open":
                return "file I/O (open)"
            if func.id == "board_read_json":
                return "board file I/O (board_read_json)"
            if func.id == "Popen":
                return "subprocess (Popen)"
            return None
        if not isinstance(func, ast.Attribute):
            return None
        attr = func.attr
        recv = self._receiver_name(func)
        if attr in _SEQ010_SOCKET_ATTRS:
            return f"socket .{attr}()"
        if attr in _SEQ010_SOCKETISH_SEND and (
            "sock" in recv or "conn" in recv
        ):
            return f"socket .{attr}()"
        if attr in _SEQ010_BOARD_ATTRS and "board" in recv:
            return f"board file I/O (.{attr}())"
        if recv == "os" and attr in _SEQ010_OS_ATTRS:
            return f"file I/O (os.{attr})"
        if recv == "subprocess" or attr == "Popen":
            return f"subprocess ({attr})"
        if recv == "shutil":
            return f"file I/O (shutil.{attr})"
        return None

    def _check_seq010(self, node: ast.Call) -> None:
        if not (self.in_serve and self._held_guards):
            return
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr == "block_until":
            # Waiting ON the held lock releases it while waiting
            # (Condition.wait_for) — that is the pop_ready/_pause
            # pattern.  Waiting on anything else keeps the held lock
            # pinned through the whole wait.
            if node.args and self._guard_token(node.args[0]) == (
                self._held_guards[-1]
            ):
                return
            self._emit(
                "SEQ010",
                node,
                "block_until on a condition other than the held lock "
                "keeps that lock pinned through the wait; wait on the "
                "owning Condition itself, or move the wait outside the "
                "`with` body",
            )
            return
        detail = self._seq010_blocking(node)
        if detail is not None:
            held = ".".join(self._held_guards[-1])
            self._emit(
                "SEQ010",
                node,
                f"{detail} lexically inside `with {held}:` stalls every "
                "thread contending that lock behind the operation; "
                "compute the verdict under the lock, do the blocking "
                "work after releasing it (see RequestQueue.submit and "
                "analysis/lockgraph.py rule b)",
            )

    # -- SEQ004: bare assert ----------------------------------------------

    def visit_Assert(self, node: ast.Assert):
        self._emit(
            "SEQ004",
            node,
            "bare assert in a runtime path vanishes under python -O; "
            "raise RuntimeError with an actionable message",
        )
        self.generic_visit(node)

    # -- SEQ003 state: track traced intermediates --------------------------

    def visit_Assign(self, node: ast.Assign):
        scope = self.scope
        if scope is not None and _is_traced_expr(node.value, scope):
            for tgt in node.targets:
                for sub in ast.walk(tgt):
                    if isinstance(sub, ast.Name):
                        scope.traced_names.add(sub.id)
        self.generic_visit(node)

    # -- SEQ003: Python branch on traced values ----------------------------

    def _check_branch(self, node):
        scope = self.scope
        if scope is not None and _is_traced_expr(node.test, scope):
            self._emit(
                "SEQ003",
                node,
                f"Python branch on a traced value in `{scope.name}`: "
                "tracing cannot follow host control flow — use lax.cond "
                "/ lax.select / jnp.where",
            )
        self.generic_visit(node)

    visit_If = _check_branch
    visit_While = _check_branch

    # -- SEQ001 / SEQ002 / SEQ005: calls -----------------------------------

    def visit_Call(self, node: ast.Call):
        func = node.func
        scope = self.scope

        # SEQ001: host-sync inside traced scoring paths.
        if scope is not None:
            if isinstance(func, ast.Attribute) and func.attr == "item":
                self._emit(
                    "SEQ001",
                    node,
                    f".item() in traced path `{scope.name}` forces a "
                    "device->host sync per call; keep the value on device",
                )
            if (
                isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "np"
                and func.attr in ("asarray", "array")
            ):
                self._emit(
                    "SEQ001",
                    node,
                    f"np.{func.attr}() in traced path `{scope.name}` "
                    "materialises the operand on host; use jnp",
                )
            if (
                isinstance(func, ast.Name)
                and func.id in ("float", "int")
                and node.args
                and not isinstance(node.args[0], ast.Constant)
                and _is_traced_expr(node.args[0], scope)
            ):
                self._emit(
                    "SEQ001",
                    node,
                    f"{func.id}() on a traced value in `{scope.name}` "
                    "forces a host sync; use .astype()/jnp casts",
                )

        # SEQ002: env reads outside the registry.
        if not self.is_env_home:
            is_environ = (
                isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Attribute)
                and isinstance(func.value.value, ast.Name)
                and func.value.value.id == "os"
                and func.value.attr == "environ"
            )  # os.environ.get(...)
            is_getenv = (
                isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "os"
                and func.attr == "getenv"
            ) or (isinstance(func, ast.Name) and func.id == "getenv")
            if is_environ or is_getenv:
                self._emit(
                    "SEQ002",
                    node,
                    "environment read outside utils/platform.py; add the "
                    "variable to the env registry (utils.platform) and "
                    "use its typed accessor",
                )

        # SEQ012: raw lax collectives outside parallel/, implicit axes.
        coll_name = None
        if isinstance(func, ast.Attribute) and func.attr in _COLLECTIVE_NAMES:
            base = func.value
            if (isinstance(base, ast.Name) and base.id == "lax") or (
                isinstance(base, ast.Attribute)
                and base.attr == "lax"
                and isinstance(base.value, ast.Name)
                and base.value.id == "jax"
            ):
                coll_name = func.attr
        elif isinstance(func, ast.Name) and func.id in _COLLECTIVE_NAMES:
            coll_name = func.id
        if coll_name is not None:
            if not self.in_collective_home:
                self._emit(
                    "SEQ012",
                    node,
                    f"raw jax.lax collective `{coll_name}` outside the "
                    "parallel/ layer; route through the parallel/ "
                    "wrappers (ring/sharding strategies) so the "
                    "collective-safety audit (analysis/collectives.py) "
                    "inventories every byte crossing the mesh",
                )
            elif not any(kw.arg == "axis_name" for kw in node.keywords):
                self._emit(
                    "SEQ012",
                    node,
                    f"collective `{coll_name}` without an explicit "
                    "axis_name= keyword; a positional/implicit axis "
                    "evades the audit's axis-resolution check — name "
                    "the mesh axis (axis_name=SEQ_AXIS / BATCH_AXIS)",
                )

        # SEQ005: wall-clock in deterministic paths.
        if self.in_deterministic and isinstance(func, ast.Attribute):
            base = func.value
            if (
                isinstance(base, ast.Name)
                and (base.id, func.attr) in _WALLCLOCK_ATTRS
            ) or (
                isinstance(base, ast.Attribute)
                and (base.attr, func.attr) in _WALLCLOCK_ATTRS
            ):
                self._emit(
                    "SEQ005",
                    node,
                    "wall-clock read in a deterministic resilience/"
                    "journal path; decisions must replay identically — "
                    "derive from the seeded policy state instead",
                )

        # SEQ006: direct stderr prints in instrumented modules.
        if (
            self.in_instrumented
            and isinstance(func, ast.Name)
            and func.id == "print"
        ):
            for kw in node.keywords:
                v = kw.value
                if (
                    kw.arg == "file"
                    and isinstance(v, ast.Attribute)
                    and v.attr == "stderr"
                ):
                    self._emit(
                        "SEQ006",
                        node,
                        "direct stderr print in an instrumented module "
                        "bypasses the observability plane; emit through "
                        "obs.events.log_line (same bytes on stderr, plus "
                        "a `log` event when the bus is armed)",
                    )

        # SEQ007: bare blocking waits in the serving plane.
        if self.in_serve:
            is_sleep = (
                isinstance(func, ast.Attribute)
                and func.attr == "sleep"
                and isinstance(func.value, ast.Name)
                and func.value.id == "time"
            ) or (isinstance(func, ast.Name) and func.id == "sleep")
            is_wait = isinstance(func, ast.Attribute) and func.attr in (
                "wait",
                "wait_for",
            )
            if is_sleep or is_wait:
                self._emit(
                    "SEQ007",
                    node,
                    "bare blocking wait in the serving plane; route the "
                    "wait through the injectable ServeClock.block_until "
                    "(serve/clock.py) so tests drive a fake clock and "
                    "drain signals stay bounded",
                )

        # SEQ015: work-unit board posts must carry trace context.  The
        # payload shape IS the signature: a serialized dict literal with
        # both "bid" and "rows" is a superblock crossing the board (the
        # fleet offer/result protocol) and must propagate "traces" too.
        if self.in_serve:
            is_dumps = (
                isinstance(func, ast.Attribute)
                and func.attr == "dumps"
                and isinstance(func.value, ast.Name)
                and func.value.id == "json"
            ) or (isinstance(func, ast.Name) and func.id == "dumps")
            if is_dumps and node.args and isinstance(node.args[0], ast.Dict):
                keys = {
                    k.value
                    for k in node.args[0].keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)
                }
                if {"bid", "rows"} <= keys and "traces" not in keys:
                    self._emit(
                        "SEQ015",
                        node,
                        "work-unit board payload (bid + rows) without a "
                        "`traces` key; propagate the admission-minted "
                        "trace ids over the board so the fleet timeline "
                        "links remote launches back to their requests",
                    )

        # SEQ010: blocking ops lexically under a held serve lock.
        self._check_seq010(node)
        self.generic_visit(node)

    # -- SEQ002: os.environ subscripts / membership ------------------------

    def visit_Subscript(self, node: ast.Subscript):
        if not self.is_env_home:
            v = node.value
            if (
                isinstance(v, ast.Attribute)
                and isinstance(v.value, ast.Name)
                and v.value.id == "os"
                and v.attr == "environ"
            ):
                self._emit(
                    "SEQ002",
                    node,
                    "environment read outside utils/platform.py; add the "
                    "variable to the env registry (utils.platform) and "
                    "use its typed accessor",
                )
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare):
        # `"X" in os.environ` membership probes count as reads too.
        if not self.is_env_home:
            for cmp_node, op in zip(node.comparators, node.ops):
                if (
                    isinstance(op, (ast.In, ast.NotIn))
                    and isinstance(cmp_node, ast.Attribute)
                    and isinstance(cmp_node.value, ast.Name)
                    and cmp_node.value.id == "os"
                    and cmp_node.attr == "environ"
                ):
                    self._emit(
                        "SEQ002",
                        node,
                        "os.environ membership probe outside "
                        "utils/platform.py; use the env registry's typed "
                        "accessor (utils.platform)",
                    )
        self.generic_visit(node)


def lint_file(path: str | Path, package_root: str | Path) -> list[LintFinding]:
    path = Path(path)
    rel = str(path.relative_to(Path(package_root).parent))
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as exc:
        return [
            LintFinding("SEQ000", rel, exc.lineno or 0, f"syntax error: {exc}")
        ]
    linter = _Linter(str(path), rel, source)
    linter.visit(tree)
    return sorted(linter.findings, key=lambda f: (f.path, f.line, f.code))


def lint_package(package_root: str | Path | None = None) -> list[LintFinding]:
    """Lint every module of the installed package tree.  scripts/ and
    tests/ are host-side tooling, outside the runtime rules' scope."""
    if package_root is None:
        package_root = Path(__file__).resolve().parent.parent
    package_root = Path(package_root)
    findings: list[LintFinding] = []
    for path in sorted(package_root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        findings.extend(lint_file(path, package_root))
    return findings


def run_or_raise(package_root: str | Path | None = None) -> int:
    """Driver entry: lint the package, raise :class:`LintError` listing
    every finding, return the number of files checked when clean."""
    if package_root is None:
        package_root = Path(__file__).resolve().parent.parent
    findings = lint_package(package_root)
    if findings:
        rows = "\n  ".join(f.describe() for f in findings)
        raise LintError(
            f"seqlint: {len(findings)} violation(s):\n  {rows}\n"
            "Fix the violation or suppress a justified case with "
            "`# seqlint: disable=<code>` (see ARCHITECTURE.md §9)."
        )
    return sum(
        1
        for p in Path(package_root).rglob("*.py")
        if "__pycache__" not in p.parts
    )
