"""Whole-program lock-graph audit (``lockgraph``).

seqlint's SEQ008/SEQ010 are *lexical*: they see one function at a time.
This pass is the interprocedural complement — the third pillar of the
analysis plane (ARCHITECTURE §9).  It walks every module's AST, builds
the intra-package call graph, extracts every lock acquisition site
(``with self.<guard>:`` on a ``threading.Condition``/``Lock``/``RLock``
attribute, ``with <local guard>:``, and explicit ``.acquire()`` /
``.release()`` calls), and audits three properties:

(a) **lock-order cycles** — the acquired-while-held relation over all
    locks must be acyclic; a cycle is a potential deadlock between the
    serve loop, reader threads, and the watchdog monitor.
(b) **no blocking operation while a serve-plane/obs lock is held** —
    socket accept/recv/connect, board I/O (``post``/``claim``/
    ``get``/``keys``/``delete`` on a board, ``board_read_json``), file
    I/O (``open``, ``os.replace``/``fsync``/``link``/...), subprocess
    spawns, ``time.sleep``, and ``ServeClock.block_until`` are all
    unbounded (or bounded only by an external timeout) — reachable
    through ANY call chain from inside a held-lock region of a module
    classified serve-plane (or living under ``obs/``) they stall every
    thread contending that lock.  The one legal waiter is
    ``block_until(cond, ...)`` where ``cond`` IS the held lock: that is
    the ``Condition.wait_for`` contract (the lock is *released* while
    waiting), the exact seam SEQ007 routes every serve wait through.
    Bounded stream writes (``.write``/``.flush`` under ``SO_SNDTIMEO``,
    serialising one responder's output) are deliberately NOT in the op
    set: serialising those writes is what the responder lock is *for*.
(c) **no cross-class acquire/release splits** — a lock explicitly
    ``.acquire()``-d in one class and ``.release()``-d in another is a
    protocol smell the ``with`` statement exists to prevent.

The call graph is resolved conservatively: ``self.m()`` to the
enclosing class, ``self.attr.m()`` through ``self.attr = Class(...)``
assignments, bare and module-qualified names through the import table.
The event bus is the one piece of dynamic dispatch the walker must know
about: ``obs.events.publish``/``log_line`` fan out *synchronously* to
every subscriber, so a ``publish()`` under a lock nests every
subscriber's recorder lock beneath it — the walker adds a static edge
from ``publish`` to every ``record_event`` method in the package.

Findings are emitted as a ``kind="concurrency-audit"`` run-report body
(scripts/concurrency_audit.py diffs the stable view against the
committed golden, exactly like ``make schedule-audit``).
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path

from . import LockGraphError
from .seqlint import ROLE_SERVE, _GUARD_TYPES, module_roles

#: Attribute calls that block on a socket regardless of receiver name.
_SOCKET_ATTRS = {"accept", "recv", "recvfrom", "connect", "sendall", "listen"}
#: ``.send`` blocks too, but the name is generic (Responder.send is a
#: host-side method); only flag it on receivers that are plainly sockets.
_SOCKETISH_NAMES = ("sock", "conn")
#: Board verbs: on a FileBoard every one is file I/O (fsync + rename).
_BOARD_ATTRS = {"post", "claim", "delete", "get", "keys"}
_OS_FILE_ATTRS = {
    "replace", "fsync", "link", "unlink", "makedirs", "rename",
    "remove", "rmdir", "listdir", "walk",
}

#: Constructor-parameter wiring the AST cannot see: attributes assigned
#: from an ``__init__`` parameter, typed here by the package's one real
#: composition (serve/loop.py run_serve wires the AdmissionController
#: into the RequestQueue).  Like the bus fan-out below, this encodes the
#: repo's wiring CONTRACT — the queue->controller lock nesting it
#: creates is deliberate and pinned in the committed golden.
_ATTR_TYPE_HINTS: dict[tuple[str, str, str], str] = {
    ("serve/queue.py", "RequestQueue", "_controller"): "AdmissionController",
}


#: Modules whose locks are in scope for rule (b): serve-plane classified
#: modules plus everything under obs/ (the recorders the bus fans into).
def _lock_in_blocking_scope(rel: str) -> bool:
    roles = module_roles("pkg/" + rel) or ()
    return ROLE_SERVE in roles or rel.startswith("obs/")


@dataclasses.dataclass(frozen=True)
class BlockingOp:
    """One lexical blocking operation inside some function."""

    kind: str  # socket / board / file / subprocess / sleep / block_until
    detail: str
    module: str
    func: str  # qualname
    line: int
    waits_on: str | None = None  # lock id block_until waits on, if known
    held: tuple = ()  # lock ids lexically held around the op

    def site(self) -> str:
        return f"{self.module}:{self.line}"


@dataclasses.dataclass
class _FuncInfo:
    """Everything the audit needs about one function/method."""

    module: str
    qualname: str
    # (callee descriptor, held-lock tuple, line)
    calls: list = dataclasses.field(default_factory=list)
    # Lock ids acquired anywhere in this function (with-statements).
    acquires: list = dataclasses.field(default_factory=list)
    # Direct nesting: (outer lock id, inner lock id, line).
    nested: list = dataclasses.field(default_factory=list)
    blocking: list = dataclasses.field(default_factory=list)
    # Explicit .acquire()/.release() calls: (lock id, verb, line).
    explicit: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class _ClassInfo:
    module: str
    name: str
    guards: set = dataclasses.field(default_factory=set)
    # attr name -> class name string it was constructed from.
    attr_types: dict = dataclasses.field(default_factory=dict)
    methods: set = dataclasses.field(default_factory=set)


class _ModuleIndex:
    """Per-module symbol tables: imports, classes, functions."""

    def __init__(self, rel: str):
        self.rel = rel
        # imported symbol name -> (module rel path or None, symbol)
        self.from_imports: dict[str, tuple[str | None, str]] = {}
        # module alias -> module rel path (intra-package only)
        self.mod_imports: dict[str, str] = {}
        self.classes: dict[str, _ClassInfo] = {}
        self.functions: set[str] = set()  # module-level function names


def _resolve_relative(rel: str, level: int, module: str | None) -> str | None:
    """Map a ``from ..obs.events import x`` to an inner module path like
    ``obs/events.py`` (None when it escapes the package)."""
    base = Path(rel).parent.parts
    hops = level - 1
    if hops > len(base):
        return None
    kept = base[: len(base) - hops] if hops else base
    tail = tuple(module.split(".")) if module else ()
    return "/".join(kept + tail) + ".py" if (kept or tail) else None


def _index_module(rel: str, tree: ast.Module) -> _ModuleIndex:
    idx = _ModuleIndex(rel)
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.level > 0:
            target = _resolve_relative(rel, node.level, node.module)
            if target is None:
                continue
            for alias in node.names:
                name = alias.asname or alias.name
                idx.from_imports[name] = (target, alias.name)
                # The imported name may itself be a MODULE of the named
                # package (`from . import clock`): keep the would-be
                # module path so `clock.f()` calls resolve.  Bogus
                # entries for plain symbols are harmless — nothing
                # attribute-calls through a function name.
                idx.mod_imports.setdefault(
                    name, target[:-3] + "/" + alias.name + ".py"
                )
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            info = _ClassInfo(rel, node.name)
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) and isinstance(
                    sub.value, ast.Call
                ):
                    func = sub.value.func
                    ctor = None
                    if isinstance(func, ast.Name):
                        ctor = func.id
                    elif isinstance(func, ast.Attribute):
                        ctor = func.attr
                    is_guard = ctor in _GUARD_TYPES and (
                        isinstance(func, ast.Name)
                        or (
                            isinstance(func, ast.Attribute)
                            and isinstance(func.value, ast.Name)
                            and func.value.id == "threading"
                        )
                    )
                    for tgt in sub.targets:
                        if (
                            isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"
                        ):
                            if is_guard:
                                info.guards.add(tgt.attr)
                            elif ctor is not None and ctor[:1].isupper():
                                info.attr_types[tgt.attr] = ctor
            for stmt in node.body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    info.methods.add(stmt.name)
            idx.classes[node.name] = info
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            idx.functions.add(node.name)
    return idx


def _root_name(node: ast.AST) -> str | None:
    """The leftmost Name of an attribute/subscript chain."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


class _FuncWalker:
    """Walk one function body lexically, tracking the held-lock stack;
    nested defs are collected and walked as their own functions (their
    bodies run later, under whatever locks their caller holds)."""

    def __init__(self, index: _ModuleIndex, cls: _ClassInfo | None,
                 qualname: str, outer_guards: dict[str, str]):
        self.index = index
        self.cls = cls
        self.info = _FuncInfo(index.rel, qualname)
        # local variable name -> lock id (threading guard constructions,
        # including those inherited from the enclosing function).
        self.local_guards = dict(outer_guards)
        self.nested_defs: list = []

    # -- lock identity -----------------------------------------------------

    def _lock_id_of(self, expr: ast.AST) -> str | None:
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and self.cls is not None
            and expr.attr in self.cls.guards
        ):
            return f"{self.index.rel}:{self.cls.name}.{expr.attr}"
        if isinstance(expr, ast.Name) and expr.id in self.local_guards:
            return self.local_guards[expr.id]
        # `self.<attr>.<guard>` — another object's lock, reached through
        # a constructor-typed attribute (rule c's cross-class shape).
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Attribute)
            and isinstance(expr.value.value, ast.Name)
            and expr.value.value.id == "self"
            and self.cls is not None
        ):
            owner = self.cls.attr_types.get(expr.value.attr)
            target = self.index.classes.get(owner) if owner else None
            if target is not None and expr.attr in target.guards:
                return f"{self.index.rel}:{target.name}.{expr.attr}"
        return None

    # -- the walk ----------------------------------------------------------

    def walk(self, body: list, held: tuple = ()) -> None:
        for stmt in body:
            self._stmt(stmt, held)

    def _stmt(self, node: ast.AST, held: tuple) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.nested_defs.append(node)
            return
        if isinstance(node, ast.Lambda):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held
            for item in node.items:
                self._expr(item.context_expr, held)
                lock = self._lock_id_of(item.context_expr)
                if lock is not None:
                    if lock not in self.info.acquires:
                        self.info.acquires.append(lock)
                    for outer in inner:
                        if outer != lock:
                            self.info.nested.append(
                                (outer, lock, node.lineno)
                            )
                    if lock not in inner:
                        inner = inner + (lock,)
            self.walk(node.body, inner)
            return
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            func = node.value.func
            ctor = None
            if isinstance(func, ast.Name):
                ctor = func.id
            elif isinstance(func, ast.Attribute) and isinstance(
                func.value, ast.Name
            ) and func.value.id == "threading":
                ctor = func.attr
            if ctor in _GUARD_TYPES:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.local_guards[tgt.id] = (
                            f"{self.index.rel}:"
                            f"{self.info.qualname}.{tgt.id}"
                        )
        for child in ast.iter_child_nodes(node):
            self._stmt(child, held) if isinstance(
                child, ast.stmt
            ) else self._expr(child, held)

    def _expr(self, node: ast.AST, held: tuple) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.nested_defs.append(node)
            return
        if isinstance(node, ast.Lambda):
            return
        if isinstance(node, ast.Call):
            self._call(node, held)
        for child in ast.iter_child_nodes(node):
            self._expr(child, held)

    # -- calls: resolution descriptors + blocking classification -----------

    def _call(self, node: ast.Call, held: tuple) -> None:
        func = node.func
        line = node.lineno
        desc = None
        if isinstance(func, ast.Name):
            desc = ("name", func.id)
            if func.id == "open":
                self._block("file", "open()", line, held)
            elif func.id == "board_read_json":
                self._block("board", "board_read_json()", line, held)
            elif func.id == "Popen":
                self._block("subprocess", "Popen()", line, held)
            elif func.id == "sleep":
                self._block("sleep", "sleep()", line, held)
        elif isinstance(func, ast.Attribute):
            base = func.value
            attr = func.attr
            root = _root_name(base)
            if isinstance(base, ast.Name) and base.id == "self":
                desc = ("self", attr)
            elif (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
            ):
                desc = ("selfattr", base.attr, attr)
            elif isinstance(base, ast.Name):
                desc = ("mod", base.id, attr)
            # blocking classification is receiver-based, resolution-free:
            if attr == "block_until":
                waits = (
                    self._lock_id_of(node.args[0]) if node.args else None
                )
                self.info.blocking.append(BlockingOp(
                    "block_until", "block_until(...)",
                    self.index.rel, self.info.qualname, line, waits, held,
                ))
            elif attr in _SOCKET_ATTRS:
                self._block("socket", f".{attr}()", line, held)
            elif attr == "send" and root is not None and any(
                s in root.lower() for s in _SOCKETISH_NAMES
            ):
                self._block("socket", f"{root}.send()", line, held)
            elif attr in _BOARD_ATTRS and root is not None and (
                "board" in root.lower()
                or (
                    isinstance(base, ast.Attribute)
                    and "board" in base.attr.lower()
                )
            ):
                self._block("board", f"{root}...{attr}()", line, held)
            elif root == "os" and attr in _OS_FILE_ATTRS:
                self._block("file", f"os.{attr}()", line, held)
            elif root in ("subprocess", "shutil"):
                self._block(
                    "subprocess" if root == "subprocess" else "file",
                    f"{root}.{attr}()", line, held,
                )
            elif root == "time" and attr == "sleep":
                self._block("sleep", "time.sleep()", line, held)
            # explicit acquire/release bookkeeping (rule c):
            if attr in ("acquire", "release"):
                lock = self._lock_id_of(base)
                if lock is not None:
                    self.info.explicit.append((lock, attr, line))
        if desc is not None:
            self.info.calls.append((desc, held, line))
        for arg in node.args:
            self._expr(arg, held)
        for kw in node.keywords:
            self._expr(kw.value, held)

    def _block(self, kind: str, detail: str, line: int, held: tuple) -> None:
        self.info.blocking.append(BlockingOp(
            kind, detail, self.index.rel, self.info.qualname, line,
            None, held,
        ))


def _walk_function(index: _ModuleIndex, cls, qualname: str, node,
                   outer_guards: dict, out: dict) -> None:
    walker = _FuncWalker(index, cls, qualname, outer_guards)
    walker.walk(node.body)
    out[(index.rel, qualname)] = walker.info
    for nested in walker.nested_defs:
        _walk_function(
            index, cls, f"{qualname}.{nested.name}", nested,
            walker.local_guards, out,
        )


# -- package walk ----------------------------------------------------------


def _package_files(package_root: Path):
    for path in sorted(package_root.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        yield path, str(path.relative_to(package_root))


def build_graph(package_root: str | Path | None = None):
    """Parse the package: (func table, module indexes, class table)."""
    if package_root is None:
        package_root = Path(__file__).resolve().parent.parent
    package_root = Path(package_root)
    funcs: dict[tuple[str, str], _FuncInfo] = {}
    indexes: dict[str, _ModuleIndex] = {}
    classes: dict[str, tuple[str, _ClassInfo]] = {}
    for path, rel in _package_files(package_root):
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError:
            continue  # seqlint owns syntax errors
        index = _index_module(rel, tree)
        indexes[rel] = index
        for (mod, cls, attr), tname in _ATTR_TYPE_HINTS.items():
            if mod == rel and cls in index.classes:
                index.classes[cls].attr_types.setdefault(attr, tname)
        for cname, cinfo in index.classes.items():
            # Last definition wins on (unexpected) cross-module clashes;
            # resolution prefers the same module first anyway.
            classes[cname] = (rel, cinfo)
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _walk_function(index, None, node.name, node, {}, funcs)
            elif isinstance(node, ast.ClassDef):
                cinfo = index.classes[node.name]
                for stmt in node.body:
                    if isinstance(
                        stmt, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        _walk_function(
                            index, cinfo,
                            f"{node.name}.{stmt.name}", stmt, {}, funcs,
                        )
    return funcs, indexes, classes


def _resolve_call(desc, module: str, qualname: str, indexes, classes, funcs):
    """Resolve one call descriptor to a func-table key, or None."""
    index = indexes[module]
    kind = desc[0]
    if kind == "self":
        cls = qualname.split(".", 1)[0]
        key = (module, f"{cls}.{desc[1]}")
        return key if key in funcs else None
    if kind == "selfattr":
        cls = qualname.split(".", 1)[0]
        cinfo = index.classes.get(cls)
        if cinfo is None:
            return None
        tname = cinfo.attr_types.get(desc[1])
        if tname is None:
            return None
        target = index.classes.get(tname)
        home = module if target is not None else None
        if target is None and tname in classes:
            home, target = classes[tname]
        if target is None:
            return None
        key = (home, f"{tname}.{desc[2]}")
        return key if key in funcs else None
    if kind == "name":
        name = desc[1]
        if (module, name) in funcs:
            return (module, name)
        imp = index.from_imports.get(name)
        if imp is not None and imp[0] is not None:
            src, sym = imp
            if (src, sym) in funcs:
                return (src, sym)
            if (src, f"{sym}.__init__") in funcs:
                return (src, f"{sym}.__init__")
        if name in index.classes and (
            (module, f"{name}.__init__") in funcs
        ):
            return (module, f"{name}.__init__")
        return None
    if kind == "mod":
        mod = index.mod_imports.get(desc[1])
        if mod is not None and (mod, desc[2]) in funcs:
            return (mod, desc[2])
        return None
    return None


class LockGraph:
    """The resolved audit state: adjacency, lock set, findings."""

    def __init__(self, package_root: str | Path | None = None):
        self.funcs, self.indexes, self.classes = build_graph(package_root)
        # Resolved adjacency: func key -> [(callee key, held, line)].
        self.calls: dict = {}
        for key, info in self.funcs.items():
            resolved = []
            for desc, held, line in info.calls:
                callee = _resolve_call(
                    desc, info.module, info.qualname,
                    self.indexes, self.classes, self.funcs,
                )
                if callee is not None:
                    resolved.append((callee, held, line))
            self.calls[key] = resolved
        # The event bus fan-out: publish/log_line synchronously invoke
        # every subscriber's record_event (obs/events.py) — static edges.
        subscribers = sorted(
            k for k in self.funcs if k[1].endswith(".record_event")
        )
        for bus in (("obs/events.py", "publish"), ("obs/events.py", "log_line")):
            if bus in self.funcs:
                self.calls.setdefault(bus, [])
                for sub in subscribers:
                    self.calls[bus].append((sub, (), 0))
        self._reach_cache: dict = {}

    # -- reachability ------------------------------------------------------

    def _reachable(self, start) -> dict:
        """Func keys reachable from ``start`` (inclusive) -> call path."""
        cached = self._reach_cache.get(start)
        if cached is not None:
            return cached
        paths = {start: (start,)}
        frontier = [start]
        while frontier:
            cur = frontier.pop()
            for callee, _held, _line in self.calls.get(cur, ()):
                if callee not in paths:
                    paths[callee] = paths[cur] + (callee,)
                    frontier.append(callee)
        self._reach_cache[start] = paths
        return paths

    # -- the audit ---------------------------------------------------------

    def audit(self) -> dict:
        locks: set[str] = set()
        for info in self.funcs.values():
            locks.update(info.acquires)
        edges: dict[tuple[str, str], str] = {}
        findings: list[dict] = []

        for key, info in self.funcs.items():
            for outer, inner, line in info.nested:
                edges.setdefault(
                    (outer, inner),
                    f"{info.module}:{info.qualname}:{line}",
                )
            # Transitive: every call made while a lock is held pulls in
            # the callee's whole reachable set.
            for callee, held, line in self.calls.get(key, ()):
                if not held:
                    continue
                paths = self._reachable(callee)
                for target, path in paths.items():
                    tinfo = self.funcs[target]
                    via = " -> ".join(
                        [f"{info.qualname}:{line}"]
                        + [self.funcs[p].qualname for p in path]
                    )
                    for lock in tinfo.acquires:
                        for outer in held:
                            if outer != lock:
                                edges.setdefault((outer, lock), via)
                    for op in tinfo.blocking:
                        for outer in held:
                            self._check_blocking(
                                outer, op, via, findings
                            )
            # Lexical blocking ops under a lock held in this very body.
            for op in info.blocking:
                for outer in op.held:
                    self._check_blocking(
                        outer, op,
                        f"{info.qualname}:{op.line}", findings,
                    )
        findings.extend(self._cycles(edges))
        findings.extend(self._split_acquire_release())

        dedup: dict[tuple, dict] = {}
        for f in findings:
            dedup.setdefault((f["kind"], f["lock"], f["site"]), f)
        ordered = sorted(
            dedup.values(),
            key=lambda f: (f["kind"], f["lock"], f["site"]),
        )
        return {
            "files": len(self.indexes),
            "functions": len(self.funcs),
            "locks": sorted(locks),
            "edges": [
                {"src": a, "dst": b, "via": via}
                for (a, b), via in sorted(edges.items())
            ],
            "findings": ordered,
            "counts": {
                "locks": len(locks),
                "edges": len(edges),
                "findings": len(ordered),
            },
        }

    def _check_blocking(self, outer: str, op: BlockingOp, via: str,
                        findings: list) -> None:
        if not _lock_in_blocking_scope(outer.split(":", 1)[0]):
            return
        if op.kind == "block_until" and op.waits_on == outer:
            return  # the legal Condition.wait_for idiom
        findings.append({
            "kind": "blocking-while-locked",
            "lock": outer,
            "site": f"{op.module}:{op.line}",
            "detail": (
                f"{op.kind} op {op.detail} in {op.func} reachable while "
                f"{outer} is held (via {via})"
            ),
        })

    def _cycles(self, edges: dict) -> list:
        adj: dict[str, list[str]] = {}
        for (a, b) in edges:
            adj.setdefault(a, []).append(b)
        findings = []
        state: dict[str, int] = {}  # 1 = on stack, 2 = done

        def visit(node: str, stack: list[str]):
            state[node] = 1
            stack.append(node)
            for nxt in sorted(adj.get(node, ())):
                if state.get(nxt) == 1:
                    cycle = stack[stack.index(nxt):] + [nxt]
                    findings.append({
                        "kind": "lock-order-cycle",
                        "lock": nxt,
                        "site": " -> ".join(cycle),
                        "detail": (
                            "lock-ordering cycle (potential deadlock): "
                            + " -> ".join(cycle)
                        ),
                    })
                elif state.get(nxt) is None:
                    visit(nxt, stack)
            stack.pop()
            state[node] = 2

        for node in sorted(adj):
            if state.get(node) is None:
                visit(node, [])
        return findings

    def _split_acquire_release(self) -> list:
        acquirers: dict[str, set] = {}
        releasers: dict[str, set] = {}
        sites: dict[str, str] = {}
        for key, info in self.funcs.items():
            owner = info.qualname.split(".", 1)[0]
            for lock, verb, line in info.explicit:
                table = acquirers if verb == "acquire" else releasers
                table.setdefault(lock, set()).add(owner)
                sites.setdefault(lock, f"{info.module}:{line}")
        findings = []
        for lock in sorted(set(acquirers) | set(releasers)):
            a = acquirers.get(lock, set())
            r = releasers.get(lock, set())
            if a and r and a != r:
                findings.append({
                    "kind": "split-acquire-release",
                    "lock": lock,
                    "site": sites[lock],
                    "detail": (
                        f"acquired by {sorted(a)} but released by "
                        f"{sorted(r)}: lock ownership must not cross "
                        "class boundaries — use `with`"
                    ),
                })
        return findings


def audit_lock_graph(package_root: str | Path | None = None) -> dict:
    """The full audit report body (never raises on findings)."""
    return LockGraph(package_root).audit()


def run_or_raise(package_root: str | Path | None = None) -> dict:
    """Driver entry: audit, raise :class:`LockGraphError` on findings,
    return the report body when clean."""
    report = audit_lock_graph(package_root)
    if report["findings"]:
        rows = "\n  ".join(
            f"[{f['kind']}] {f['lock']} at {f['site']}: {f['detail']}"
            for f in report["findings"]
        )
        raise LockGraphError(
            f"lockgraph: {len(report['findings'])} finding(s):\n  {rows}\n"
            "Fix the ordering/blocking site (hoist the call out of the "
            "locked region, or route the wait through the held "
            "condition's block_until)."
        )
    return report
