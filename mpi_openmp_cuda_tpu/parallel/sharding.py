"""Batch sharding: the Scatter/Compute/Gather tier (reference parity: C6+C7).

The reference decomposes the Seq2 batch as ``MPI_Scatter`` of a fixed-stride
buffer to ranks, independent per-rank compute, and ``MPI_Gather`` x3 of the
result arrays, with a special serial "remainder" path on the root rank
(main.c:110-121,174,184-185,195-197).  The TPU design instead:

* pads the batch to a multiple of (devices x chunk) with empty rows — no
  remainder rank, masked rows cost one lane each and are dropped on output;
* places the padded batch with ``NamedSharding(mesh, P('batch'))`` — the
  scatter is a layout annotation, the transfer rides ICI/DCN;
* replicates the read-only state (seq1, value table) with ``P()`` — the
  Bcast / constant-memory tier;
* runs the same chunked scorer body per shard under ``jax.shard_map``;
* fetches the (globally-sharded) output to host — the gather.  No psum:
  results are concatenated per-sequence rows, not reductions.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.dispatch import (
    DEFAULT_CHUNK_BUDGET,
    PaddedBatch,
    choose_chunk_rows,
    pad_batch_rows,
)
from ..resilience.watchdog import guard as _deadline_guard
from .mesh import BATCH_AXIS, batch_sharded, make_mesh, replicated


def _put_global(arr: np.ndarray, sharding):
    """Place a host array (identical on every process) onto a possibly
    multi-host sharding.  make_array_from_callback only reads the shard
    slices addressable by this process, so it works both single- and
    multi-host — unlike a bare device_put of host data onto a global mesh."""
    import jax

    return jax.make_array_from_callback(arr.shape, sharding, lambda idx: arr[idx])


def _fetch_global(out) -> np.ndarray:
    """Gather a (possibly cross-process) sharded result to every host —
    the MPI_Gather x3 analogue (main.c:195-197)."""
    import jax

    if jax.process_count() == 1:
        return np.asarray(out)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(out, tiled=True))


@dataclass(frozen=True)
class ShardedPending:
    """A dispatched-but-unfetched sharded scoring result (VERDICT r2
    item 6).  ``out`` is the still-sharded device array of the shard_map
    call — dispatch has returned, the device computes in the background —
    and ``result()`` performs the gather (``_fetch_global``; a collective
    on multi-host, so every process must reach it, which the CLI's
    chunk-lockstep schedule guarantees).  Deferring the fetch preserves
    --stream's parse/compute overlap and the bucketed back-to-back
    dispatch on meshes, where forcing inside ``score`` serialised them."""

    out: object
    count: int

    def prefetch(self) -> None:
        """Non-blocking device->host copy start (see
        ``PendingResult.prefetch``).  Single-process only: the multi-host
        ``result()`` is a collective gather whose schedule every host
        must reach identically — prefetching locally would not change
        it, and the tunnel-latency problem it solves is single-host."""
        import jax

        if jax.process_count() == 1:
            f = getattr(self.out, "copy_to_host_async", None)
            if f is not None:
                f()

    def result(self) -> np.ndarray:
        with _deadline_guard("sharded result gather"):
            return _fetch_global(self.out)[: self.count]


@dataclass
class BatchSharding:
    """Scores a PaddedBatch data-parallel over a 1-D device mesh."""

    mesh: Mesh

    # Batch-only meshes support length-bucketed dispatch (VERDICT r2
    # item 8): the bucket schedule derives deterministically from the
    # broadcast-identical global lens, so every host runs the same
    # sequence of per-bucket collectives.
    bucketed = True

    @classmethod
    def over_devices(cls, n_devices: int | None = None) -> "BatchSharding":
        return cls(mesh=make_mesh(n_devices))

    @property
    def n_devices(self) -> int:
        return self.mesh.devices.size

    def score(
        self,
        batch: PaddedBatch,
        val_flat: np.ndarray,
        backend: str = "xla",
        chunk_budget: int = DEFAULT_CHUNK_BUDGET,
    ) -> np.ndarray:
        """Returns [B, 3] int32 host array, input order."""
        return self.score_async(
            batch, val_flat, backend=backend, chunk_budget=chunk_budget
        ).result()

    def score_async(
        self,
        batch: PaddedBatch,
        val_flat: np.ndarray,
        backend: str = "xla",
        chunk_budget: int = DEFAULT_CHUNK_BUDGET,
    ) -> ShardedPending:
        """``score`` without forcing the gather: returns a
        :class:`ShardedPending` immediately after the shard_map dispatch."""
        fn, args, b = self._prepare(
            batch, val_flat, backend=backend, chunk_budget=chunk_budget
        )
        return ShardedPending(fn(*args), b)

    def _prepare(
        self,
        batch: PaddedBatch,
        val_flat: np.ndarray,
        backend: str = "xla",
        chunk_budget: int = DEFAULT_CHUNK_BUDGET,
    ):
        """Resolve the compiled sharded program and its device-placed
        arguments without dispatching: ``(fn, args, batch_size)`` — the
        same split as ``RingSharding._prepare``, shared by ``score_async``
        and the compiled-collective-structure tests (which lower exactly
        the production program)."""
        import jax.numpy as jnp

        from ..ops.dispatch import choose_pallas_formulation, xla_formulation_mode

        if backend == "pallas":
            # Shared eligibility policy (exactness + import guard); shape
            # alignment is handled per-shard by pallas_pair_scorer's own
            # fallback, so no dims are pinned here.  The broadcast batch's
            # l2p engages the length-aware exactness bound identically on
            # every host (same compiled SPMD program).
            fm = choose_pallas_formulation(val_flat, (), batch.l2p)
            if fm[0] == "pallas":
                from ..ops.pallas_scorer import choose_superblock

                # Every host derives sb from the same broadcast problem,
                # so the compiled SPMD programs agree.
                sb = choose_superblock(
                    batch.l1p // 128,
                    batch.l2p // 128,
                    batch.len1,
                    batch.len2,
                    fm[1],
                )
                mode = ("pallas", batch.l1p, batch.l2p, fm[1], sb)
            else:
                # Same float32 bound as the matmul path: route to int32.
                mode = ("gather",)
        else:
            m = xla_formulation_mode(backend, val_flat, batch.l2p)
            if m == "mm":
                from ..ops.matmul_scorer import mm_precision

                mode = ("mm", mm_precision(val_flat))
            else:
                mode = (m,)

        d = self.n_devices
        b = batch.batch_size
        # Pallas mode streams V through VMEM: per-row footprint is the
        # codes row, not the XLA paths' l1p*l2p intermediates.
        per_pair = batch.l2p if mode[0] == "pallas" else batch.l1p * batch.l2p
        cb = choose_chunk_rows(per_pair, chunk_budget, -(-b // d))
        bl = cb * (-(-b // (d * cb)))  # per-device rows, multiple of cb
        bp = bl * d

        rows, lens = pad_batch_rows(batch, bp)

        rows_d = _put_global(rows, batch_sharded(self.mesh))
        lens_d = _put_global(lens, batch_sharded(self.mesh))
        seq1_d = _put_global(
            np.asarray(batch.seq1ext, dtype=np.int32), replicated(self.mesh)
        )
        val_d = _put_global(
            np.asarray(val_flat, dtype=np.int32), replicated(self.mesh)
        )
        len1_d = jnp.int32(batch.len1)

        fn = _sharded_fn(self.mesh, cb, mode)
        return fn, (seq1_d, len1_d, rows_d, lens_d, val_d), b


@functools.lru_cache(maxsize=64)
def _sharded_fn(mesh, cb, mode: tuple):
    """Build (and cache) the jitted shard_map scorer for one mesh/chunk
    config; jit itself then caches per input-shape bucket.  ``mode`` is a
    hashable formulation key — ('mm', precision), ('gather',) or
    ('pallas', l1p, l2p, feed) — never a closure object, so repeated calls
    hit the cache."""
    import jax

    if mode[0] == "pallas":
        from ..ops.pallas_scorer import pallas_pair_scorer

        pair_like = pallas_pair_scorer(mode[1], mode[2], mode[3], mode[4])
        chunks_body = None
    elif mode[0] == "mm":
        from ..ops.matmul_scorer import score_chunks_mm_body

        chunks_body = functools.partial(score_chunks_mm_body, mm_precision=mode[1])
        pair_like = None
    else:
        from ..ops.xla_scorer import score_chunks_body as chunks_body

        pair_like = None

    def local_fn(seq1ext, len1, rows, lens, val_flat):
        bl, l2p = rows.shape
        if pair_like is not None:
            return pair_like(seq1ext, len1, rows, lens, val_flat)
        out = chunks_body(
            seq1ext,
            len1,
            rows.reshape(bl // cb, cb, l2p),
            lens.reshape(bl // cb, cb),
            val_flat,
        )
        return out.reshape(bl, 3)

    from .compat import shard_map

    return jax.jit(
        shard_map(
            local_fn,
            mesh=mesh,
            in_specs=(P(), P(), P(BATCH_AXIS), P(BATCH_AXIS), P()),
            out_specs=P(BATCH_AXIS),
            # pallas_call out_shapes carry no varying-mesh-axes metadata, so
            # the vma check must be off for the pallas mode only — the XLA
            # modes keep the trace-time sharding safety net.
            check_vma=(mode[0] != "pallas"),
        )
    )
