"""Multi-host runtime (reference parity: C7 process tier + makefile runOn2).

The reference deploys across two machines with ``mpiexec -np 2 -machinefile
mf --map-by node`` (makefile:15): same binary on every node, rank 0 does the
I/O.  The TPU-native equivalent is single-controller-style multi-host JAX:
every host runs this same program, ``jax.distributed.initialize`` joins the
job (env-driven under SLURM/GKE/TPU-VM metadata, or explicit flags), the
global mesh spans all hosts' devices, and only process 0 touches
stdin/stdout — workers feed from a host-0 broadcast exactly like the
reference's ``MPI_Bcast`` of seq1/weights/sizes (main.c:149-152).
"""

from __future__ import annotations

import functools

from ..resilience.faults import fire as _fault
from ..resilience.watchdog import guard as _deadline_guard
from ..utils.platform import env_int, env_str


def _guarded(describe: str):
    """Arm the run's watchdog (if any) around a coordinator collective:
    the broadcast half of the ``block_until_ready`` / broadcast / gather
    boundary set the --deadline contract names.  A no-op context manager
    when no watchdog is armed."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with _deadline_guard(describe):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join (or start) a multi-host JAX job.

    With no arguments, defers to jax.distributed's environment
    auto-detection (TPU pod metadata, SLURM, ...).  Explicit arguments —
    or JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID env
    vars — cover bare two-machine deployments (the `runOn2` analogue,
    machinefile `mf` replaced by one coordinator address).
    """
    import jax

    coordinator_address = coordinator_address or env_str(
        "JAX_COORDINATOR_ADDRESS"
    )
    if num_processes is None:
        num_processes = env_int("JAX_NUM_PROCESSES")
    if process_id is None:
        process_id = env_int("JAX_PROCESS_ID")
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (ValueError, RuntimeError) as e:
        raise RuntimeError(
            "multi-host initialization failed: set JAX_COORDINATOR_ADDRESS, "
            "JAX_NUM_PROCESSES and JAX_PROCESS_ID (or run under a cluster "
            f"jax.distributed can auto-detect): {e}"
        ) from e


def is_coordinator() -> bool:
    """True on the rank that owns stdin/stdout (reference ROOT, main.c:9)."""
    import jax

    return jax.process_index() == 0


def process_count() -> int:
    import jax

    return jax.process_count()


@_guarded("problem broadcast")
def broadcast_problem(problem, *, failed: bool = False):
    """Broadcast a parsed Problem from process 0 to all processes.

    Only the coordinator reads stdin (reference semantics, main.c:76-108);
    worker processes pass ``problem=None`` and receive the coordinator's.
    Two-phase: a fixed-shape header (sizes) first, then the padded payload —
    the fixed-stride-record idiom of the reference's Scatter buffer
    (main.c:110-121) reused as a broadcast wire format.

    ``failed=True`` (coordinator only) broadcasts an abort header instead,
    so workers raise rather than hang in the collective when the
    coordinator's parse failed — whole-job fail-stop, the C11 stance.
    """
    import jax
    import numpy as np

    _fault("broadcast_problem")
    if jax.process_count() == 1:
        return problem
    from jax.experimental import multihost_utils

    from ..io.parse import Problem
    from ..models.encoding import decode

    if failed:
        header = np.array([0, 0, 0, 1], dtype=np.int32)
    elif problem is not None:
        lens2 = np.array([c.size for c in problem.seq2_codes], dtype=np.int32)
        maxl2 = int(lens2.max()) if lens2.size else 0
        header = np.array(
            [problem.seq1_codes.size, len(problem.seq2_codes), maxl2, 0],
            dtype=np.int32,
        )
    else:
        header = np.zeros(4, dtype=np.int32)
    header = np.asarray(multihost_utils.broadcast_one_to_all(header))
    if int(header[3]):
        if jax.process_index() == 0:
            # The coordinator already has the real parse exception in
            # flight; let it propagate instead of masking it here.
            return None
        raise RuntimeError(
            "coordinator failed before broadcasting the problem; aborting"
        )
    l1, n, maxl2 = int(header[0]), int(header[1]), int(header[2])

    if problem is not None:
        weights = np.asarray(problem.weights, dtype=np.int32)
        seq1 = np.asarray(problem.seq1_codes, dtype=np.int8)
        rows = np.zeros((n, maxl2), dtype=np.int8)
        for i, c in enumerate(problem.seq2_codes):
            rows[i, : c.size] = c
        lens = lens2
    else:
        weights = np.zeros(4, dtype=np.int32)
        seq1 = np.zeros(l1, dtype=np.int8)
        rows = np.zeros((n, maxl2), dtype=np.int8)
        lens = np.zeros(n, dtype=np.int32)

    weights, seq1, rows, lens = (
        np.asarray(a)
        for a in multihost_utils.broadcast_one_to_all((weights, seq1, rows, lens))
    )
    seq2_codes = [rows[i, : int(lens[i])] for i in range(n)]
    return Problem(
        weights=[int(x) for x in weights],
        seq1=decode(seq1),
        seq2=[decode(c) for c in seq2_codes],
        seq1_codes=seq1,
        seq2_codes=seq2_codes,
    )


def broadcast_from_coordinator(tree):
    """Host-level broadcast of (numpy) data from process 0 to all processes —
    the MPI_Bcast tier for multi-host runs where only host 0 parsed stdin.
    No-op in single-process jobs."""
    import jax

    if jax.process_count() == 1:
        return tree
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(tree)


def _bcast(arr):
    import numpy as np
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.broadcast_one_to_all(arr))


@_guarded("resume index-set broadcast")
def broadcast_index_set(indices=None, *, failed: bool = False):
    """Two-phase broadcast of an int32 index array from the coordinator
    (workers pass ``None``); returns the array on every process.

    The --journal x --distributed composition: the coordinator loads the
    journal's done-set and broadcasts the indices, so every host derives
    the IDENTICAL reduced scoring schedule — resume must never
    desynchronise the collective schedules (the r1 static rejection this
    replaces).  ``failed=True`` (coordinator only) broadcasts an abort
    header so workers raise instead of hanging in the payload collective
    when the coordinator's journal load failed.
    """
    import jax
    import numpy as np

    _fault("broadcast_index_set")
    if jax.process_count() == 1:
        return np.asarray(
            [] if indices is None else indices, dtype=np.int32
        )
    if failed:
        header = np.array([0, 1], dtype=np.int32)
    elif indices is not None:
        header = np.array([len(indices), 0], dtype=np.int32)
    else:
        header = np.zeros(2, dtype=np.int32)
    header = _bcast(header)
    if int(header[1]):
        if jax.process_index() == 0:
            return None  # the real exception is already in flight
        raise RuntimeError(
            "coordinator failed while loading the resume journal; aborting"
        )
    n = int(header[0])
    if indices is not None:
        payload = np.asarray(indices, dtype=np.int32).reshape(n)
    else:
        payload = np.zeros(n, dtype=np.int32)
    return _bcast(payload) if n else payload


@_guarded("stream header broadcast")
def broadcast_stream_meta(meta=None, *, failed: bool = False):
    """Broadcast a --stream run's fixed state (weights, seq1_codes,
    num_seq2) from the coordinator; workers pass ``None`` and receive the
    tuple.  ``failed=True`` aborts workers (header parse failed)."""
    import jax
    import numpy as np

    _fault("broadcast_stream_meta")
    if jax.process_count() == 1:
        return meta
    if failed:
        header = np.array([0, 0, 1], dtype=np.int32)
    elif meta is not None:
        weights, seq1_codes, num_seq2 = meta
        header = np.array([len(seq1_codes), num_seq2, 0], dtype=np.int32)
    else:
        header = np.zeros(3, dtype=np.int32)
    header = _bcast(header)
    if int(header[2]):
        if jax.process_index() == 0:
            return None
        raise RuntimeError(
            "coordinator failed before broadcasting the stream header; aborting"
        )
    l1, n = int(header[0]), int(header[1])
    if meta is not None:
        weights = np.asarray(meta[0], dtype=np.int32)
        seq1 = np.asarray(meta[1], dtype=np.int8)
    else:
        weights = np.zeros(4, dtype=np.int32)
        seq1 = np.zeros(l1, dtype=np.int8)
    weights, seq1 = (_bcast(a) for a in (weights, seq1))
    return [int(x) for x in weights], seq1, n


@_guarded("chunk broadcast")
def broadcast_chunk(codes=None, *, end: bool = False, failed: bool = False):
    """Broadcast one streaming chunk's (possibly journal-reduced) code
    arrays from the coordinator; workers pass ``None``.

    Returns the list of code arrays, or ``None`` when the coordinator
    signalled ``end=True`` (stream complete).  ``failed=True`` aborts
    workers mid-stream (parse error / journal mismatch after some chunks
    already streamed) instead of leaving them blocked on the next chunk.
    """
    import jax
    import numpy as np

    _fault("broadcast_chunk")
    if jax.process_count() == 1:
        return None if (end or failed) else codes
    if failed:
        header = np.array([0, 0, 1, 0], dtype=np.int32)
    elif end:
        header = np.array([0, 0, 0, 1], dtype=np.int32)
    elif codes is not None:
        # maxl floor of 1: a chunk of n > 0 all-empty sequences must not
        # broadcast (n, 0)-shaped rows — the zero-size-transport reliance
        # the n == 0 skip removed (ADVICE r3).  Workers still recover
        # empty arrays via lens.
        maxl = max(max((c.size for c in codes), default=0), 1)
        header = np.array([len(codes), maxl, 0, 0], dtype=np.int32)
    else:
        header = np.zeros(4, dtype=np.int32)
    header = _bcast(header)
    if int(header[2]):
        if jax.process_index() == 0:
            return None
        raise RuntimeError(
            "coordinator failed mid-stream; aborting"
        )
    if int(header[3]):
        return None  # end of stream
    n, maxl = int(header[0]), int(header[1])
    if not n:
        # Fully-journalled chunk: skip the payload collectives entirely,
        # exactly like broadcast_index_set — every host derives n from the
        # header it just received, so the skip stays in lockstep (ADVICE
        # r2: broadcasting (0, 0)-shaped arrays relied on zero-size
        # support in the transport).
        return []
    rows = np.zeros((n, maxl), dtype=np.int8)
    lens = np.zeros(n, dtype=np.int32)
    for i, c in enumerate(codes or ()):
        rows[i, : c.size] = c
        lens[i] = c.size
    rows, lens = (_bcast(a) for a in (rows, lens))
    return [rows[i, : int(lens[i])] for i in range(n)]


def scatter_gather_rescue(
    seq1_codes,
    seq2_codes,
    weights,
    *,
    policy,
    beacon_s: float,
    backend: str = "xla",
    board=None,
    process_id: int | None = None,
    num_processes: int | None = None,
    run_tag: str = "batch0",
    log=None,
):
    """Host-level scatter/gather scoring with lost-shard rescue (the
    ``SEQALIGN_BEACON_S`` tier for ``--distributed`` batch runs).

    The SPMD sharded path gathers results inside a collective, so a dead
    worker hangs every peer until the coordination-service teardown and
    the whole batch dies — the reference's MPI_Gatherv failure mode
    (main.c:190-197) in TPU clothes.  This tier trades the collective
    for the reference's *scatter* shape made survivable:

    1. Every process derives the same contiguous index ledger
       (:func:`resilience.rescue.shard_index_sets` — MPI_Scatter parity)
       and scores its OWN shard on a LOCAL scorer.  No collectives:
       a dead worker cannot hang anyone.
    2. Each process posts a liveness beacon + its rows to the
       coordination-service KV board (process 0's sidecar server, which
       outlives dead workers).
    3. The coordinator gathers each worker's shard under the beacon
       deadline (watchdog-guarded); a timeout identifies exactly which
       index-set the missing worker owned.
    4. Orphaned indices are rescored locally through the degradation
       chain (:func:`resilience.rescue.rescue_orphans`, local XLA
       backend) — the run completes with byte-identical output, minus
       the dead worker's speedup.

    Returns the full [N, 3] int32 rows on the coordinator, None on
    workers (they print nothing — main.c:199-211 semantics).
    ``board`` / ``process_id`` / ``num_processes`` are injectable so the
    lost-worker protocol is testable single-process (a worker that never
    posted to a MemoryBoard IS a lost worker, deterministically).
    """
    import jax
    import numpy as np

    from ..obs import export as obs_export
    from ..obs.events import log_line
    from ..ops.dispatch import AlignmentScorer
    from ..resilience import rescue

    pid = jax.process_index() if process_id is None else int(process_id)
    nprocs = (
        jax.process_count() if num_processes is None else int(num_processes)
    )
    log = log or log_line
    if board is None:
        board = (
            rescue.MemoryBoard()
            if nprocs == 1
            else rescue.CoordinationBoard(beacon_s)
        )
    ledger = rescue.shard_index_sets(len(seq2_codes), nprocs)
    mine = ledger[pid]
    scorer = AlignmentScorer(backend=backend)
    my_rows = (
        scorer.score_codes(
            seq1_codes, [seq2_codes[i] for i in mine], weights
        )
        if mine
        else np.zeros((0, 3), dtype=np.int32)
    )
    rescue.post_shard(board, run_tag, pid, my_rows)
    # The metrics plane rides the same board: each host's snapshot posts
    # next to its rows (no-op with metrics off), so the coordinator's run
    # report can carry a merged per-host `hosts` section.
    obs_export.post_host_snapshot(board, run_tag, pid)
    if pid != 0:
        return None

    out = np.zeros((len(seq2_codes), 3), dtype=np.int32)
    if mine:
        out[mine] = my_rows
    lost = []
    for w in range(1, nprocs):
        idx = ledger[w]
        if not idx:
            continue
        with _deadline_guard(f"shard gather (worker {w})"):
            rows = rescue.fetch_shard(
                board, run_tag, w, len(idx), timeout_s=beacon_s
            )
        if rows is None:
            lost.append(w)
            continue
        out[idx] = rows
    # Fold posted host snapshots into the fleet report; workers already
    # known lost are skipped rather than waiting out their timeout twice.
    obs_export.gather_fleet(
        board, run_tag, nprocs, skip=lost, timeout_s=beacon_s
    )
    if lost:
        orphans = [i for w in lost for i in ledger[w]]
        log(
            f"mpi_openmp_cuda_tpu: warning: worker(s) {lost} missed the "
            f"{beacon_s:g}s beacon deadline; rescuing {len(orphans)} "
            "orphaned sequence(s) on the coordinator's local backend"
        )
        out[orphans] = rescue.rescue_orphans(
            seq1_codes,
            [seq2_codes[i] for i in orphans],
            weights,
            policy=policy,
            backend=backend,
            log=log,
        )
    return out
