"""Mesh-spec grammar shared by every entry point (CLI --mesh, the native
ABI's TPU_SEQALIGN_MESH, library callers).

One parser so the surfaces cannot drift: 'N' or 'batch:N' shards the Seq2
batch over N devices (data parallel, the MPI_Scatter tier), 'seq:N'
ring-shards Seq1 over N devices (sequence/context parallel), 'DxS'
composes both on a 2-D mesh.  Bad specs raise ValueError (never a silent
fallback to some other parallelism strategy); a missing subsystem module
raises RuntimeError with the offending feature named.
"""

from __future__ import annotations


class FeatureUnavailableError(RuntimeError):
    """A lazily-imported subsystem is absent from this build."""


def _feature_import(what: str, importer):
    try:
        return importer()
    except ModuleNotFoundError as e:
        raise FeatureUnavailableError(
            f"{what} is not available in this build ({e.name} missing)"
        ) from e


def build_sharding(mesh_arg: str | None):
    """Parse a mesh spec into a sharding strategy (None = single device)."""
    if mesh_arg is None:
        return None

    def _imp_batch():
        from .sharding import BatchSharding

        return BatchSharding

    def _imp_ring():
        from .ring import RingSharding

        return RingSharding

    def _bad(detail: str = "") -> ValueError:
        return ValueError(
            f"bad --mesh spec {mesh_arg!r}: expected 'N', 'batch:N', "
            f"'seq:N', or 'DxS'{detail}"
        )

    def _count(token: str) -> int:
        try:
            value = int(token)
        except ValueError:
            raise _bad() from None
        if value < 1:
            raise _bad(f" (device count must be >= 1, got {value})")
        return value

    spec = mesh_arg.split(":")
    if len(spec) == 2:
        # Explicit axis prefix: anything but 'seq'/'batch' is a spec error,
        # never a silent fallback to some other parallelism strategy.
        if spec[0] == "seq":
            return _feature_import(
                "--mesh sequence sharding", _imp_ring
            ).over_devices(seq=_count(spec[1]))
        if spec[0] == "batch":
            return _feature_import(
                "--mesh batch sharding", _imp_batch
            ).over_devices(_count(spec[1]))
        raise _bad(f" (unknown axis {spec[0]!r})")
    if len(spec) != 1:
        raise _bad()
    if "x" in spec[0]:
        tokens = spec[0].split("x")
        if len(tokens) != 2:
            raise _bad()
        dp, sp = (_count(t) for t in tokens)
        return _feature_import("--mesh 2-D sharding", _imp_ring).over_devices(
            seq=sp, batch=dp
        )
    return _feature_import("--mesh batch sharding", _imp_batch).over_devices(
        _count(spec[0])
    )
