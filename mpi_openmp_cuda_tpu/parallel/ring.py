"""Sequence/context parallelism: ring-sharded Seq1 (SURVEY §2.4 SP/CP row).

The reference parallelises *within* a sequence only inside one GPU (one CUDA
thread per Seq2 character, cudaFunctions.cu:66-99); Seq1 itself is bounded
by a single device's buffer (myProto.h:3).  This module removes that ceiling
the way ring attention does for KV blocks:

* Seq1 is split into ``sp`` contiguous blocks, one per device along a
  ``'seq'`` mesh axis; each device *owns the candidate offsets* that start
  inside its block (the "query block" analogue).
* Scoring offset ``n`` needs the Seq1 window ``[n, n + L2 + 1]``, which
  spills into neighbouring blocks.  Each device assembles its window from
  ``R = ceil((L2P+1)/Bs)`` ring steps of ``lax.ppermute`` — neighbour
  exchange over ICI, never an all-gather of the full sequence.  Per-device
  memory is O(Bs + L2) for the window, O(Bs * L2) for its score grid —
  both independent of the global Seq1 length.
* Each device reduces its grid to one best candidate (first-hit argmax =
  the reference's offset-major tie-break within the block, SURVEY A.3),
  then one tiny ``all_gather`` of per-device (score, n, k, eq) candidates
  picks the global winner — lowest device index on ties, which is exactly
  offset-major order globally.
* Wrapped ring blocks (past the end of Seq1) only ever feed grid cells
  that the validity masks already exclude: valid reads stop at global
  index ``len1 - 1 < sp * Bs``.

Composes with data parallelism on a 2-D ``('batch', 'seq')`` mesh: the
batch axis shards Seq2 rows (the MPI_Scatter tier), the seq axis shards
Seq1 — dp x sp.  Yields the same (score, n, k) triples, bit-exact, as the
single-device paths; property-tested against the host oracle.

Measured cost (``scripts/ring_bench.py``, TPU v5 lite, probe-gated): the
ring schedule itself taxes the fused kernel ~1.00-1.14x at reference
scale (input3 through ring-sp1 vs direct, three gated session pairs
across r4-r5; the r5 pair read 0.993 - statistically equal), and
the unbounded tier sustains 1.14e14 eq-comparisons/s/chip at Seq1 = 4x
the reference's cap and 3.83e14 at 8x with Seq2 at 2x its cap
(BASELINE.md r4 ring row; the eq metric is the reference's
(L1-L2)*L2^2 cost model while the ring does O(L1*L2) real work, so the
past-cap numbers partly measure that blow-up — walls 3.28/7.12 ms).
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.dispatch import (
    DEFAULT_CHUNK_BUDGET,
    PaddedBatch,
    choose_chunk_rows,
    pad_batch_rows,
    round_up,
)
from ..utils.constants import ALPHABET_SIZE, INT32_MIN
from .mesh import BATCH_AXIS, SEQ_AXIS, make_2d_mesh


@dataclass
class RingSharding:
    """Scores a PaddedBatch with Seq1 ring-sharded over the 'seq' axis."""

    mesh: Mesh  # axes (BATCH_AXIS, SEQ_AXIS)

    # Sharded Seq1 has no single-buffer ceiling: AlignmentScorer lifts the
    # reference's BUF_SIZE caps (myProto.h:3-4) when scoring through this.
    unbounded = True

    @classmethod
    def over_devices(cls, seq: int, batch: int = 1) -> "RingSharding":
        return cls(mesh=make_2d_mesh(batch, seq))

    @property
    def sp(self) -> int:
        return self.mesh.shape[SEQ_AXIS]

    @property
    def dp(self) -> int:
        return self.mesh.shape[BATCH_AXIS]

    def score(
        self,
        batch: PaddedBatch,
        val_flat: np.ndarray,
        backend: str = "xla",
        chunk_budget: int = DEFAULT_CHUNK_BUDGET,
    ) -> np.ndarray:
        """Returns [B, 3] int32 host array, input order."""
        return self.score_async(
            batch, val_flat, backend=backend, chunk_budget=chunk_budget
        ).result()

    def score_async(
        self,
        batch: PaddedBatch,
        val_flat: np.ndarray,
        backend: str = "xla",
        chunk_budget: int = DEFAULT_CHUNK_BUDGET,
    ):
        """``score`` without forcing the gather (VERDICT r2 item 6):
        returns a ShardedPending immediately after the shard_map dispatch.

        Formulations: the XLA gather path (always available) and the fused
        Pallas kernel run per shard on its ring-assembled window
        ('pallas'; falls back to gather for overflow-risk weights or
        non-128-aligned shape buckets, mirroring the batch-sharded path).
        'oracle' fails fast rather than silently running something else.
        """
        fn, args, b = self._prepare(
            batch, val_flat, backend=backend, chunk_budget=chunk_budget
        )
        from .sharding import ShardedPending

        return ShardedPending(fn(*args), b)

    def _prepare(
        self,
        batch: PaddedBatch,
        val_flat: np.ndarray,
        backend: str = "xla",
        chunk_budget: int = DEFAULT_CHUNK_BUDGET,
    ):
        """Resolve the compiled ring program and its device-placed
        arguments without dispatching: ``(fn, args, batch_size)``.

        Shared by ``score_async`` (which calls ``fn(*args)`` once) and the
        ring-tier bench (``scripts/ring_bench.py``), which times an
        amortised loop around the SAME compiled fn and argument placement
        the production path dispatches — one derivation, so the bench
        cannot drift from what ships."""
        if backend not in ("xla", "xla-gather", "pallas"):
            raise ValueError(
                f"backend {backend!r} is not available on the sequence-parallel "
                "ring path; drop --backend or use a batch-only mesh"
            )
        import jax.numpy as jnp

        from ..ops.dispatch import choose_pallas_formulation

        mode: tuple = ("gather",)
        if backend == "pallas":
            # Bs (the kernel's L1P) is forced to a 128 multiple below.
            # The kernel's Seq2 span is l2p on every shard, so the
            # length-aware exactness bound applies unchanged here.
            mode = choose_pallas_formulation(val_flat, (batch.l2p,), batch.l2p)

        sp, dp = self.sp, self.dp
        bs, _ = ring_plan(batch.l1p, batch.l2p, sp, pallas=mode[0] == "pallas")
        if mode[0] == "pallas":
            from ..ops.pallas_scorer import choose_superblock

            # One sb for every shard (same compiled SPMD program); model
            # it with a fully-valid shard window (len1 = bs) — the ring
            # exists for wide valid ranges, and every host derives the
            # same value from the same broadcast lens.
            mode = (*mode, choose_superblock(
                bs // 128, batch.l2p // 128, bs, batch.len2, mode[1]
            ))

        seq1pad = np.zeros(sp * bs, dtype=np.int32)
        take = min(seq1pad.size, batch.seq1ext.size)
        seq1pad[:take] = batch.seq1ext[:take]

        b = batch.batch_size
        # Chunk the per-device batch rows so the [cb, Bs, L2P] grid stays
        # inside the budget (the C14 memory-manager role).
        per_pair = batch.l2p if mode[0] == "pallas" else bs * batch.l2p
        cb = choose_chunk_rows(per_pair, chunk_budget, -(-b // dp))
        bl = cb * (-(-b // (dp * cb)))
        bp = bl * dp
        rows, lens = pad_batch_rows(batch, bp)

        from .sharding import _put_global

        rows_d = _put_global(rows, NamedSharding(self.mesh, P(BATCH_AXIS)))
        lens_d = _put_global(lens, NamedSharding(self.mesh, P(BATCH_AXIS)))
        seq1_d = _put_global(seq1pad, NamedSharding(self.mesh, P(SEQ_AXIS)))
        val_d = _put_global(
            np.asarray(val_flat, dtype=np.int32), NamedSharding(self.mesh, P())
        )
        fn = _ring_fn(self.mesh, bs, batch.l2p, cb, mode)
        args = (seq1_d, jnp.int32(batch.len1), rows_d, lens_d, val_d)
        return fn, args, b


def ring_plan(l1p: int, l2p: int, sp: int, pallas: bool) -> tuple[int, int]:
    """``(Bs, R)``: the per-device offset-block size (sublane-aligned;
    full 128-lane alignment for the Pallas kernel so its grid tiles) and
    the ring-step count ``R = ceil((L2P+1)/Bs)`` needed to materialise
    each shard's window.  Single source for both the production program
    (``_prepare``/``_ring_fn``) and the compiled-collective-structure
    tests that assert the SPMD program performs exactly R neighbour
    exchanges and never a full-Seq1 gather (VERDICT r4 item 1)."""
    bs = round_up(math.ceil(l1p / sp), 128 if pallas else 8)
    return bs, _ring_steps(l2p, bs)


def _ring_steps(l2p: int, bs: int) -> int:
    return math.ceil((l2p + 1) / bs)


@functools.lru_cache(maxsize=32)
def _ring_fn(mesh, bs, l2p, cb, mode: tuple = ("gather",)):
    """Jitted shard_map ring scorer for one (mesh, Bs, L2P, chunk,
    formulation) config.  ``mode`` is ('gather',) or ('pallas', feed, sb)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    sp = mesh.shape[SEQ_AXIS]
    # Ring steps so the window [0, Bs + L2P + 1) is fully materialised.
    r_steps = _ring_steps(l2p, bs)
    win_len = (r_steps + 1) * bs
    neg = jnp.int32(INT32_MIN)

    def local_fn(seq1_blk, len1, rows, lens, val_flat):
        d = lax.axis_index(SEQ_AXIS).astype(jnp.int32)

        # -- assemble the window: R neighbour exchanges over the ring ----
        win = jnp.zeros(win_len, dtype=jnp.int32)
        blk = seq1_blk
        win = lax.dynamic_update_slice(win, blk, (0,))
        perm = [(j, (j - 1) % sp) for j in range(sp)]
        for r in range(1, r_steps + 1):
            blk = lax.ppermute(blk, axis_name=SEQ_AXIS, perm=perm)
            win = lax.dynamic_update_slice(win, blk, (r * bs,))

        bl = rows.shape[0]
        if mode[0] == "pallas":
            # Fused-kernel formulation: the shard's window is a
            # self-contained Seq1 for the kernel; a block-local effective
            # len1 makes its offset-block skip and the in-kernel validity
            # mask agree with the global bound gn < len1 - len2.  The
            # kernel reduces each pair to its best in-shard candidate, so
            # the combine below works on scalars.
            from ..ops.pallas_scorer import _pallas_best

            win_k = win[: bs + l2p + 1]
            len1_eff = len1 - d * bs
            bv, bi, bk, eq = _pallas_best(
                win_k, len1_eff, rows, lens, val_flat, feed=mode[1],
                sb=mode[2],
            )
            # All-invalid shards carry the kernel's f32 _NEG sentinel
            # (every feed — the packed epilogue maps its pack sentinel
            # back to _NEG), far below int32 range: map to INT32_MIN
            # before the int cast.
            sc = jnp.where(
                bv <= jnp.float32(INT32_MIN), neg, bv.astype(jnp.int32)
            )
            cand = jnp.stack(
                [sc, d * bs + bi, bk, eq.astype(jnp.int32)], axis=1
            )
        else:
            n_local = jnp.arange(bs, dtype=jnp.int32)[:, None]
            i = jnp.arange(l2p, dtype=jnp.int32)[None, :]
            idx0 = n_local + i
            kk = jnp.arange(l2p, dtype=jnp.int32)[None, :]
            gn = d * bs + n_local

            # Window-value hoist (r6): the whole Seq1 side of the value
            # lookup is pair-independent, so materialise
            # vw[c, t] = val[c, win[t]] once per shard ([27, win_len]
            # int32, a few KB) right after the ring exchanges.  Each
            # candidate pair then performs ONE [Bs, L2P] gather per
            # diagonal family — indexing vw by row-major arithmetic —
            # where the previous body chained a [Bs, L2P] window-char
            # gather (g0/g1) into the value gather under the vmap.
            vw = jnp.take(
                val_flat.reshape(ALPHABET_SIZE, ALPHABET_SIZE), win, axis=1
            ).reshape(-1)  # [27 * win_len]

            def pair_candidate(row, len2):
                vw_base = row[None, :].astype(jnp.int32) * win_len
                charmask = i < len2
                v0 = jnp.where(charmask, jnp.take(vw, vw_base + idx0), 0)
                v1 = jnp.where(charmask, jnp.take(vw, vw_base + idx0 + 1), 0)
                c0 = jnp.cumsum(v0, axis=1)
                c1 = jnp.cumsum(v1, axis=1)
                t0 = c0[:, -1:]
                t1 = c1[:, -1:]
                scores = jnp.concatenate(
                    [t0, c0[:, :-1] + (t1 - c1[:, :-1])], axis=1
                )
                valid = (gn < jnp.maximum(len1 - len2, 0)) & (
                    (kk == 0) | (kk < len2)
                )
                flat = jnp.where(valid, scores, neg).reshape(-1)
                bi = jnp.argmax(flat).astype(jnp.int32)
                # eq: positional score at global n=0 — real on device 0.
                return jnp.stack(
                    [flat[bi], d * bs + bi // l2p, bi % l2p, c0[0, -1]]
                )

            def chunk_fn(args):
                rows_c, lens_c = args
                return jax.vmap(pair_candidate)(rows_c, lens_c)

            cand = lax.map(
                chunk_fn,
                (rows.reshape(bl // cb, cb, l2p), lens.reshape(bl // cb, cb)),
            ).reshape(bl, 4)

        # -- global combine: tiny all_gather of one candidate per device --
        gathered = lax.all_gather(cand, axis_name=SEQ_AXIS)  # [sp, bl, 4]
        scores = gathered[:, :, 0]
        gi = jnp.argmax(scores, axis=0)  # first-hit: lowest block wins ties
        best = jnp.take_along_axis(
            gathered, gi[None, :, None], axis=0
        )[0]  # [bl, 4]
        eq = gathered[0, :, 3]

        searchable = (lens < len1) & (lens > 0)
        score = jnp.where(
            lens == len1, eq, jnp.where(searchable, best[:, 0], neg)
        )
        out_n = jnp.where(searchable, best[:, 1], 0)
        out_k = jnp.where(searchable, best[:, 2], 0)
        return jnp.stack([score, out_n, out_k], axis=1).astype(jnp.int32)

    from .compat import shard_map

    return jax.jit(
        shard_map(
            local_fn,
            mesh=mesh,
            in_specs=(P(SEQ_AXIS), P(), P(BATCH_AXIS), P(BATCH_AXIS), P()),
            out_specs=P(BATCH_AXIS),
            # The output is replicated over 'seq' by construction (every
            # device runs the identical combine on the all_gather'd
            # candidates), which the static vma inference cannot see.
            check_vma=False,
        )
    )
