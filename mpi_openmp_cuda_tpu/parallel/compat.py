"""JAX API compatibility for the pinned deployment surface.

The package pins ``jax >= 0.4.37`` (pyproject.toml) — the floor is the
version the suite is actually run against, chosen for the Pallas strided
rotate (``pltpu.roll`` with ``stride``/``stride_axis``) and the modern
``shard_map``.  One API moved between the floor and current jax:
``shard_map`` lived in ``jax.experimental.shard_map`` (replication check
spelled ``check_rep``) before graduating to ``jax.shard_map`` (spelled
``check_vma``).  Every shard_map construction in the package goes through
this one shim so the two sharded paths (batch + ring) cannot drift in how
they handle the rename.
"""

from __future__ import annotations


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the ``check_vma``/``check_rep`` rename
    papered over: the graduated API when present, else the experimental
    one (jax 0.4.x), mapping ``check_vma`` onto its ``check_rep`` — the
    same trace-time replication safety net under its earlier name."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=check_vma,
    )
