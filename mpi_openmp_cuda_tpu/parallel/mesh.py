"""Device mesh construction (reference parity: C7 topology setup).

The reference's process topology is `mpiexec -np N` + `MPI_COMM_WORLD`
(makefile:11,15; main.c:62-64).  The TPU equivalent is a named
`jax.sharding.Mesh`: a 1-D ``('batch',)`` axis for data parallelism over the
Seq2 batch; the sequence-parallel ring (parallel/ring.py) adds a ``'seq'``
axis for long-context sharding.  Collectives ride ICI within a slice and
DCN across slices — chosen by XLA from the sharding layout, not hand-coded.
"""

from __future__ import annotations

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

BATCH_AXIS = "batch"
SEQ_AXIS = "seq"


def make_mesh(
    n_devices: int | None = None,
    *,
    axis_name: str = BATCH_AXIS,
    devices=None,
) -> Mesh:
    """1-D mesh over the first ``n_devices`` devices (default: all)."""
    import jax

    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        if n_devices < 1:
            raise ValueError(f"mesh needs at least 1 device, got {n_devices}")
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices but only {len(devs)} available"
            )
        if jax.process_count() > 1 and n_devices != len(devs):
            # Slicing the global device list would exclude some hosts'
            # devices; their processes would then address nothing in the
            # mesh and hang/fail in the collectives.
            raise ValueError(
                f"multi-host jobs must mesh all {len(devs)} global devices, "
                f"got --mesh {n_devices}"
            )
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def make_2d_mesh(
    batch: int, seq: int, *, devices=None
) -> Mesh:
    """[batch, seq] mesh for combined data + sequence parallelism."""
    import jax

    devs = list(devices if devices is not None else jax.devices())
    if batch * seq > len(devs):
        raise ValueError(
            f"mesh {batch}x{seq} needs {batch * seq} devices, have {len(devs)}"
        )
    if jax.process_count() > 1 and batch * seq != len(devs):
        # Same hazard as make_mesh: a partial global mesh leaves some
        # hosts' devices unaddressed and their processes hang in the
        # collectives instead of erroring.
        raise ValueError(
            f"multi-host jobs must mesh all {len(devs)} global devices, "
            f"got {batch}x{seq}"
        )
    return Mesh(
        np.array(devs[: batch * seq]).reshape(batch, seq), (BATCH_AXIS, SEQ_AXIS)
    )


def replicated(mesh: Mesh) -> NamedSharding:
    """Every-device copy — the MPI_Bcast / CUDA-constant-memory analogue."""
    return NamedSharding(mesh, PartitionSpec())


def batch_sharded(mesh: Mesh, axis_name: str = BATCH_AXIS) -> NamedSharding:
    """Leading-axis shard over the batch — the MPI_Scatter analogue."""
    return NamedSharding(mesh, PartitionSpec(axis_name))
