"""Process-start prewarming: replay the manifest, warm the problem's
schedule, rewrite the manifest — all before the first real dispatch.

Called from the CLI behind ``--prewarm`` / ``SEQALIGN_PREWARM``:

* **serve startup** — before the loop's first tick, so
  ``ServeLoop.baseline_steady`` can pin the steady-compile baseline at
  tick 0 instead of absorbing the first block as warmup, and the
  recompile detector's steady-state-zero gate holds from the FIRST
  request;
* **batch / --resume** — a drain -> resume restart (resilience plane)
  replays its predecessor's manifest and rejoins warm instead of
  re-paying the 3.6-3.8 s first-compile tax the bench measures.

Failure policy: prewarming is an optimization.  Every per-entry compile
is individually guarded (a failed entry is counted on ``aot.failed``
and logged, the rest proceed), and the CLI wraps the whole call — no
prewarm outcome may fail the run.

Emits ``aot.entries`` / ``aot.compiled`` / ``aot.stale`` /
``aot.failed`` counters and the ``prewarm_wall_s`` gauge into the obs
registry, so the run report shows exactly what warmth cost.
"""

from __future__ import annotations

import time

from ..obs.events import log_line
from ..obs.metrics import gauge, inc
from .compile import compile_entry, ensure_persistence
from .manifest import (
    build_manifest,
    default_manifest_path,
    load_manifest,
    split_entries,
    write_manifest,
)
from .warmset import WarmEntry, backend_fingerprint, select_warmset


def _replay_entries(manifest_path: str | None, digest: str):
    """(fresh, stale) from the on-disk manifest; ([], []) when there is
    no manifest to replay."""
    if manifest_path is None:
        return [], []
    report = load_manifest(manifest_path)
    if report is None:
        return [], []
    return split_entries(report, digest)


def prewarm(
    problem=None,
    backend: str | None = None,
    *,
    rows_per_block: int | None = None,
    manifest_path: str | None = None,
    top_k: int | None = None,
) -> dict:
    """Warm the process: manifest replay + (when a problem is in hand)
    the problem-derived warm set; returns a summary dict.

    Merge order — manifest first (known-hot from a real prior run),
    then the problem's selected set, then stale re-warms (prior-
    fingerprint entries recompiled under the CURRENT fingerprint,
    source ``stale-rewarm`` — listed in the new manifest, never
    silently replayed) — deduplicated on ``executable_key``.
    """
    t0 = time.perf_counter()
    fp = backend_fingerprint()
    cache_dir = ensure_persistence()
    if manifest_path is None:
        manifest_path = default_manifest_path()

    fresh, stale = _replay_entries(manifest_path, fp["digest"])
    merged: dict[tuple, WarmEntry] = {}
    for e in fresh:
        merged.setdefault(e.executable_key, e)
    if problem is not None and backend not in (None, "oracle"):
        kwargs = {"rows_per_block": rows_per_block}
        if top_k is not None:
            kwargs["top_k"] = top_k
        for e in select_warmset(problem, backend, **kwargs):
            merged.setdefault(e.executable_key, e)
    for d in stale:
        try:
            e = WarmEntry.from_dict({**d, "source": "stale-rewarm"})
        except (ValueError, TypeError) as err:
            log_line(f"mpi_openmp_cuda_tpu: aot stale entry dropped ({err})")
            continue
        merged.setdefault(e.executable_key, e)

    results = []
    failed = 0
    for entry in merged.values():
        try:
            wall_s, nbytes = compile_entry(entry)
        except Exception as err:
            # advisory: one failed warm compile is inventory, not an
            # error — the entry stays cold and run-time compile covers it.
            failed += 1
            inc("aot.failed")
            log_line(
                "mpi_openmp_cuda_tpu: aot compile failed for "
                f"{entry.executable_key} ({err})"
            )
            continue
        results.append((entry, wall_s, nbytes))

    if manifest_path is not None and results:
        report = build_manifest(results, fp, stale=stale)
        try:
            write_manifest(report, manifest_path)
        except OSError as err:
            log_line(f"mpi_openmp_cuda_tpu: aot manifest write failed ({err})")
            manifest_path = None

    wall = time.perf_counter() - t0
    inc("aot.entries", len(merged))
    inc("aot.compiled", len(results))
    inc("aot.stale", len(stale))
    gauge("prewarm_wall_s", round(wall, 6))
    log_line(
        f"mpi_openmp_cuda_tpu: prewarmed {len(results)}/{len(merged)} "
        f"executables in {wall:.3f}s "
        f"(replayed {len(fresh)}, stale {len(stale)}, failed {failed}; "
        f"cache={'on' if cache_dir else 'off'})"
    )
    return {
        "entries": len(merged),
        "compiled": len(results),
        "replayed": len(fresh),
        "stale": len(stale),
        "failed": failed,
        "prewarm_wall_s": wall,
        "cache_dir": cache_dir,
        "manifest_path": manifest_path,
    }
