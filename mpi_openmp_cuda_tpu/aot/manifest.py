"""The warm-set manifest: what was compiled, under which fingerprint,
at what cost — atomic on disk, versioned through the obs run-report
envelope.

The manifest is the restart half of the warm plane.  A prewarming
process records every entry it compiled (``cache_key``, fingerprint
digest, ``compile_wall_s``, serialized ``bytes``); the NEXT process
loads it and replays those entries through ``compile.compile_entry``
before serving — persistent-cache hits, milliseconds each — without
needing the original problem in hand.

Staleness contract: an entry whose recorded fingerprint digest differs
from the current :func:`~.warmset.backend_fingerprint` is INVALID — a
jax upgrade, backend switch, or device-count change means its cached
executable may not even deserialize (the cross-config segfault
documented in ``utils/platform.enable_compilation_cache``).  Stale
entries are split out by :func:`split_entries`, listed in the next
manifest's ``stale`` section, and re-warmed under the new fingerprint
by ``prewarm`` — never silently reused.

Loading is deliberately forgiving (missing/corrupt manifest -> ``None``
plus a logged line): prewarm is an optimization and must never be the
reason a process fails to start.  Writing is strict and atomic
(tmp + ``os.replace``, the obs exporter's idiom): a reader never sees a
torn manifest.
"""

from __future__ import annotations

import json
import os

from ..obs.events import log_line
from ..obs.metrics import validate_report, wrap_report

#: Envelope kind (validate_report knows this branch).
MANIFEST_KIND = "aot-manifest"


def default_manifest_path() -> str | None:
    """``<cache home>/aot/<platform tag>.json`` — partitioned by the
    same platform/flags tag as the persistent compilation cache, so a
    CPU manifest never drives a TPU replay.  ``None`` when caching is
    disabled (nowhere durable to point at)."""
    from ..utils.platform import cache_home, platform_tag

    home = cache_home()
    if home is None:
        return None
    return os.path.join(home, "aot", f"{platform_tag()}.json")


def build_manifest(results, fingerprint: dict, *, stale=()) -> dict:
    """Wrap compile results into the versioned report envelope.

    ``results`` is ``[(WarmEntry, compile_wall_s, bytes_or_None), ...]``;
    ``stale`` lists superseded entry dicts (prior-fingerprint entries
    re-warmed this run) so the staleness event is auditable, not
    silent."""
    entries = []
    total_wall = 0.0
    total_bytes = 0
    for entry, wall_s, nbytes in results:
        d = entry.to_dict()
        d["fingerprint"] = fingerprint["digest"]
        d["compile_wall_s"] = round(float(wall_s), 6)
        d["bytes"] = nbytes
        entries.append(d)
        total_wall += float(wall_s)
        total_bytes += int(nbytes or 0)
    body = {
        "fingerprint": dict(fingerprint),
        "entries": entries,
        "stale": [dict(s) for s in stale],
        "totals": {
            "entries": len(entries),
            "compile_wall_s": round(total_wall, 6),
            "bytes": total_bytes,
        },
    }
    return wrap_report(MANIFEST_KIND, body)


def write_manifest(report: dict, path: str) -> None:
    """Validate, then atomically persist (tmp + ``os.replace``) —
    a crashing prewarm leaves the previous manifest intact."""
    validate_report(report)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(json.dumps(report, indent=2, sort_keys=True) + "\n")
    os.replace(tmp, path)


def load_manifest(path: str) -> dict | None:
    """The forgiving loader: a valid report dict, or ``None`` (absent,
    unparseable, or schema-invalid — each logged, none fatal)."""
    try:
        with open(path) as f:
            report = json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as e:
        log_line(f"mpi_openmp_cuda_tpu: aot manifest unreadable ({e})")
        return None
    try:
        validate_report(report)
    except ValueError as e:
        log_line(f"mpi_openmp_cuda_tpu: aot manifest invalid ({e})")
        return None
    if report.get("kind") != MANIFEST_KIND:
        log_line(
            f"mpi_openmp_cuda_tpu: aot manifest has kind "
            f"{report.get('kind')!r}, want {MANIFEST_KIND!r}"
        )
        return None
    return report


def split_entries(report: dict, digest: str):
    """(fresh WarmEntries, stale entry dicts) under the CURRENT
    fingerprint digest — the staleness gate.  Fresh entries replay;
    stale ones are re-warmed under the new fingerprint and listed."""
    from .warmset import WarmEntry

    fresh, stale = [], []
    for d in report.get("entries", []):
        if d.get("fingerprint") == digest:
            fresh.append(WarmEntry.from_dict(d))
        else:
            stale.append(d)
    return fresh, stale
