"""AOT lowering/compiling of warm entries, and the persistence they
ride on.

``jit(fn).lower(args).compile()`` performs the REAL backend compile —
the one ``/jax/core/compile/backend_compile_duration`` meters — and, when
the persistent compilation cache is armed, writes the serialized
executable to disk.  Two facts this module is built around (verified
against jax 0.4.37 source — ``pxla.py`` wraps ``compile_or_get_cached``
in the event timer, and ``log_elapsed_time`` records unconditionally):

* The backend-compile monitoring event fires on EVERY compile request
  **including persistent-cache hits**.  The only silent dispatch path
  is the in-memory pjit cache (no re-trace, no compile request at
  all), and ``lower()``/``compile()`` do NOT populate it.  So after
  AOT-compiling, :func:`compile_entry` EXECUTES the jitted entry point
  once with the production avals: the executed call's backend compile
  is a persistent-cache hit (milliseconds), and it leaves the
  in-memory cache primed so the first production dispatch is
  event-silent — which is what lets
  ``analysis/recompile.assert_compiles(0)`` act as the restart-warmth
  oracle from tick 0.
* Executing (rather than just lowering) also warms the eager tiny-op
  executables that tracing dispatches for concrete constants (iota /
  cumsum / where epilogue helpers) — each of those is its own tiny
  compile request, and each fires the event when cold.
* Compiles go through the SAME module-level jitted callables the
  dispatch layer invokes (``score_chunks_pallas`` / ``score_chunks`` /
  ``score_chunks_mm``), with argument avals constructed exactly as
  ``AlignmentScorer._score_local`` builds them — a near-miss aval
  (wrong dtype, wrong weak-typing) would warm a DIFFERENT program and
  the production dispatch would re-trace anyway.

``jax.experimental.serialize_executable`` is probed for per-entry
executable bytes (manifest accounting and a forward path to shipping
executables between hosts); where the backend does not support it the
persistent cache remains the portable replay mechanism and ``bytes`` is
recorded as ``None``.
"""

from __future__ import annotations

import time

import numpy as np


def ensure_persistence() -> str | None:
    """Arm the persistent-cache knobs for prewarming and return the
    active cache directory (``None`` = cache disabled; AOT compiles
    still warm the current process but a restart will re-pay them).

    ``enable_compilation_cache`` keeps jax's 0.2 s floor for normal
    runs — persisting every trivial CPU executable is churn — but a
    prewarm's whole point is replaying FAST compiles too, so the floor
    drops to 0 here."""
    import jax

    cache_dir = getattr(jax.config, "jax_compilation_cache_dir", None)
    if not cache_dir:
        return None
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    return str(cache_dir)


def _target(entry):
    """(jitted callable, static kwargs) for one warm entry — the same
    module-level jit objects the dispatch layer calls, so the lowered
    program is the dispatched program."""
    if entry.formulation == "pallas":
        from ..ops.pallas_scorer import score_chunks_pallas

        return score_chunks_pallas, {
            "feed": entry.feed, "sb": entry.sb, "l2s": entry.l2s,
        }
    if entry.formulation == "xla-mm":
        from jax import lax

        from ..ops.matmul_scorer import score_chunks_mm

        return score_chunks_mm, {
            "mm_precision": lax.Precision.HIGHEST if entry.mm_hi else None,
        }
    if entry.formulation == "xla-gather":
        from ..ops.xla_scorer import score_chunks

        return score_chunks, {}
    raise ValueError(f"unknown formulation {entry.formulation!r}")


def _concrete_args(entry):
    """Concrete zero-filled operands with exactly the avals
    ``_score_local`` dispatches: [L1P+L2P+1] int32 seq1ext, int32 len1
    scalar, [NC, CB, L2P] rows, [NC, CB] lens, [A^2] flat value table.
    Concrete (not ShapeDtypeStruct) so weak-typing matches the real
    call and lowering shares the dispatch-time cache key."""
    import jax.numpy as jnp

    from ..utils.constants import ALPHABET_SIZE

    return (
        jnp.asarray(np.zeros(entry.l1p + entry.l2p + 1, dtype=np.int32)),
        jnp.int32(entry.len1),
        jnp.asarray(
            np.zeros((entry.n_chunks, entry.cb, entry.l2p), dtype=np.int32)
        ),
        jnp.asarray(np.zeros((entry.n_chunks, entry.cb), dtype=np.int32)),
        jnp.asarray(np.zeros(ALPHABET_SIZE**2, dtype=np.int32)),
    )


def _executable_bytes(compiled) -> int | None:
    """Serialized-executable size when the backend supports it, else
    ``None`` (the persistent cache still replays the entry)."""
    try:
        from jax.experimental.serialize_executable import serialize

        blob = serialize(compiled)
        if isinstance(blob, tuple):
            blob = blob[0]
        return len(blob)
    except Exception:
        # advisory: serialized-size probe only — the compile itself
        # already succeeded; None just hides the bytes column.
        return None


def compile_entry(entry) -> tuple[float, int | None]:
    """Compile-and-warm ONE entry; returns (compile_wall_s, bytes).

    Two steps, both timed: the AOT ``lower().compile()`` (real backend
    compile on a cold cache, deserialization on a warm one — and the
    handle the manifest's ``bytes`` accounting needs), then ONE
    executed call on the same avals.  The call's own compile request
    hits the executable just written, and it is the step that primes
    the in-memory pjit cache — the only thing that makes the next
    dispatch of this program event-silent (see module docstring).  The
    wall is therefore the honest restart cost: seconds cold,
    milliseconds replaying a populated cache."""
    import jax

    fn, statics = _target(entry)
    args = _concrete_args(entry)
    t0 = time.perf_counter()
    compiled = fn.lower(*args, **statics).compile()
    jax.block_until_ready(fn(*args, **statics))
    wall = time.perf_counter() - t0
    return wall, _executable_bytes(compiled)
