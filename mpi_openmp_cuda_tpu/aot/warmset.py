"""Warm-set selection: WHICH executables to compile before the first
request, and the fingerprint that scopes their validity.

An executable's identity has two halves:

* the **static half** — ``ops/schedule.BucketKernelConfig.cache_key``
  (formulation, feed, shape bucket, chunk, superblock, packing class)
  plus the traced ``n_chunks`` leading dimension and, on the matmul
  path, the static ``mm_precision`` argument.  :class:`WarmEntry`
  carries exactly this; its :attr:`WarmEntry.executable_key` is the
  dedup key of the warm set.
* the **environment half** — :func:`backend_fingerprint`: jax/jaxlib
  versions, the resolved backend, and the platform/flags tag
  ``utils.platform.platform_tag`` already partitions the persistent
  cache by.  A manifest entry whose recorded fingerprint differs from
  the current one is STALE: re-warmed under the new fingerprint, never
  silently reused (the cross-config deserialization crash documented in
  ``utils/platform.enable_compilation_cache`` is what "silently reused"
  costs).

:func:`select_warmset` merges three sources, most valuable first:

1. the top-K of ``analysis/costmodel.schedule_cost_sheet``'s hot-config
   ranking (built "for AOT warming"; pallas schedules only — the sheet
   prices the fused kernel),
2. the problem's full production bucket schedule (one entry per LAUNCH
   GROUP: since r6's launch fusion, ``production_schedule`` emits the
   fusion planner's merged groups, so the warm set compiles the fused
   executables — not the pre-fusion per-bucket ones — through the same
   routing ``AlignmentScorer._score_local`` applies at dispatch time),
   and
3. the serve superblock shapes (every ``--serve`` dispatch is exactly
   ``rows_per_block`` padded rows per L2P bucket), so a batch-mode
   prewarm also warms a later serve replica of the same problem key.

Caveat recorded, not hidden: serve-block pallas entries are resolved
with full-length rows (the padded-tail shape a partially-filled block
actually has).  A pallas block of ALL-short real rows may pick a
different superblock/packing class and still pay one compile; the XLA
formulations are shape-only, so the CPU serve path is warmed exactly.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json

import numpy as np

#: Hot-config rows taken from the cost sheet's ranking by default.
DEFAULT_TOP_K = 4


@dataclasses.dataclass(frozen=True)
class WarmEntry:
    """One AOT-compilable executable identity (the static half)."""

    formulation: str  # 'pallas' | 'xla-mm' | 'xla-gather'
    feed: str | None  # MXU feed (pallas only)
    mm_hi: bool  # xla-mm: Precision.HIGHEST (static argument)
    l1p: int
    l2p: int
    len1: int  # provenance only: a traced RUNTIME scalar, not identity
    cb: int  # rows per chunk (the traced [NC, CB, L2P] middle dim)
    n_chunks: int  # the traced leading dim
    sb: int | None  # offset-superblock width (static, pallas)
    l2s: int | None  # row-packing class (static, pallas)
    source: str = "schedule"  # schedule | hot-config | serve-block | manifest

    @property
    def cache_key(self) -> tuple:
        """Mirrors ``BucketKernelConfig.cache_key`` field for field."""
        return (
            self.formulation, self.feed, self.l1p, self.l2p, self.cb,
            self.sb, self.l2s,
        )

    @property
    def executable_key(self) -> tuple:
        """The dedup key: cache_key x traced chunk count x the matmul
        path's static precision.  ``len1`` is excluded deliberately —
        it is a runtime scalar operand, so two entries differing only
        in len1 share one compiled program."""
        return self.cache_key + (self.n_chunks, bool(self.mm_hi))

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["cache_key"] = list(self.cache_key)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "WarmEntry":
        fields = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in d.items() if k in fields}
        missing = {
            "formulation", "l1p", "l2p", "cb", "n_chunks",
        } - set(kw)
        if missing:
            raise ValueError(
                f"warm entry missing fields {sorted(missing)}: {d!r}"
            )
        kw.setdefault("feed", None)
        kw.setdefault("mm_hi", False)
        kw.setdefault("len1", 0)
        kw.setdefault("sb", None)
        kw.setdefault("l2s", None)
        return cls(**kw)


def backend_fingerprint() -> dict:
    """The environment half of an executable's identity, with a stable
    ``digest`` the manifest staleness check compares.

    Includes the resolved runtime backend (initialising it is fine here:
    prewarm runs at process start, after ``apply_platform_override``)
    and the same platform/flags tag the persistent cache partitions its
    directory by — writers and readers of a warm set must agree on every
    component, exactly like the cache partitioning they ride on."""
    import jax

    from ..utils.platform import platform_tag

    try:
        import jaxlib

        jaxlib_version = getattr(jaxlib, "__version__", jax.__version__)
    except ImportError:  # pragma: no cover - jaxlib always ships with jax
        jaxlib_version = jax.__version__
    fp = {
        "jax": jax.__version__,
        "jaxlib": jaxlib_version,
        "backend": jax.default_backend(),
        "platform_tag": platform_tag(),
    }
    fp["digest"] = hashlib.sha256(
        json.dumps(fp, sort_keys=True).encode()
    ).hexdigest()[:16]
    return fp


def _resolve_entry_config(backend, val_flat, l1p, l2p, len1, lens):
    """(formulation, feed, sb, l2s, mm_hi) for one padded bucket —
    the same routing ``AlignmentScorer._score_local`` applies, via the
    same single-source policy helpers, so a warm entry names exactly
    the program the dispatch will call."""
    from ..ops.dispatch import (
        choose_pallas_formulation,
        choose_rowpack,
        xla_formulation_mode,
    )
    from ..ops.values import max_abs_value

    if backend == "pallas":
        fm = choose_pallas_formulation(val_flat, (), l2p)
        if fm[0] == "pallas":
            from ..ops.pallas_scorer import choose_superblock

            feed = fm[1]
            sb = choose_superblock(l1p // 128, l2p // 128, int(len1), lens, feed)
            l2s = choose_rowpack(feed, l2p, lens, maxv=max_abs_value(val_flat))
            return ("pallas", feed, sb, l2s, False)
        backend = "xla-gather"  # the overflow-risk fallback routing
    if xla_formulation_mode(backend, val_flat, l2p) == "mm":
        from ..ops.matmul_scorer import mm_precision

        return ("xla-mm", None, None, None, mm_precision(val_flat) is not None)
    return ("xla-gather", None, None, None, False)


def _schedule_entries(problem, backend, val_flat) -> list[WarmEntry]:
    """One entry per production-schedule launch group (source 2; the
    fused executables, since the schedule derivation IS the fusion
    planner's output)."""
    from ..ops.schedule import production_schedule

    _, sched = production_schedule(problem, backend)
    out = []
    for part in sched:
        batch = part["batch"]
        nc, cb = part["lens"].shape
        form, feed, sb, l2s, mm_hi = _resolve_entry_config(
            backend, val_flat, batch.l1p, batch.l2p, batch.len1, batch.len2
        )
        out.append(
            WarmEntry(
                formulation=form, feed=feed, mm_hi=mm_hi,
                l1p=batch.l1p, l2p=batch.l2p, len1=batch.len1,
                cb=cb, n_chunks=nc, sb=sb, l2s=l2s, source="schedule",
            )
        )
    return out


def _serve_block_entries(
    problem, backend, val_flat, rows_per_block: int
) -> list[WarmEntry]:
    """One entry per L2P bucket at the serve superblock shape (source 3).

    ``serve/batcher.plan_blocks`` buckets rows with ``packable=False,
    min_rows=1`` and pads every block to exactly ``rows_per_block``
    rows with full-length pad rows — so the dispatched shape per bucket
    is ``[rows_per_block, l2p]`` and (for packing purposes) the lens
    vector of a padded block maxes out at ``l2p``."""
    from ..ops.dispatch import (
        DEFAULT_CHUNK_BUDGET,
        PaddedBatch,
        choose_chunk,
        effective_backend,
        plan_buckets,
        round_up,
    )
    from ..utils.constants import BUF_SIZE_SEQ2

    len1 = int(problem.seq1_codes.size)
    l1p = round_up(len1, 128)
    groups = plan_buckets(
        [c.size for c in problem.seq2_codes], packable=False, min_rows=1
    )
    out = []
    for l2p in sorted(groups):
        real = sorted(
            int(problem.seq2_codes[i].size) for i in groups[l2p]
        )[:rows_per_block]
        # plan_blocks pads tail blocks with rows of min(l2p, buffer cap)
        # characters, so that is the padded block's lens fill value.
        lens = np.full(
            rows_per_block, min(int(l2p), BUF_SIZE_SEQ2), dtype=np.int32
        )
        lens[: len(real)] = real
        batch = PaddedBatch(
            seq1ext=np.zeros(l1p + l2p + 1, dtype=np.int32),
            len1=len1,
            seq2=np.zeros((rows_per_block, l2p), dtype=np.int32),
            len2=lens,
            l1p=l1p,
            l2p=l2p,
        )
        cb = choose_chunk(
            batch,
            DEFAULT_CHUNK_BUDGET,
            backend=effective_backend(backend, val_flat, l2p),
        )
        nc = round_up(rows_per_block, cb) // cb
        form, feed, sb, l2s, mm_hi = _resolve_entry_config(
            backend, val_flat, l1p, l2p, len1, lens
        )
        out.append(
            WarmEntry(
                formulation=form, feed=feed, mm_hi=mm_hi,
                l1p=l1p, l2p=l2p, len1=len1,
                cb=cb, n_chunks=nc, sb=sb, l2s=l2s, source="serve-block",
            )
        )
    return out


def _hot_config_entries(problem, backend, top_k: int) -> list[WarmEntry]:
    """Top-K of the cost sheet's hot-config ranking (source 1).

    The sheet prices the fused kernel only, so this source contributes
    nothing off the pallas backend (``hot_configs`` is empty there) —
    the schedule source still covers those buckets.  Per-entry
    ``n_chunks`` comes from the matching ``kernel_configs`` bucket (the
    hot row's ``launches`` aggregates across buckets sharing a key and
    is NOT a traced dimension)."""
    if backend != "pallas":
        return []
    from ..analysis.costmodel import schedule_cost_sheet
    from ..ops.schedule import kernel_configs

    sheet = schedule_cost_sheet(problem, backend)
    cfgs = kernel_configs(problem, backend) or []
    by_key: dict[tuple, object] = {}
    for c in cfgs:
        by_key.setdefault(c.cache_key, c)
    out = []
    for row in sheet["hot_configs"][:top_k]:
        key = (
            row["formulation"], row["feed"], row["l1p"], row["l2p"],
            row["cb"], row["sb"], row["l2s"],
        )
        cfg = by_key.get(key)
        if cfg is None:
            continue
        out.append(
            WarmEntry(
                formulation=cfg.formulation, feed=cfg.feed, mm_hi=False,
                l1p=cfg.l1p, l2p=cfg.l2p, len1=cfg.len1,
                cb=cfg.cb, n_chunks=cfg.n_chunks, sb=cfg.sb, l2s=cfg.l2s,
                source="hot-config",
            )
        )
    return out


def select_warmset(
    problem,
    backend: str,
    *,
    rows_per_block: int | None = None,
    top_k: int = DEFAULT_TOP_K,
) -> list[WarmEntry]:
    """The deduplicated warm set for one problem/backend, hot configs
    first (most modelled wall saved per compile), then the full bucket
    schedule, then the serve superblock shapes."""
    if backend == "oracle":
        return []  # host numpy: nothing compiles
    from ..ops.values import value_table

    val_flat = value_table(problem.weights).astype(np.int32).reshape(-1)
    merged: dict[tuple, WarmEntry] = {}
    for entry in _hot_config_entries(problem, backend, top_k):
        merged.setdefault(entry.executable_key, entry)
    for entry in _schedule_entries(problem, backend, val_flat):
        merged.setdefault(entry.executable_key, entry)
    if rows_per_block:
        for entry in _serve_block_entries(
            problem, backend, val_flat, int(rows_per_block)
        ):
            merged.setdefault(entry.executable_key, entry)
    return list(merged.values())


def crosscheck_hot_configs(entries, hot_rows) -> list[dict]:
    """Hot-ranking rows with NO covering warm entry (empty = the warm
    set subsumes the ranking).  Keys on the golden-view fields
    ``(l1p, l2p, cb, sb, l2s)`` so it accepts both live cost-sheet rows
    and the committed ``tests/golden/schedule_audit.json`` view."""
    have = {(e.l1p, e.l2p, e.cb, e.sb, e.l2s) for e in entries}
    return [
        r
        for r in hot_rows
        if (r["l1p"], r["l2p"], r["cb"], r.get("sb"), r.get("l2s"))
        not in have
    ]
