"""AOT warm plane: persistent executable cache + startup prewarm.

The reference program pays zero compile cost at run time — ``nvcc``
AOT-compiles its one CUDA kernel at build time (cudaFunctions.cu) — while
our JIT-compiled scorer re-pays 3.6-3.8 s of XLA/Mosaic compiles on every
process start (BENCH_r04/r05).  That tax is fatal for autoscaling serve
replicas and for preemption recovery: a rescued host must rejoin in
milliseconds, not seconds (ROADMAP item 5).

Four modules, one contract:

* :mod:`.warmset` — WHAT to compile: the resolved production-schedule
  bucket configs for the current problem (``ops/schedule.kernel_configs``
  keys), the serve superblock shapes, and the top-K of the cost model's
  hot-config ranking, each keyed on ``cache_key`` x ``n_chunks`` x a
  backend/jax-version fingerprint.
* :mod:`.compile` — HOW: ``jit(...).lower(args).compile()`` PLUS one
  executed call, through the SAME module-level jitted callables the
  dispatch layer calls.  The AOT compile performs the backend compile
  and writes JAX's persistent compilation cache (a restarted process
  replays disk hits in milliseconds instead of recompiling); the
  executed call primes the in-memory pjit cache — the only
  event-silent dispatch path, since jax's backend-compile monitoring
  event fires even on persistent-cache hits.  Together they are what
  lets the ``analysis/recompile.py`` zero-compile oracle hold on the
  first post-prewarm dispatch.
* :mod:`.manifest` — the atomic, versioned warm-set manifest (entry,
  cache_key, fingerprint, compile_wall_s, bytes) in the obs run-report
  envelope, with staleness detection: a fingerprint mismatch makes an
  entry invalid — listed and re-warmed, never silently reused.
* :mod:`.prewarm` — the process-start orchestration behind ``--prewarm``
  / ``SEQALIGN_PREWARM``: manifest replay + problem-derived warm set,
  wired into serve startup (so the steady-state recompile gate holds
  from tick 0) and the batch/``--resume`` path (so drain -> resume
  restarts rejoin warm).
"""

from __future__ import annotations
