"""The closing loop: refit the admission plane from measured load.

The serve plane prices admission in *modelled* superblock-wall seconds
(``analysis/costmodel`` at the i8 feed) — a calibrated-for-TPU prior
that can be orders of magnitude off the wall the deployment actually
achieves (different hardware, CPU fallback, interpreter overhead).  A
mispriced bucket admits hours of real work into a seconds budget and
the queue, not admission, absorbs the overload.  This module applies
the measure-model-refit discipline (the PR-3 chooser pattern; the HPX
collectives study's measurement-vs-model method) to that prior:

* **scale** — the per-launch gap rows the trace recorder already
  keeps (``gap_attribution.launches``: measured vs modelled wall per
  dispatched superblock) give the calibration directly:
  ``scale = total_measured / total_modelled``.  The static model stays
  the AUDITED PRIOR: the refit never edits it, it feeds the multiplier
  back through the env registry (``SEQALIGN_SERVE_COST_SCALE``) and
  reports drift beyond tolerance as a *finding* — the ranges-cert
  constant-drift pattern, where disagreement with the prior is itself
  the result;
* **budget** — measured queue-wait percentiles tune
  ``SEQALIGN_SERVE_COST_BUDGET_S`` toward a target wait: if admitted
  work queued ``p90_wait`` seconds against a ``target_wait_s`` SLO,
  the budget shrinks proportionally (clamped, prior-anchored), so the
  bucket — not the queue — becomes the backpressure surface.

Pure arithmetic over collected reports (role ``deterministic``);
``scripts/load_smoke.py`` demonstrates the loop end-to-end by
replaying the identical captured schedule under the refit knobs and
gating on the p99 queue-wait improving.
"""

from __future__ import annotations

import dataclasses

from ..obs.metrics import percentile

#: Refit multiplier clamp: beyond this the measurement itself is
#: suspect (a 10^7x drift is a broken trace, not a slow host).
SCALE_CLAMP = (1e-3, 1e7)

#: Budget refit clamp, as a fraction of the prior budget: the refit
#: may tighten hard but never to zero (that would reject everything)
#: nor loosen past 4x (that would un-ask the SLO question).
BUDGET_CLAMP = (0.05, 4.0)

#: Measured/prior drift beyond this factor (either direction) is a
#: finding: the audited prior no longer describes this deployment.
DRIFT_TOLERANCE = 2.0

#: Gap rows below this count refuse to refit (hold the prior): one
#: launch's wall is noise, not a calibration.
MIN_LAUNCHES = 3


@dataclasses.dataclass(frozen=True)
class RefitResult:
    """One refit's knobs, evidence, and findings."""

    prior_scale: float
    scale: float
    prior_budget_s: float
    budget_s: float
    launches: int
    measured_total_s: float
    modelled_total_s: float
    ratio_p50: float  # per-launch measured/modelled spread
    ratio_p90: float
    measured_p90_wait_s: float
    target_wait_s: float
    findings: tuple

    @property
    def drift(self) -> float:
        """Measured-over-prior calibration factor (1.0 = the prior was
        right)."""
        return self.scale / self.prior_scale if self.prior_scale else 0.0

    def env(self) -> dict:
        """The tuned knobs, as env-registry assignments for the next
        run (the feedback half of the loop)."""
        return {
            "SEQALIGN_SERVE_COST_SCALE": f"{self.scale:.6g}",
            "SEQALIGN_SERVE_COST_BUDGET_S": f"{self.budget_s:.6g}",
        }

    def delta_rows(self) -> list:
        """The measured-vs-prior delta report, one row per knob."""
        return [
            {
                "knob": "SEQALIGN_SERVE_COST_SCALE",
                "prior": self.prior_scale,
                "refit": round(self.scale, 6),
                "evidence": (
                    f"{self.launches} launch gap rows: measured "
                    f"{self.measured_total_s:.4f}s vs modelled "
                    f"{self.modelled_total_s:.6f}s (per-launch ratio "
                    f"p50 {self.ratio_p50:.1f}, p90 {self.ratio_p90:.1f})"
                ),
                "drift": round(self.drift, 6),
            },
            {
                "knob": "SEQALIGN_SERVE_COST_BUDGET_S",
                "prior": self.prior_budget_s,
                "refit": round(self.budget_s, 6),
                "evidence": (
                    f"measured p90 queue wait "
                    f"{self.measured_p90_wait_s:.4f}s vs target "
                    f"{self.target_wait_s:.4f}s"
                ),
                "drift": round(
                    self.budget_s / self.prior_budget_s, 6
                ) if self.prior_budget_s else 0.0,
            },
        ]


def _clamp(x: float, lo: float, hi: float) -> float:
    return min(hi, max(lo, x))


def refit(
    gap_attribution: dict | None,
    server_report: dict | None,
    *,
    prior_scale: float = 1.0,
    prior_budget_s: float,
    target_wait_s: float,
    tolerance: float = DRIFT_TOLERANCE,
    min_launches: int = MIN_LAUNCHES,
) -> RefitResult:
    """One measure-vs-prior pass; never raises on thin evidence — it
    holds the prior and says so in ``findings`` instead."""
    findings = []
    gap = gap_attribution or {}
    rows = [
        r for r in (gap.get("launches") or [])
        if isinstance(r, dict)
        and isinstance(r.get("measured_s"), (int, float))
        and isinstance(r.get("modelled_s"), (int, float))
        and r["modelled_s"] > 0.0
    ]
    measured = sum(r["measured_s"] for r in rows)
    modelled = sum(r["modelled_s"] for r in rows)
    ratios = [r["measured_s"] / r["modelled_s"] for r in rows]

    scale = float(prior_scale)
    if len(rows) < max(1, int(min_launches)) or modelled <= 0.0:
        findings.append(
            f"insufficient gap evidence ({len(rows)} priced launches, "
            f"want >= {min_launches}): holding the prior cost scale "
            f"{prior_scale:g}"
        )
    else:
        scale = _clamp(measured / modelled, *SCALE_CLAMP)
        drift = scale / float(prior_scale)
        if drift > tolerance or drift < 1.0 / tolerance:
            findings.append(
                f"cost-model drift: measured launch walls are "
                f"{drift:.1f}x the audited prior (tolerance "
                f"{tolerance:g}x) — the static model stays the prior; "
                f"refit scale {scale:.6g} feeds back via "
                f"SEQALIGN_SERVE_COST_SCALE"
            )

    hist = ((server_report or {}).get("histograms") or {}).get(
        "queue_wait_s"
    ) or {}
    p90_wait = float(hist.get("p90", 0.0))
    budget = float(prior_budget_s)
    if p90_wait > target_wait_s > 0.0:
        lo, hi = BUDGET_CLAMP
        budget = _clamp(
            prior_budget_s * target_wait_s / p90_wait,
            lo * prior_budget_s,
            hi * prior_budget_s,
        )
        ratio = budget / float(prior_budget_s)
        if ratio > tolerance or ratio < 1.0 / tolerance:
            findings.append(
                f"admission-budget drift: measured p90 queue wait "
                f"{p90_wait:.3f}s vs {target_wait_s:.3f}s target refits "
                f"the budget {ratio:.2f}x the prior "
                f"{prior_budget_s:g}s (tolerance {tolerance:g}x)"
            )

    return RefitResult(
        prior_scale=float(prior_scale),
        scale=scale,
        prior_budget_s=float(prior_budget_s),
        budget_s=budget,
        launches=len(rows),
        measured_total_s=round(measured, 9),
        modelled_total_s=round(modelled, 9),
        ratio_p50=round(percentile(ratios, 0.50), 6),
        ratio_p90=round(percentile(ratios, 0.90), 6),
        measured_p90_wait_s=p90_wait,
        target_wait_s=float(target_wait_s),
        findings=tuple(findings),
    )
