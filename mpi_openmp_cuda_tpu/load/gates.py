"""Machine-checked overload-survival gates.

Each gate returns a list of problem strings (empty = pass) so callers
aggregate everything wrong at once — the serve/fleet-chaos reporting
style.  Pure functions over already-collected data (role
``deterministic``): the driver and the run report measure, these judge.

The three promises, from the ISSUE:

1. **Answered-or-typed** (:func:`survival_problems`): at any offered
   rate, every request ends in a result or a *typed* rejection — a
   ``missing`` (silent drop) or ``reset`` (connection death) outcome is
   an overload-survival failure, full stop.
2. **Goodput holds** (:func:`survival_problems` with ``plateau_rps``):
   past saturation the server keeps completing at ≥
   ``min_goodput_frac`` of its pre-saturation plateau — overload may
   shed the excess, it may not collapse the core.
3. **Hysteresis contract** (:func:`transition_problems`): the shed
   machine steps through ``accept → shed-new → drain-only`` one state
   per transition, never teleports; breaker transitions follow
   ``closed → open → half-open → {closed | open}``.  Checked against
   the bus instants in the trace export (``kind="trace"``
   ``traceEvents``), i.e. against what the server actually published.
"""

from __future__ import annotations

from ..serve.slo import _SHED_ORDER


def survival_problems(
    result,
    *,
    phase: str,
    plateau_rps: float | None = None,
    min_goodput_frac: float = 0.8,
    require_typed_shed: bool = False,
) -> list:
    """Gates 1 + 2 over one :class:`~..load.driver.LoadResult`."""
    problems = []
    counts = result.counts()
    for kind, label in (
        ("missing", "silently dropped (no reply before grace deadline)"),
        ("reset", "lost to connection resets/errors"),
    ):
        bad = [o.id for o in result.outcomes if o.kind == kind]
        if bad:
            problems.append(
                f"{phase}: {counts[kind]} request(s) {label}: "
                f"{bad[:8]}{'...' if len(bad) > 8 else ''}"
            )
    for o in result.outcomes:
        if o.kind == "rejected" and o.retry_after_s is None:
            problems.append(
                f"{phase}: overloaded rejection for {o.id} lacks the "
                f"retry_after_s back-off hint"
            )
    if require_typed_shed and counts["rejected"] + counts["failed"] == 0:
        problems.append(
            f"{phase}: expected typed sheds at this offered rate, saw "
            f"none (did the overload phase actually overload?)"
        )
    if plateau_rps is not None and plateau_rps > 0:
        floor = min_goodput_frac * plateau_rps
        if result.goodput_rps < floor:
            problems.append(
                f"{phase}: goodput collapsed past saturation: "
                f"{result.goodput_rps:.2f} req/s < {min_goodput_frac:.0%} "
                f"of the {plateau_rps:.2f} req/s pre-saturation plateau"
            )
    return problems


def _bus_instants(trace_events, name: str) -> list:
    return [
        ev.get("args", {})
        for ev in trace_events
        if isinstance(ev, dict)
        and ev.get("ph") == "i"
        and ev.get("name") == name
    ]


def shed_sequence(trace_events) -> list:
    """The published shed-state sequence, in bus order."""
    return [
        str(args.get("state"))
        for args in _bus_instants(trace_events, "serve.shed.state")
    ]


def breaker_sequence(trace_events) -> list:
    """Published breaker transitions (``open``/``half_open``/``close``)."""
    out = []
    for ev in trace_events:
        if not isinstance(ev, dict) or ev.get("ph") != "i":
            continue
        name = str(ev.get("name", ""))
        if name.startswith("breaker."):
            out.append(name.split(".", 1)[1])
    return out


def transition_problems(trace_events) -> list:
    """Gate 3: every published shed transition moves exactly one step;
    every breaker transition is legal from its predecessor."""
    problems = []
    prev = _SHED_ORDER[0]  # the machine starts at accept
    for state in shed_sequence(trace_events):
        if state not in _SHED_ORDER:
            problems.append(f"shed sequence: unknown state {state!r}")
            continue
        step = abs(_SHED_ORDER.index(state) - _SHED_ORDER.index(prev))
        if step != 1:
            problems.append(
                f"shed sequence: illegal transition {prev!r} -> {state!r} "
                f"({step} steps; the hysteresis contract is one per tick)"
            )
        prev = state
    bstate = "closed"
    legal = {
        "closed": {"open"},
        "open": {"half_open"},
        "half_open": {"close", "open"},
    }
    for what in breaker_sequence(trace_events):
        if what not in legal.get(bstate, set()):
            problems.append(
                f"breaker sequence: illegal transition {bstate!r} -> "
                f"{what!r}"
            )
            break
        bstate = "closed" if what == "close" else what
    return problems
