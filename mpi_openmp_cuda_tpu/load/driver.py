"""The open-loop client driver: schedules onto sockets, replies into
typed outcomes.

This is the load plane's ONE wall-clock module (role ``host``): it
paces a prebuilt schedule onto real ndjson connections with
``time.monotonic`` and classifies what comes back.  Open-loop means the
pacing never waits for the server — a request is sent at its scheduled
offset whether or not earlier requests have been answered, which is
exactly how production traffic behaves and exactly what closed-loop
smokes cannot test.

Concurrency model: ``clients`` connections, schedule entries assigned
round-robin; each connection runs one writer thread (paced sends) and
one reader thread (terminal-record collection).  Threads share nothing
across connections and the per-connection state is joined before
anyone reads it, so the driver needs no locks — and adds nothing to
the lockgraph inventory.

Every scheduled request ends in exactly one typed
:class:`Outcome`:

``done``      the full result streamed and the ``done`` record landed;
``rejected``  a typed ``overloaded`` rejection (the admission plane's
              shed path, ``retry_after_s`` captured);
``failed``    any other typed ``{"id", "error"}`` reply (deadline,
              queue full, invalid, draining — answered, just not
              scored);
``missing``   no terminal record before the grace deadline — a SILENT
              DROP, which the survival gates treat as fatal;
``reset``     the connection died under us (ECONNRESET, timeout,
              refused) — equally fatal to the gates.
"""

from __future__ import annotations

import dataclasses
import json
import socket
import threading
import time


@dataclasses.dataclass
class Outcome:
    """One scheduled request's classified fate."""

    id: str
    kind: str  # done | rejected | failed | missing | reset
    error: str | None = None
    retry_after_s: float | None = None
    latency_s: float | None = None
    sent_t_s: float | None = None  # measured send offset from drive t0
    lines: int = 0  # streamed result rows seen before the terminal

    @property
    def answered(self) -> bool:
        """Did the server hold its one promise: a result or a TYPED
        rejection (never silence, never a reset)?"""
        return self.kind in ("done", "rejected", "failed")


@dataclasses.dataclass
class LoadResult:
    """One drive's classified outcomes + measured envelope."""

    outcomes: list
    offered: int  # scheduled requests
    duration_s: float  # first send -> last terminal (wall)
    send_span_s: float  # first send -> last send (wall)

    def counts(self) -> dict:
        c = {"done": 0, "rejected": 0, "failed": 0, "missing": 0, "reset": 0}
        for o in self.outcomes:
            c[o.kind] = c.get(o.kind, 0) + 1
        return c

    @property
    def goodput_rps(self) -> float:
        done = sum(1 for o in self.outcomes if o.kind == "done")
        return done / self.duration_s if self.duration_s > 0 else 0.0

    def latencies_s(self, *, kind: str = "done") -> list:
        return [
            o.latency_s
            for o in self.outcomes
            if o.kind == kind and o.latency_s is not None
        ]


class _Client:
    """One connection's writer+reader pair; owns all its own state."""

    def __init__(self, host, port, entries, timeout_s):
        self.host = host
        self.port = int(port)
        self.entries = entries  # [(offset_s, raw)]
        self.timeout_s = timeout_s
        self.sent: dict = {}  # id -> monotonic send time
        self.sent_offsets: dict = {}  # id -> offset from drive t0
        self.terminal: dict = {}  # id -> (record, monotonic recv time)
        self.lines: dict = {}  # id -> streamed row count
        self.dead: str | None = None  # socket-level failure, if any
        self._sock = None
        self._reader = None
        self.last_terminal_t = 0.0

    def _read_loop(self, rfile):
        try:
            for line in rfile:
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                rid = rec.get("id")
                if rid is None:
                    continue
                rid = str(rid)
                if (
                    rec.get("done")
                    or rec.get("error") is not None
                    or rec.get("duplicate")
                ):
                    t = time.monotonic()
                    self.terminal.setdefault(rid, (rec, t))
                    self.last_terminal_t = max(self.last_terminal_t, t)
                else:
                    self.lines[rid] = self.lines.get(rid, 0) + 1
        except (OSError, ValueError):
            # advisory: socket death is classified from the writer side
            # (self.dead) and by missing terminals — the reader just
            # stops.
            pass

    def run(self, t0: float) -> None:
        try:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout_s
            )
            self._sock.settimeout(self.timeout_s)
            rfile = self._sock.makefile("r", encoding="utf-8")
        except OSError as e:
            self.dead = f"connect: {e}"
            return
        self._reader = threading.Thread(
            target=self._read_loop, args=(rfile,), daemon=True
        )
        self._reader.start()
        try:
            for offset, raw in self.entries:
                delay = (t0 + offset) - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                payload = (json.dumps(raw) + "\n").encode("utf-8")
                self._sock.sendall(payload)
                now = time.monotonic()
                rid = str(raw.get("id"))
                self.sent[rid] = now
                self.sent_offsets[rid] = now - t0
        except OSError as e:
            self.dead = f"send: {e}"

    def await_terminals(self, deadline: float) -> None:
        """Block (bounded) until every sent id has a terminal record."""
        while time.monotonic() < deadline:
            if all(rid in self.terminal for rid in self.sent):
                break
            time.sleep(0.02)

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        if self._reader is not None:
            self._reader.join(timeout=2.0)


def _classify(raw, client) -> Outcome:
    rid = str(raw.get("id"))
    sent_t = client.sent.get(rid)
    out = Outcome(
        id=rid,
        kind="missing",
        sent_t_s=client.sent_offsets.get(rid),
        lines=client.lines.get(rid, 0),
    )
    term = client.terminal.get(rid)
    if term is not None:
        rec, recv_t = term
        if sent_t is not None:
            out.latency_s = max(0.0, recv_t - sent_t)
        err = rec.get("error")
        if rec.get("done") or rec.get("duplicate"):
            out.kind = "done"
        elif err == "overloaded":
            out.kind = "rejected"
            out.error = str(err)
            ra = rec.get("retry_after_s")
            if isinstance(ra, (int, float)):
                out.retry_after_s = float(ra)
        elif isinstance(err, str):
            out.kind = "failed"
            out.error = err
        return out
    if client.dead is not None:
        out.kind = "reset"
        out.error = client.dead
    elif sent_t is None:
        # Never sent and the socket is healthy: the drive gave up
        # before this offset — still a reset for gate purposes (the
        # harness, not the server, must explain it).
        out.kind = "reset"
        out.error = "never sent"
    return out


def drive(
    host: str,
    port: int,
    schedule,
    *,
    clients: int = 32,
    grace_s: float = 30.0,
    timeout_s: float = 30.0,
) -> LoadResult:
    """Replay ``schedule`` open-loop over ``clients`` connections and
    classify every request.  Returns when every request has a terminal
    record or the grace deadline past the last scheduled send expires.
    """
    schedule = list(schedule)
    n_clients = max(1, min(int(clients), max(1, len(schedule))))
    pools: list[list] = [[] for _ in range(n_clients)]
    for i, entry in enumerate(schedule):
        pools[i % n_clients].append(entry)
    conns = [
        _Client(host, port, pool, timeout_s) for pool in pools if pool
    ]
    t0 = time.monotonic() + 0.05  # small runway so client 0 isn't late
    writers = [
        threading.Thread(target=c.run, args=(t0,), daemon=True)
        for c in conns
    ]
    for w in writers:
        w.start()
    for w in writers:
        w.join()
    last_offset = schedule[-1][0] if schedule else 0.0
    deadline = t0 + last_offset + float(grace_s)
    for c in conns:
        c.await_terminals(deadline)
    for c in conns:
        c.close()

    by_id = {}
    for c in conns:
        for _, raw in c.entries:
            by_id[str(raw.get("id"))] = _classify(raw, c)
    outcomes = [by_id[str(raw.get("id"))] for _, raw in schedule]

    send_times = [t for c in conns for t in c.sent.values()]
    term_times = [
        c.last_terminal_t for c in conns if c.last_terminal_t > 0.0
    ]
    first_send = min(send_times) if send_times else t0
    last_event = max(term_times) if term_times else first_send
    send_span = (max(send_times) - first_send) if send_times else 0.0
    return LoadResult(
        outcomes=outcomes,
        offered=len(schedule),
        duration_s=max(1e-9, last_event - first_send),
        send_span_s=max(0.0, send_span),
    )
