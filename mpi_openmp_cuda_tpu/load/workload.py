"""Seeded request synthesis: WHAT each arrival carries.

Production traffic is diverse along exactly the axes the serve plane
batches, prices, and deadline-checks on, so the generator controls each
one explicitly:

* **length mix** — seq2 lengths drawn from weighted ``(lo, hi)``
  buckets: the length-bucket batcher and the cost model both key on
  these, so the mix decides batch-fill and admission pressure;
* **problem-key diversity** — distinct ``(weights, seq1)`` combos: each
  is a separate scoring problem (and a separate superblock group), so
  diversity decides how much coalescing the batcher can do;
* **deadline mix** — the fraction of requests carrying ``deadline_s``:
  under overload these convert queue waits into typed deadline misses,
  the SLO surface the record reports on.

Same seed → byte-identical requests (seqlint SEQ005, role
``deterministic``): ids are sequential, sequences come from one
``random.Random(seed)``, and nothing reads a clock.
"""

from __future__ import annotations

import random

_ALPHABET = "ACGT"

#: Default seq2 length mix: mostly short interactive-sized queries with
#: a heavier tail — the shape that makes cost-aware admission matter
#: (a depth cap would starve the tail or admit hours of it).
DEFAULT_LEN_MIX = ((4, 24, 0.7), (24, 96, 0.25), (96, 256, 0.05))

#: Weight tables the problem keys cycle through (match/mismatch/gap
#: open/gap extend, the reference's parameter shape).
_WEIGHT_TABLES = (
    [1, -3, -5, -2],
    [2, -1, -3, -1],
    [1, -2, -2, -1],
    [3, -2, -4, -2],
)


def _seq(rng: random.Random, length: int) -> str:
    return "".join(rng.choice(_ALPHABET) for _ in range(length))


def synth_requests(
    n: int,
    *,
    seed: int,
    problem_keys: int = 2,
    len_mix: tuple = DEFAULT_LEN_MIX,
    pairs_per_request: tuple[int, int] = (1, 2),
    seq1_len: int = 64,
    deadline_mix: float = 0.0,
    deadline_s: float = 30.0,
    id_prefix: str = "q",
) -> list[dict]:
    """``n`` raw ndjson request dicts, deterministically from ``seed``.

    ``problem_keys`` distinct (weights, seq1) combos are synthesised
    first, then each request picks one round-robin (so diversity is
    exact, not stochastic); seq2 count and lengths, and whether the
    request carries a deadline, come from the seeded RNG.
    """
    n = int(n)
    if n < 0:
        raise ValueError(f"request count must be >= 0, got {n}")
    keys = max(1, int(problem_keys))
    lo_pairs, hi_pairs = (
        max(1, int(pairs_per_request[0])),
        max(1, int(pairs_per_request[1])),
    )
    if hi_pairs < lo_pairs:
        raise ValueError(
            f"pairs_per_request range is inverted: {pairs_per_request}"
        )
    frac = float(deadline_mix)
    if not 0.0 <= frac <= 1.0:
        raise ValueError(f"deadline_mix must be in [0, 1], got {deadline_mix}")
    buckets = [(int(lo), int(hi), float(w)) for lo, hi, w in len_mix]
    if not buckets or any(
        lo <= 0 or hi < lo or w <= 0 for lo, hi, w in buckets
    ):
        raise ValueError(f"bad len_mix {len_mix!r}: want (lo, hi, weight>0)")
    weights = [w for _, _, w in buckets]

    rng = random.Random(int(seed))
    problems = [
        {
            "weights": list(_WEIGHT_TABLES[k % len(_WEIGHT_TABLES)]),
            "seq1": _seq(rng, max(1, int(seq1_len))),
        }
        for k in range(keys)
    ]
    out = []
    for i in range(n):
        prob = problems[i % keys]
        lo, hi, _ = rng.choices(buckets, weights=weights)[0]
        raw = {
            "id": f"{id_prefix}{i:05d}",
            "weights": list(prob["weights"]),
            "seq1": prob["seq1"],
            "seq2": [
                _seq(rng, rng.randint(lo, hi))
                for _ in range(rng.randint(lo_pairs, hi_pairs))
            ],
        }
        if frac > 0.0 and rng.random() < frac:
            raw["deadline_s"] = float(deadline_s)
        out.append(raw)
    return out
