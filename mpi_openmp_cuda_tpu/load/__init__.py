"""Load plane: deterministic open-loop traffic against the serve plane
(ROADMAP Open item 4; docs/ARCHITECTURE.md §12.10).

The serve plane's overload defences — cost-aware admission, shed
hysteresis, deadlines, breakers, fleet redispatch — all predate this
package, but every smoke that exercised them was *closed-loop*: clients
waited for replies before sending more, so the offered rate politely
collapsed to whatever the server could absorb and the defences were
never driven past their knees.  Production traffic does not wait.  This
package generates the open-loop regime — an arrival schedule fixed
BEFORE the run, replayed against the wire no matter how the server
responds — and closes the measure-model-refit loop on the admission
plane the same way PR 3 closed it on the kernel chooser:

* :mod:`.arrival` — seeded arrival-time schedules (constant / poisson /
  burst / ramp); pure arithmetic over an injected seed, never
  wall-clock (seqlint SEQ005, role ``deterministic``);
* :mod:`.workload` — seeded request synthesis: seq2 length mix,
  problem-key diversity (distinct weights+seq1 compile keys), deadline
  mix;
* :mod:`.replay` — request-trace record/replay at k× speed: a captured
  schedule is a JSONL artifact, and re-running it is the controlled
  A/B the refit loop needs;
* :mod:`.driver` — the only wall-clock module: hundreds of concurrent
  ndjson socket clients paced to the schedule (open-loop: a slow
  server changes nothing about send times), every request classified
  into a typed outcome;
* :mod:`.gates` — machine-checked overload-survival gates: every
  request answered or typed-rejected (no silent drops, no resets),
  goodput retention past saturation, shed/breaker transition sequences
  legal under the PR-9 hysteresis contract;
* :mod:`.report` — the official ``formulation="serve-load"`` bench
  record in the obs run-report envelope;
* :mod:`.refit` — the closing loop: refit ``RequestCostModel`` scale
  and the admission budget from measured launch gap rows (obs/trace)
  and queue-wait percentiles, static model as the audited prior, drift
  beyond tolerance reported as a finding, tuned knobs fed back through
  the env registry (``SEQALIGN_SERVE_COST_SCALE``,
  ``SEQALIGN_SERVE_COST_BUDGET_S``).

``scripts/load_smoke.py`` (``make load-smoke``) drives the whole loop:
calibrate the pre-saturation plateau, hold 2× and 5× saturation,
enforce the survival gates, emit the serve-load record, refit, replay
the same trace with the refit knobs, and require the p99 queue-wait to
improve.  The package is pure library + stdlib (no jax import), so the
generator can price and schedule without touching the accelerator.
"""
