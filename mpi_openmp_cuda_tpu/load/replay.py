"""Request-trace record/replay: a captured schedule as an artifact.

A *schedule* is the load plane's unit of reproducibility: a list of
``(send_offset_s, raw_request)`` pairs, offsets sorted ascending.  The
generator builds one (:func:`build_schedule`), the driver replays one,
and this module round-trips one through a JSONL file — so "re-run the
same traffic with different knobs" is a file replay, not a hope that
two seeded runs stayed in sync.  :func:`scale_schedule` replays a
capture at k× speed (k>1 compresses the gaps: 2× the arrival rate from
the identical request bodies — the saturation dial for refit A/Bs).

File format (one JSON object per line, schema guarded on load)::

    {"t_s": 0.125, "raw": {"id": "q00003", "weights": [...], ...}}

Deterministic module (seqlint SEQ005): offsets come in from the
schedule, never from a clock.
"""

from __future__ import annotations

import json

Schedule = list  # list[tuple[float, dict]]


def build_schedule(times: list[float], requests: list[dict]) -> Schedule:
    """Zip arrival offsets onto request bodies (lengths must match)."""
    if len(times) != len(requests):
        raise ValueError(
            f"schedule shape mismatch: {len(times)} arrival times vs "
            f"{len(requests)} requests"
        )
    sched = sorted(
        ((float(t), raw) for t, raw in zip(times, requests)),
        key=lambda p: p[0],
    )
    if sched and sched[0][0] < 0.0:
        raise ValueError(
            f"arrival offsets must be >= 0, got {sched[0][0]}"
        )
    return sched


def scale_schedule(schedule: Schedule, k: float) -> Schedule:
    """The same requests at k× speed: offsets divided by ``k`` (k=2
    doubles the offered rate; k=0.5 halves it)."""
    k = float(k)
    if k <= 0.0:
        raise ValueError(f"replay speed k must be > 0, got {k}")
    return [(t / k, raw) for t, raw in schedule]


def save_schedule(path: str, schedule: Schedule) -> None:
    """One request per line, offsets first — diff-able and grep-able."""
    with open(path, "w", encoding="utf-8") as fh:
        for t, raw in schedule:
            fh.write(
                json.dumps({"t_s": round(float(t), 9), "raw": raw}) + "\n"
            )


def load_schedule(path: str) -> Schedule:
    """Load + validate a captured schedule; raises ValueError naming the
    first bad line so a torn capture cannot silently replay as a
    shorter run."""
    sched: Schedule = []
    with open(path, encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(
                    f"{path}:{lineno}: not JSON ({e.msg})"
                ) from None
            t = row.get("t_s") if isinstance(row, dict) else None
            raw = row.get("raw") if isinstance(row, dict) else None
            if not isinstance(t, (int, float)) or t < 0 or not isinstance(
                raw, dict
            ):
                raise ValueError(
                    f"{path}:{lineno}: want {{'t_s': <seconds>=0>, "
                    f"'raw': {{...}}}}, got {line[:120]!r}"
                )
            sched.append((float(t), raw))
    sched.sort(key=lambda p: p[0])
    return sched
