"""The official ``formulation="serve-load"`` bench record.

Kernel bench records (``bench.py``) carry ``formulation="batch"``-style
throughput rows; this module gives serve robustness the same citizen
status in the BENCH_r* trajectory: one wrapped ``kind="bench"`` record
whose headline value is GOODPUT (completed requests per second under a
known open-loop offered rate), with the SLO surface — latency and
queue-wait percentiles, shed/deadline-miss rates, batch fill, breaker
and fleet transition counts — riding alongside.  The record marries
the two measurement sides:

* client-side truth from the driver's :class:`~.driver.LoadResult`
  (what the wire actually delivered, classified);
* server-side truth from the ``--metrics-out`` run report (queue-wait
  histograms, fill gauge, transition counters — what the serve plane
  believes it did).

``validate_report`` (obs/metrics.py) enforces the serve-load field
contract whenever ``formulation == "serve-load"``, so a malformed
record fails schema validation exactly like a malformed run report.

Percentiles here are :func:`obs.metrics.percentile` — the ONE rank
implementation the shed machine and the report histograms already
share, so client latency, server queue-wait, and shed thresholds are
directly comparable numbers.
"""

from __future__ import annotations

from ..obs.metrics import percentile, wrap_report


def _pctls(samples) -> dict:
    xs = [float(x) for x in samples]
    return {
        "p50": round(percentile(xs, 0.50), 6),
        "p90": round(percentile(xs, 0.90), 6),
        "p99": round(percentile(xs, 0.99), 6),
    }


def _report_pctls(server_report: dict | None, name: str) -> dict:
    hist = ((server_report or {}).get("histograms") or {}).get(name) or {}
    return {
        "p50": float(hist.get("p50", 0.0)),
        "p90": float(hist.get("p90", 0.0)),
        "p99": float(hist.get("p99", 0.0)),
    }


def serve_load_record(
    result,
    server_report: dict | None,
    *,
    process: str,
    rate_rps: float,
    seed: int,
    clients: int,
    speedup_k: float = 1.0,
    plateau_rps: float | None = None,
    meta: dict | None = None,
) -> dict:
    """Assemble + wrap one serve-load bench record (validate with
    :func:`obs.metrics.validate_report` like every other envelope)."""
    counts = result.counts()
    offered = max(1, result.offered)
    counters = (server_report or {}).get("counters") or {}
    gauges = (server_report or {}).get("gauges") or {}
    deadline_failed = sum(
        1 for o in result.outcomes if o.kind == "failed"
        and o.error == "deadline"
    )
    goodput = round(result.goodput_rps, 6)
    body = {
        "metric": (
            f"serve goodput, open-loop {process} @ {rate_rps:.1f} req/s"
        ),
        "value": goodput,
        "unit": "req/s",
        "formulation": "serve-load",
        "arrival": {
            "process": str(process),
            "rate_rps": round(float(rate_rps), 6),
            "seed": int(seed),
            "speedup_k": round(float(speedup_k), 6),
            "clients": int(clients),
        },
        "offered_rps": round(
            offered / result.send_span_s, 6
        ) if result.send_span_s > 0 else round(float(rate_rps), 6),
        "duration_s": round(result.duration_s, 6),
        "requests": {
            "offered": offered,
            "done": counts["done"],
            "rejected": counts["rejected"],
            "failed": counts["failed"],
            "missing": counts["missing"],
            "reset": counts["reset"],
        },
        "goodput_rps": goodput,
        "latency_s": _pctls(result.latencies_s()),
        "queue_wait_s": _report_pctls(server_report, "queue_wait_s"),
        "shed_rate": round(
            (counts["rejected"] + counts["failed"]) / offered, 6
        ),
        "deadline_miss_rate": round(deadline_failed / offered, 6),
        "batch_fill_ratio": float(gauges.get("batch_fill_ratio", 0.0)),
        "shed_transitions": int(counters.get("serve_shed_transitions", 0)),
        "breaker": {
            "opens": int(counters.get("breaker_opens", 0)),
            "half_opens": int(counters.get("breaker_half_opens", 0)),
            "closes": int(counters.get("breaker_closes", 0)),
        },
        "fleet": {
            "redispatches": int(counters.get("fleet_redispatches", 0)),
            "deaths": int(counters.get("fleet_deaths", 0)),
        },
    }
    if plateau_rps is not None and plateau_rps > 0:
        body["plateau_rps"] = round(float(plateau_rps), 6)
        body["goodput_retention"] = round(goodput / float(plateau_rps), 6)
    return wrap_report("bench", body, meta=meta)
