"""Open-loop arrival schedules: WHEN each request hits the wire.

A schedule is a sorted list of non-negative send offsets (seconds from
the run's t0).  It is computed entirely up front from a seeded RNG —
the defining property of open-loop load: the server's behaviour cannot
slow the arrivals down, because the arrivals were decided before the
server saw anything.  No wall-clock reads here (seqlint SEQ005, role
``deterministic``); the driver owns the one wall-clock loop that paces
these offsets onto real sockets.

Four processes, selected by name through :func:`arrival_times`:

``constant``   evenly spaced at the target rate — the baseline shape;
``poisson``    exponential inter-arrival gaps (memoryless arrivals, the
               classic open-loop model) at the same mean rate;
``burst``      groups of ``burst_size`` requests land simultaneously,
               groups spaced so the AVERAGE rate holds — the shape that
               stresses admission hysteresis hardest;
``ramp``       rate climbs linearly from ``ramp_from_rps`` to the
               target across the schedule — the shape that finds the
               saturation knee.
"""

from __future__ import annotations

import random

PROCESSES = ("constant", "poisson", "burst", "ramp")


def _validated(n: int, rate_rps: float) -> tuple[int, float]:
    n = int(n)
    rate = float(rate_rps)
    if n < 0:
        raise ValueError(f"arrival count must be >= 0, got {n}")
    if rate <= 0.0:
        raise ValueError(f"arrival rate_rps must be > 0, got {rate_rps}")
    return n, rate


def constant_times(n: int, rate_rps: float) -> list[float]:
    n, rate = _validated(n, rate_rps)
    return [i / rate for i in range(n)]


def poisson_times(n: int, rate_rps: float, *, seed: int) -> list[float]:
    n, rate = _validated(n, rate_rps)
    rng = random.Random(int(seed))
    t = 0.0
    out = []
    for _ in range(n):
        t += rng.expovariate(rate)
        out.append(t)
    return out


def burst_times(
    n: int, rate_rps: float, *, burst_size: int = 8
) -> list[float]:
    n, rate = _validated(n, rate_rps)
    size = max(1, int(burst_size))
    gap = size / rate  # group spacing preserving the average rate
    return [(i // size) * gap for i in range(n)]


def ramp_times(
    n: int, rate_rps: float, *, ramp_from_rps: float | None = None
) -> list[float]:
    n, rate = _validated(n, rate_rps)
    r0 = float(ramp_from_rps) if ramp_from_rps is not None else rate / 4.0
    if r0 <= 0.0:
        raise ValueError(f"ramp_from_rps must be > 0, got {ramp_from_rps}")
    t = 0.0
    out = []
    for i in range(n):
        out.append(t)
        frac = i / max(1, n - 1)
        t += 1.0 / (r0 + (rate - r0) * frac)
    return out


def arrival_times(
    process: str,
    n: int,
    rate_rps: float,
    *,
    seed: int = 0,
    burst_size: int = 8,
    ramp_from_rps: float | None = None,
) -> list[float]:
    """One schedule by process name; same inputs → same offsets, on
    every host, every run."""
    if process == "constant":
        return constant_times(n, rate_rps)
    if process == "poisson":
        return poisson_times(n, rate_rps, seed=seed)
    if process == "burst":
        return burst_times(n, rate_rps, burst_size=burst_size)
    if process == "ramp":
        return ramp_times(n, rate_rps, ramp_from_rps=ramp_from_rps)
    raise ValueError(
        f"unknown arrival process {process!r}: want one of "
        f"{', '.join(PROCESSES)}"
    )
