"""Canonical synthetic workloads — the deterministic problem factories
shared by the bench harness and the static schedule auditor.

``bench.load_workload`` historically built its synthetic input3-class
fallback inline, which made the workload unreachable from the analysis
layer without importing the bench script (and its timing machinery).
The factory lives here so that:

* ``bench.py`` keeps its exact fallback semantics (same rng stream,
  same sizes, same weights — goldens unchanged), and
* ``scripts/schedule_audit.py`` / ``analysis.costmodel`` can price the
  SAME composed bucketed schedule on any machine, with or without the
  reference input tree mounted, and pin the result against a committed
  golden.  The audit always uses this synthetic problem (never
  ``BENCH_INPUT``) so the golden is environment-independent.
"""

from __future__ import annotations

import numpy as np

#: The input3-class synthetic workload's shape: one ~1.5k Seq1 against
#: 32 Seq2s spanning the bucketed schedule's length range.  Mirrors
#: /root/reference/input3.txt closely enough that the production
#: schedule exercises the same bucket/chunk machinery.
INPUT3_CLASS_SEED = 3
INPUT3_CLASS_LEN1 = 1489
INPUT3_CLASS_N_SEQ2 = 32
INPUT3_CLASS_LEN2_RANGE = (56, 1153)
INPUT3_CLASS_WEIGHTS = (2, 2, 1, 10)
INPUT3_CLASS_NAME = "synthetic-input3-class"


def input3_class_problem():
    """The deterministic input3-class synthetic :class:`~..io.parse.Problem`
    (uppercase sequences from ``default_rng(3)``, weights [2, 2, 1, 10]).

    Byte-for-byte the problem ``bench.load_workload`` falls back to when
    the reference tree is absent — the two call sites MUST stay one
    derivation, or the schedule-audit golden and the bench measurement
    silently describe different schedules.
    """
    from ..io.parse import Problem
    from .encoding import decode, encode_normalized

    rng = np.random.default_rng(INPUT3_CLASS_SEED)
    lo, hi = INPUT3_CLASS_LEN2_RANGE
    seq1 = decode(rng.integers(1, 27, size=INPUT3_CLASS_LEN1))
    lens2 = [int(x) for x in rng.integers(lo, hi, size=INPUT3_CLASS_N_SEQ2)]
    seqs = [decode(rng.integers(1, 27, size=l)) for l in lens2]
    return Problem(
        weights=list(INPUT3_CLASS_WEIGHTS),
        seq1=seq1,
        seq2=seqs,
        seq1_codes=encode_normalized(seq1),
        seq2_codes=[encode_normalized(s) for s in seqs],
    )
