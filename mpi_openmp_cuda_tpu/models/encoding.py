"""Sequence text <-> integer-code encoding (part of reference C5's job).

The reference uppercases input in-place with OpenMP loops (`main.c:82-96`)
and keeps sequences as C strings.  The TPU build normalises once on the host
and encodes to small integer codes: 0 = pad (reserved, like the reference's
unused matrix index 0, `main.c:38`), 1..26 = 'A'..'Z'.  Codes index directly
into the 27x27 class matrix.
"""

from __future__ import annotations

import numpy as np

from ..utils.constants import PAD_CODE


class InvalidSequenceError(ValueError):
    """Raised when a sequence contains characters outside A-Z after uppercasing."""


def normalize(text: str) -> str:
    """Uppercase a raw sequence string (the OpenMP-parallel-for's job)."""
    return text.strip().upper()


def encode(seq: str) -> np.ndarray:
    """Encode an (already normalised) A-Z string to int8 codes 1..26."""
    try:
        raw = seq.encode("ascii", errors="strict")
    except UnicodeEncodeError as e:
        raise InvalidSequenceError(
            f"invalid sequence character {seq[e.start]!r}; expected A-Z"
        ) from e
    buf = np.frombuffer(raw, dtype=np.uint8)
    codes = buf.astype(np.int8) - (ord("A") - 1)
    if codes.size and (codes.min() < 1 or codes.max() > 26):
        bad = seq[int(np.argmax((codes < 1) | (codes > 26)))]
        raise InvalidSequenceError(f"invalid sequence character {bad!r}; expected A-Z")
    return codes


def encode_normalized(text: str) -> np.ndarray:
    """normalize + encode in one step."""
    return encode(normalize(text))


def decode(codes: np.ndarray) -> str:
    """Inverse of encode (pads are dropped)."""
    codes = np.asarray(codes)
    codes = codes[codes != PAD_CODE]
    return bytes((codes + (ord("A") - 1)).astype(np.uint8)).decode("ascii")


def pad_to(codes: np.ndarray, length: int) -> np.ndarray:
    """Right-pad a code vector with PAD_CODE to a fixed length."""
    if codes.size > length:
        raise InvalidSequenceError(
            f"sequence length {codes.size} exceeds buffer size {length}"
        )
    out = np.full(length, PAD_CODE, dtype=np.int8)
    out[: codes.size] = codes
    return out
