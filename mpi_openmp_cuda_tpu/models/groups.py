"""Amino-acid substitution groups (reference parity: C3).

The spec (parallel_finalEx2021_summer.pdf p.1-2) defines 9 conservative and
11 semi-conservative amino-acid groups; the reference hard-codes them as two
string arrays (`main.c:59-60`).  Two characters in the same conservative
group classify as '%'; in the same semi-conservative group (and not
conservative / identical) as '#'.
"""

from __future__ import annotations

CONSERVATIVE_GROUPS: tuple[str, ...] = (
    "NDEQ",
    "NEQK",
    "STA",
    "MILV",
    "QHRK",
    "NHQK",
    "FYW",
    "HY",
    "MILF",
)

SEMI_CONSERVATIVE_GROUPS: tuple[str, ...] = (
    "SAG",
    "ATV",
    "CSA",
    "SGND",
    "STPA",
    "STNK",
    "NEQHRK",
    "NDEQHK",
    "SNDEQK",
    "HFY",
    "FVLIM",
)
