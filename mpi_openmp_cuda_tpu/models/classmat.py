"""Pair-classification matrix builder (reference parity: C4).

The reference flattens group membership into two 27x27 0/1 lookup matrices
(`build_mat`, main.c:14-44 — buggily, see SURVEY B1) and tests them in
precedence order inside the kernel (cudaFunctions.cu:88-95).  The TPU build
collapses both matrices and the precedence chain into ONE dense int8 27x27
matrix of class ids (0='$', 1='%', 2='#', 3=space), built host-side once and
replicated to devices — the `__constant__`-memory analogue (C10).

Index 0 of both axes is reserved for pad/hyphen (main.c:38 "do not use
index 0"); its class is irrelevant because pad positions are masked to a
zero score contribution before any reduction.
"""

from __future__ import annotations

import functools

import numpy as np

from ..utils.constants import (
    ALPHABET_SIZE,
    CLASS_DOLLAR,
    CLASS_HASH,
    CLASS_PERCENT,
    CLASS_SPACE,
)
from .groups import CONSERVATIVE_GROUPS, SEMI_CONSERVATIVE_GROUPS


def _code(ch: str) -> int:
    return ord(ch) - ord("A") + 1


@functools.cache
def build_class_matrix() -> np.ndarray:
    """Dense [27, 27] int8 matrix of class ids with '$'>'%'>'#'>space precedence.

    Cached: the matrix is a pure function of the hard-coded spec group tables.
    Returned array is read-only to keep the cache safe.
    """
    mat = np.full((ALPHABET_SIZE, ALPHABET_SIZE), CLASS_SPACE, dtype=np.int8)
    # Lowest precedence first so later writes implement the precedence chain.
    for group in SEMI_CONSERVATIVE_GROUPS:
        codes = [_code(c) for c in group]
        for a in codes:
            for b in codes:
                mat[a, b] = CLASS_HASH
    for group in CONSERVATIVE_GROUPS:
        codes = [_code(c) for c in group]
        for a in codes:
            for b in codes:
                mat[a, b] = CLASS_PERCENT
    for a in range(1, ALPHABET_SIZE):
        mat[a, a] = CLASS_DOLLAR
    mat.setflags(write=False)
    return mat


def classify_pair(a: str, b: str) -> int:
    """Class id for a single uppercase character pair (unit-test helper)."""
    return int(build_class_matrix()[_code(a), _code(b)])
