"""MXU-formulated XLA scorer: gather-free diagonal prefix sums.

The first XLA formulation (xla_scorer.py) indexes the 27x27 value table and
seq1 with large gathers, which TPUs execute poorly (the bench showed the
host numpy oracle outrunning it).  This formulation maps the same math onto
the hardware's strengths:

* **Value matrix via one-hot matmul (MXU).**  ``V[i, j] = val[seq2[i],
  seq1[j]]`` becomes ``onehot(seq2) @ (val @ onehot(seq1).T)`` — the
  ``[27, W]`` right factor is shared by the whole batch, so each pair costs
  one ``[L2P, 27] x [27, W]`` matmul.  Integer values < 2^24 are exact in
  float32 *accumulation*, but TPU MXUs MULTIPLY f32 at bf16 precision by
  default (single pass), which silently rounds values above 2^8 — every
  f32 matmul here therefore runs ``Precision.HIGHEST`` (multi-pass bf16),
  exact for these operands because one side is always 0/1 and the other's
  values fit 16 mantissa bits (the live operand is the delta
  |d0 - d1| <= 2 * max|v|, and :func:`max_exact_value` caps max|v| at
  32767).  The exactness ceiling is LENGTH-AWARE (r6): a prefix over at
  most L2P live rows bounds every partial by 2 * L2P * max|v| < 2^24, so
  short-Seq2 buckets keep the exact path for weights far past the static
  4095 cap; the dispatch layer falls back to the gather path only for
  weights that could overflow at the batch's actual L2P.
* **Diagonal shear via pad+reshape (zero data movement).**  Appending one
  zero column's worth of padding to ``V``'s flat buffer and re-viewing it
  with row stride W+1 shifts row i left by i: ``D[i, n] = V[i, i+n]`` —
  the diagonal family — with NO gather (wrap garbage lands only in cells
  the (n, k) validity mask kills anyway).
* **Prefix sums on the VPU; argmax as reductions.**  ``score(n, k) =
  prefix0[k] + total1 - prefix1[k]``; the best candidate is found with a
  per-offset max over k, an argmax over offsets (first-hit = smallest n),
  then a first-equal scan over k — reproducing the reference's
  offset-major, k-ascending-with-0-first tie-break exactly
  (cudaFunctions.cu:161) without materialising a transposed grid.

Semantics are identical to xla_scorer/the oracles; property tests pin all
three to each other.
"""

from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.constants import ALPHABET_SIZE, BUF_SIZE_SEQ2, INT32_MIN
from .bounds import (  # noqa: F401 - re-exported public API
    MAX_EXACT_WEIGHT,
    MAX_HIGHEST_OPERAND as _MAX_HIGHEST_OPERAND,
    max_exact_value,
)

_NEG = jnp.float32(-(2.0**40))

# Up to this bound the MXU's DEFAULT f32 precision (single-pass bf16
# multiplies) is already exact: one operand is 0/1 and |d0-d1| <= 2*128
# = 2^8 fits bf16's mantissa.  Above it the matmuls must run
# Precision.HIGHEST (multi-pass) to stay exact on TPU hardware.
MAX_NATIVE_PRECISION_WEIGHT = 128


def mm_precision(val_flat) -> "lax.Precision | None":
    """Static matmul precision for a CONCRETE value table: None (default,
    fastest) when single-pass bf16 multiplies are exact for these values,
    Precision.HIGHEST otherwise."""
    from .values import max_abs_value

    if max_abs_value(val_flat) <= MAX_NATIVE_PRECISION_WEIGHT:
        return None
    return lax.Precision.HIGHEST


def _onehot(codes, width: int) -> jax.Array:
    return (
        codes[:, None] == jnp.arange(width, dtype=codes.dtype)[None, :]
    ).astype(jnp.float32)


def _shear(v: jax.Array) -> jax.Array:
    """[M, W] -> [M, W+1] with row i shifted left by i: out[i, n] = v[i, i+n].

    Pure pad+reshape on the flat buffer (row stride W -> W+1); cells with
    i+n >= W hold wrap garbage that only the validity mask ever sees.
    """
    m, w = v.shape
    flat = jnp.concatenate([v.reshape(-1), jnp.zeros(m, v.dtype)])
    return flat.reshape(m, w + 1)


_SCAN_BLOCK = 128  # MXU-native tile edge


def _block_prefix(d: jax.Array, precision) -> jax.Array:
    """Inclusive prefix sum over axis 0 via a two-level block-scan.

    ``jnp.cumsum`` over a 1280-long axis and a full [M, M] triangular
    matmul both measured ~6-8 ms/rep on the stress workload; splitting M
    into 128-row blocks does the heavy lifting with [128, 128] triangular
    matmuls on the MXU (10x fewer FLOPs than the full triangle) plus a
    tiny carry-in cumsum over the block totals.  Exact in float32: every
    partial sum is an integer below 2^24 regardless of summation order.
    """
    m, w = d.shape
    if m % _SCAN_BLOCK != 0:  # bucketing guarantees this; stay safe anyway
        return jnp.cumsum(d, axis=0)  # adds: exact at any precision
    nb = m // _SCAN_BLOCK
    ii = jnp.arange(_SCAN_BLOCK)
    ltri = (ii[:, None] >= ii[None, :]).astype(d.dtype)
    blocks = d.reshape(nb, _SCAN_BLOCK, w)
    within = jnp.einsum(
        "kb,nbw->nkw",
        ltri,
        blocks,
        preferred_element_type=d.dtype,
        precision=precision,
    )
    carry = jnp.cumsum(within[:, -1, :], axis=0) - within[:, -1, :]
    return (within + carry[:, None, :]).reshape(m, w)


def _score_pair_mm(a_right, len1, seq2row, len2, noff, precision):
    """Score one pair against the shared right factor ``a_right`` =
    val @ onehot(seq1).T, shape [27, W].  Returns (score, n, k) int32.

    Delta formulation.  With d0/d1 the unshifted/shifted diagonal values and
    dD = d0 - d1, every candidate collapses to

        score(n, k) = t1(n) + G[kappa(k), n],   G = prefix_i(dD)

    where kappa(k) = k for k in 1..len2-1 and kappa(0) = len2 (hyphen after
    end == take the full unshifted prefix; dD rows past len2 are zero, so
    G[len2] = t0 - t1 exactly).  The per-offset suffix term t1(n) is common
    to all k, so the inner argmax over k needs only G — one [L2P, NOFF]
    max/argmax instead of materialising the full score matrix, and the
    valid kappa range is simply rows 1..len2.
    """
    l2p = seq2row.shape[0]
    i = jnp.arange(l2p, dtype=jnp.int32)

    oh2 = _onehot(seq2row.astype(jnp.int32), ALPHABET_SIZE)
    oh2 = jnp.where((i < len2)[:, None], oh2, 0.0)  # pad rows contribute 0
    v = jax.lax.dot_general(
        oh2,
        a_right,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=precision,
    )  # [L2P, W]

    d = _shear(v)  # [L2P, W+1]
    d0 = d[:, :noff]
    d1 = d[:, 1 : noff + 1]
    t1 = jnp.sum(d1, axis=0)  # [NOFF] shifted totals
    g = _block_prefix(d0 - d1, precision)  # [L2P, NOFF]; row r = kappa (r+1)

    # Valid kappa = 1..len2  <=>  rows 0..len2-1.
    gm = jnp.where((i < len2)[:, None], g, _NEG)
    run_max = jnp.max(gm, axis=0)  # [NOFF]
    run_row = jnp.argmax(gm, axis=0).astype(jnp.int32)  # first row hitting max
    end_g = g[jnp.maximum(len2 - 1, 0), :]  # G at kappa = len2 (k=0's cell)

    # k=0 outranks equal-scoring k>=1 in the reference's candidate order.
    best_k_per_n = jnp.where(end_g == run_max, 0, run_row + 1)
    score_per_n = t1 + run_max

    n = jnp.arange(noff, dtype=jnp.int32)
    score_per_n = jnp.where(n < jnp.maximum(len1 - len2, 0), score_per_n, _NEG)
    best_n = jnp.argmax(score_per_n).astype(jnp.int32)  # first max: smallest n
    best = score_per_n[best_n]
    best_k = best_k_per_n[best_n]

    eq_score = t1[0] + end_g[0]  # == t0[0]: positional score at n=0
    searchable = (len2 < len1) & (len2 > 0)
    score_f = jnp.where(len2 == len1, eq_score, best)
    score = jnp.where(
        searchable | (len2 == len1),
        score_f.astype(jnp.int32),
        jnp.int32(INT32_MIN),
    )
    out_n = jnp.where(searchable, best_n, 0)
    out_k = jnp.where(searchable, best_k, 0)
    return jnp.stack([score, out_n, out_k])


def score_chunks_mm_body(
    seq1ext,
    len1,
    seq2_chunks,
    len2_chunks,
    val_flat,
    *,
    mm_precision=lax.Precision.HIGHEST,
):
    """MXU-path analogue of xla_scorer.score_chunks_body: [NC, CB, L2P]
    chunked batch -> [NC, CB, 3] int32.

    ``mm_precision`` must be static (jit static_argname / partial) and
    come from :func:`mm_precision` on the concrete weights; the HIGHEST
    default is always exact, merely slower than needed for small weights.
    """
    nc, cb, l2p = seq2_chunks.shape
    noff = seq1ext.shape[0] - l2p - 1  # == L1P, same convention as gather path
    w = noff

    # Shared right factor: [27, W], one small matmul per problem.
    val27 = val_flat.reshape(ALPHABET_SIZE, ALPHABET_SIZE).astype(jnp.float32)
    oh1 = _onehot(seq1ext[:w].astype(jnp.int32), ALPHABET_SIZE)  # [W, 27]
    a_right = jax.lax.dot_general(
        val27,
        oh1,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=mm_precision,
    )  # [27, W]

    def chunk_fn(args):
        rows, lens = args
        return jax.vmap(
            lambda r, l: _score_pair_mm(a_right, len1, r, l, noff, mm_precision)
        )(rows, lens)

    return lax.map(chunk_fn, (seq2_chunks, len2_chunks))


# donate_argnums per the DonationPlan (analysis/dataflow.py) — see
# ops/xla_scorer.py for the pin rationale; `make donation-audit`
# cross-checks this literal against the proof.
score_chunks_mm = jax.jit(
    score_chunks_mm_body,
    static_argnames=("mm_precision",),
    donate_argnums=(0, 2),
)

warnings.filterwarnings("ignore", message="Some donated buffers were not usable")
