"""MXU-formulated XLA scorer: gather-free diagonal prefix sums.

The first XLA formulation (xla_scorer.py) indexes the 27x27 value table and
seq1 with large gathers, which TPUs execute poorly (the bench showed the
host numpy oracle outrunning it).  This formulation maps the same math onto
the hardware's strengths:

* **Value matrix via one-hot matmul (MXU).**  ``V[i, j] = val[seq2[i],
  seq1[j]]`` becomes ``onehot(seq2) @ (val @ onehot(seq1).T)`` — the
  ``[27, W]`` right factor is shared by the whole batch, so each pair costs
  one ``[L2P, 27] x [27, W]`` matmul.  Integer values < 2^24 are exact in
  float32 (the dispatch layer falls back to the gather path for weights
  that could overflow this).
* **Diagonal shear via pad+reshape (zero data movement).**  Appending one
  zero column's worth of padding to ``V``'s flat buffer and re-viewing it
  with row stride W+1 shifts row i left by i: ``D[i, n] = V[i, i+n]`` —
  the diagonal family — with NO gather (wrap garbage lands only in cells
  the (n, k) validity mask kills anyway).
* **Prefix sums on the VPU; argmax as reductions.**  ``score(n, k) =
  prefix0[k] + total1 - prefix1[k]``; the best candidate is found with a
  per-offset max over k, an argmax over offsets (first-hit = smallest n),
  then a first-equal scan over k — reproducing the reference's
  offset-major, k-ascending-with-0-first tie-break exactly
  (cudaFunctions.cu:161) without materialising a transposed grid.

Semantics are identical to xla_scorer/the oracles; property tests pin all
three to each other.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.constants import ALPHABET_SIZE, INT32_MIN

_NEG = jnp.float32(-(2.0**40))

# Weight magnitudes up to this keep every partial sum an exact float32
# integer (|score| <= BUF_SIZE_SEQ2 * max_w < 2^24).
MAX_EXACT_WEIGHT = 4095


def _onehot(codes, width: int) -> jax.Array:
    return (
        codes[:, None] == jnp.arange(width, dtype=codes.dtype)[None, :]
    ).astype(jnp.float32)


def _shear(v: jax.Array) -> jax.Array:
    """[M, W] -> [M, W+1] with row i shifted left by i: out[i, n] = v[i, i+n].

    Pure pad+reshape on the flat buffer (row stride W -> W+1); cells with
    i+n >= W hold wrap garbage that only the validity mask ever sees.
    """
    m, w = v.shape
    flat = jnp.concatenate([v.reshape(-1), jnp.zeros(m, v.dtype)])
    return flat.reshape(m, w + 1)


def _score_pair_mm(a_right, len1, seq2row, len2, noff):
    """Score one pair against the shared right factor ``a_right`` =
    val @ onehot(seq1).T, shape [27, W].  Returns (score, n, k) int32."""
    l2p = seq2row.shape[0]
    i = jnp.arange(l2p, dtype=jnp.int32)

    oh2 = _onehot(seq2row.astype(jnp.int32), ALPHABET_SIZE)
    oh2 = jnp.where((i < len2)[:, None], oh2, 0.0)  # pad rows contribute 0
    v = jax.lax.dot_general(
        oh2,
        a_right,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [L2P, W]

    d = _shear(v)  # [L2P, W+1]
    d0 = d[:, :noff]  # D0[i, n] = V[i, i+n]
    d1 = d[:, 1 : noff + 1]  # D1[i, n] = V[i, i+n+1]
    c0 = jnp.cumsum(d0, axis=0)
    c1 = jnp.cumsum(d1, axis=0)
    t0 = c0[-1, :]  # full unshifted sum per offset (k=0 candidate)
    t1 = c1[-1, :]

    # Row k holds mutant k: k=0 -> t0; k>=1 -> prefix0(k) + shifted suffix1(k).
    s = jnp.concatenate(
        [t0[None, :], c0[:-1, :] + (t1[None, :] - c1[:-1, :])], axis=0
    )  # [L2P, NOFF]

    k = jnp.arange(l2p, dtype=jnp.int32)[:, None]
    n = jnp.arange(noff, dtype=jnp.int32)[None, :]
    valid = (n < jnp.maximum(len1 - len2, 0)) & ((k == 0) | (k < len2))
    s = jnp.where(valid, s, _NEG)

    per_n_max = jnp.max(s, axis=0)  # [NOFF]
    best_n = jnp.argmax(per_n_max).astype(jnp.int32)  # first max -> smallest n
    best = per_n_max[best_n]
    col = s[:, best_n]
    best_k = jnp.argmax(col == best).astype(jnp.int32)  # first k achieving it

    eq_score = c0[-1, 0]  # positional score at n=0 (branch-A analogue)
    searchable = (len2 < len1) & (len2 > 0)
    score_f = jnp.where(len2 == len1, eq_score, best)
    score = jnp.where(
        searchable | (len2 == len1),
        score_f.astype(jnp.int32),
        jnp.int32(INT32_MIN),
    )
    out_n = jnp.where(searchable, best_n, 0)
    out_k = jnp.where(searchable, best_k, 0)
    return jnp.stack([score, out_n, out_k])


def score_chunks_mm_body(seq1ext, len1, seq2_chunks, len2_chunks, val_flat):
    """MXU-path analogue of xla_scorer.score_chunks_body: [NC, CB, L2P]
    chunked batch -> [NC, CB, 3] int32."""
    nc, cb, l2p = seq2_chunks.shape
    noff = seq1ext.shape[0] - l2p - 1  # == L1P, same convention as gather path
    w = noff

    # Shared right factor: [27, W], one small matmul per problem.
    val27 = val_flat.reshape(ALPHABET_SIZE, ALPHABET_SIZE).astype(jnp.float32)
    oh1 = _onehot(seq1ext[:w].astype(jnp.int32), ALPHABET_SIZE)  # [W, 27]
    a_right = jax.lax.dot_general(
        val27,
        oh1,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [27, W]

    def chunk_fn(args):
        rows, lens = args
        return jax.vmap(
            lambda r, l: _score_pair_mm(a_right, len1, r, l, noff)
        )(rows, lens)

    return lax.map(chunk_fn, (seq2_chunks, len2_chunks))


score_chunks_mm = jax.jit(score_chunks_mm_body)
