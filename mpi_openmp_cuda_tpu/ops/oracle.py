"""Reference oracles for the alignment search (SURVEY Appendix A semantics).

Two independent host-side (numpy) implementations of the clean behavioural
contract, used as the ground truth the accelerated paths are property-tested
against (the test pyramid the reference lacks, SURVEY §4):

* ``brute_force_best`` — literal transcription of the spec: O((L1-L2)*L2^2),
  the same asymptotic shape as the reference kernel's serial candidate-grid
  loop (cudaFunctions.cu:116-168), minus its races.
* ``prefix_best`` — the O(L1*L2) diagonal prefix-sum formulation (SURVEY
  §7.2) that the XLA/Pallas device paths vectorise.

Both implement the exact reference semantics:
* mutant k: hyphen inserted after the k-th character; chars i < k pair with
  seq1[n+i], chars i >= k with seq1[n+i+1]; k = 0 encodes hyphen-after-end
  (all chars unshifted) — the reference's encoding of spec-k = len2
  (cudaFunctions.cu:118,132; SURVEY A.2/§7.4.3).
* offsets n in [0, len1-len2) (cudaFunctions.cu:116).
* tie-break: first maximum in offset-major, k-ascending-with-0-first order
  (strict-> update, cudaFunctions.cu:161; SURVEY A.3).
* len2 == len1: direct positional score, n = 0, k = 0 (branch A,
  cudaFunctions.cu:74-106); len2 > len1: (INT32_MIN, 0, 0) (SURVEY B12).
"""

from __future__ import annotations

import numpy as np

from ..utils.constants import INT32_MIN
from .values import value_table

Result = tuple[int, int, int]  # (score, n, k)


def _as_codes(seq) -> np.ndarray:
    return np.asarray(seq, dtype=np.int64)


def equal_length_score(seq1, seq2, weights) -> int:
    """Positional score of two equal-length code vectors (branch A)."""
    seq1, seq2 = _as_codes(seq1), _as_codes(seq2)
    if seq1.size != seq2.size:
        # Runtime path: must survive python -O (seqlint SEQ004).
        raise RuntimeError(
            f"equal_length_score needs equal-length inputs, got "
            f"{seq1.size} vs {seq2.size}"
        )
    val = value_table(weights)
    return int(val[seq2, seq1].sum())


def brute_force_best(seq1, seq2, weights) -> Result:
    """Exhaustive search over all (n, k) candidates. Small inputs only."""
    seq1, seq2 = _as_codes(seq1), _as_codes(seq2)
    l1, l2 = seq1.size, seq2.size
    if l2 > l1:
        return INT32_MIN, 0, 0
    if l2 == l1:
        return equal_length_score(seq1, seq2, weights), 0, 0
    val = value_table(weights)
    best, best_n, best_k = INT32_MIN, 0, 0
    for n in range(l1 - l2):
        for k in range(l2):  # k=0 (hyphen after end) first, then 1..l2-1
            s = 0
            for i in range(l2):
                j = n + i if (k == 0 or i < k) else n + i + 1
                s += int(val[seq2[i], seq1[j]])
            if s > best:
                best, best_n, best_k = s, n, k
    return best, best_n, best_k


def prefix_best(seq1, seq2, weights) -> Result:
    """Diagonal prefix-sum search, O(L1*L2). Exact same results as brute force."""
    seq1, seq2 = _as_codes(seq1), _as_codes(seq2)
    l1, l2 = seq1.size, seq2.size
    if l2 > l1:
        return INT32_MIN, 0, 0
    if l2 == l1:
        return equal_length_score(seq1, seq2, weights), 0, 0
    if l2 == 0:
        # Empty candidate: the (n, k) grid has no k values (k ranges over
        # 0..l2-1), so no candidate is ever scored — INT_MIN sentinel, same
        # as the reference's never-updated best (cudaFunctions.cu:113).
        return INT32_MIN, 0, 0
    val = value_table(weights).astype(np.int64)
    n = np.arange(l1 - l2)[:, None]
    i = np.arange(l2)[None, :]
    v0 = val[seq2[None, :], seq1[n + i]]  # pair values on the unshifted diagonal
    v1 = val[seq2[None, :], seq1[n + i + 1]]  # ... and the hyphen-shifted diagonal
    c0 = v0.cumsum(axis=1)
    c1 = v1.cumsum(axis=1)
    t0, t1 = c0[:, -1:], c1[:, -1:]
    # Column j holds k=j: k=0 -> full unshifted sum; k>=1 -> prefix(k) + shifted suffix(k).
    scores = np.concatenate([t0, c0[:, :-1] + (t1 - c1[:, :-1])], axis=1)
    flat = int(scores.argmax())  # first max in n-major, k=0,1,.. order == reference order
    return int(scores.reshape(-1)[flat]), flat // l2, flat % l2


def score_batch_oracle(seq1, seq2_list, weights) -> list[Result]:
    """prefix_best over a ragged batch (the whole program, as one pure function)."""
    return [prefix_best(seq1, s2, weights) for s2 in seq2_list]
