"""Batch dispatch: shape bucketing, padding, backend selection (C6 + C14).

The reference splits the Seq2 batch into fixed-stride 2000-byte records
(main.c:110-121) and launches one kernel per sequence in a serial,
synchronising host loop (cudaFunctions.cu:204-220).  Here the batch is
padded into a rectangular [B, L2P] array once, shapes are rounded up to a
small set of buckets (so XLA compiles a handful of programs, not one per
problem), and the whole batch is scored in one jitted call — chunked
internally to bound live memory.
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass

import numpy as np

from ..models.encoding import encode_normalized, pad_to
from ..obs.events import log_line
from ..obs.metrics import gauge as _obs_gauge, inc as _obs_inc
from ..obs.spans import fence as _obs_fence, span as _obs_span
from ..resilience.faults import fire as _fault
from ..resilience.watchdog import guard as _deadline_guard
from ..utils.constants import ALPHABET_SIZE, BUF_SIZE_SEQ1, BUF_SIZE_SEQ2
from .bounds import fits_exact_window  # noqa: F401 - re-exported certified gate
from .oracle import score_batch_oracle
from .values import value_table

# Shape buckets: multiples of the TPU lane width keep tiles aligned; the
# bucket floor bounds recompilation for tiny inputs.
_LANE = 128

# Max live elements per intermediate array inside one chunk
# (~64 MiB of int32 at the default). Tunable via AlignmentScorer.
DEFAULT_CHUNK_BUDGET = 16 * 1024 * 1024

# Length buckets smaller than this merge into the next wider bucket:
# below it, a separate compilation + dispatch costs more than padding.
MIN_BUCKET_ROWS = 8


def round_up(x: int, mult: int) -> int:
    return max(mult, mult * math.ceil(x / mult))


@dataclass(frozen=True)
class PaddedBatch:
    """A rectangular, bucket-padded encoding of one scoring problem."""

    seq1ext: np.ndarray  # [L1P + L2P + 1] int32
    len1: int
    seq2: np.ndarray  # [B, L2P] int32
    len2: np.ndarray  # [B] int32
    l1p: int
    l2p: int

    @property
    def batch_size(self) -> int:
        return self.seq2.shape[0]


def pad_problem(
    seq1_codes: np.ndarray,
    seq2_codes: list[np.ndarray],
    *,
    lane: int = _LANE,
    enforce_caps: bool = True,
) -> PaddedBatch:
    """Encode a ragged problem into bucket-padded rectangular arrays.

    ``enforce_caps=False`` lifts the reference's fixed buffer limits
    (myProto.h:3-4) for the long-context sequence-parallel path, which
    shards Seq1 across devices and has no single-buffer ceiling.
    """
    len1 = int(seq1_codes.size)
    if enforce_caps and len1 > BUF_SIZE_SEQ1:
        raise ValueError(f"Seq1 length {len1} exceeds BUF_SIZE_SEQ1={BUF_SIZE_SEQ1}")
    for idx, codes in enumerate(seq2_codes):
        if enforce_caps and codes.size > BUF_SIZE_SEQ2:
            raise ValueError(
                f"Seq2[{idx}] length {codes.size} exceeds BUF_SIZE_SEQ2={BUF_SIZE_SEQ2}"
            )
    l1p = round_up(len1, lane)
    max_l2 = max((c.size for c in seq2_codes), default=1)
    l2p = round_up(max_l2, lane)
    seq1ext = np.zeros(l1p + l2p + 1, dtype=np.int32)
    seq1ext[:len1] = seq1_codes
    rows = np.stack(
        [pad_to(c, l2p).astype(np.int32) for c in seq2_codes]
    ) if seq2_codes else np.zeros((0, l2p), dtype=np.int32)
    lens = np.array([c.size for c in seq2_codes], dtype=np.int32)
    return PaddedBatch(seq1ext, len1, rows, lens, l1p, l2p)


# Grid-cell ceiling for one fused-kernel call: far above any real batch
# chunk, far below anything that could stress the runtime.
PALLAS_MAX_CHUNK = 512


def choose_chunk(batch: PaddedBatch, budget: int, backend: str = "xla") -> int:
    """Chunk size bounding per-chunk live memory; power of two for
    bucketing.

    The XLA formulations materialise O(L1P x L2P) intermediates per pair,
    so their chunk is budget / (l1p*l2p).  The fused Pallas kernel keeps V
    in VMEM and streams pairs through the grid — pp = 2 pairs per grid
    cell on even chunks (pp = 1 odd), and p = 128/l2s pairs per tile on
    the row-packed path — so its per-pair HBM is just the codes row + a
    128-lane output row (verified against analysis.vmem's streamed-block
    model) and it takes the whole batch in one call (capped): splitting
    it pays per-call dispatch overhead AND re-DMAs the A bands per call
    (measured on the max-size config: the old l1p*l2p budget forced
    cb=2 -> 32 calls x 6.8 MiB of A3 traffic, ~2x the kernel's own
    wall)."""
    return choose_chunk_dims(
        batch.l1p, batch.l2p, batch.batch_size, budget, backend
    )


def choose_chunk_dims(
    l1p: int,
    l2p: int,
    batch_size: int,
    budget: int = DEFAULT_CHUNK_BUDGET,
    backend: str = "xla",
) -> int:
    """:func:`choose_chunk` on bare dims — the launch-fusion planner
    prices candidate groups before any ``PaddedBatch`` exists, and the
    chunk policy must be THE dispatch policy or the planner would price
    a launch count the dispatch never runs."""
    if backend == "pallas":
        per_pair = l2p  # codes row; outputs are O(128)
    else:
        per_pair = l1p * l2p
    cb = max(1, budget // max(per_pair, 1))
    cb = 1 << (cb.bit_length() - 1)  # floor to power of two
    if backend == "pallas":
        cb = min(cb, PALLAS_MAX_CHUNK)
    return min(cb, max(1, 1 << (max(batch_size, 1) - 1).bit_length()))


def choose_chunk_rows(per_pair: int, budget: int, per_dev_rows: int) -> int:
    """Per-device chunk size: the single chunk policy shared by the sharded
    paths (batch and ring).  Power-of-two rows whose [rows, per_pair] grid
    fits the budget, never exceeding the per-device row count."""
    cb = max(1, budget // max(per_pair, 1))
    cb = 1 << (cb.bit_length() - 1)  # floor to power of two
    while cb > max(1, per_dev_rows):
        cb >>= 1
    return cb


def resolve_auto_backend() -> str:
    """'pallas' when the runtime default backend is a real TPU and the
    pallas module imports, else 'xla'.

    The policy behind the CLI's / native driver's / bench's 'auto'
    default: on TPU the fused kernel is the fastest exact path (with its
    own per-call routing for wide weights and unaligned buckets); off-TPU
    pallas would run interpret mode, far slower than the XLA formulation.
    """
    try:
        import jax

        on_tpu = jax.default_backend() == "tpu"
        multi_host = jax.process_count() > 1
    except Exception:
        # advisory: backend probe during auto-resolution — a jax-less or
        # unreadied runtime resolves to the reference backend.
        on_tpu = False
        multi_host = False
    if on_tpu:
        try:
            from . import pallas_scorer  # noqa: F401

            return "pallas"
        except Exception as e:
            if multi_host:
                # In a multi-host job the backend choice IS the SPMD
                # program: a host silently downgrading to 'xla' while its
                # peers resolve 'pallas' would desynchronise collectives
                # (a hang, not an error).  Fail fast instead; the operator
                # picks one explicit --backend for every host.
                raise RuntimeError(
                    "backend 'auto' cannot resolve 'pallas' on this host "
                    f"(import failed: {e}) while the job is multi-host; "
                    "pass the same explicit --backend on every host"
                ) from e
            # Never silent: a broken pallas build on TPU downgrades the
            # default path 26x — the operator must see why this host
            # chose 'xla'.
            log_line(
                "mpi_openmp_cuda_tpu: warning: backend 'auto' fell back to "
                f"'xla' on a TPU host (pallas import failed: {e}); pass an "
                "explicit --backend to silence or to fail fast"
            )
            return "xla"
    return "xla"


def mm_formulation_exact(val_flat: np.ndarray, l2p: int | None = None) -> bool:
    """True when every partial sum stays an exact float32 integer on the
    matmul path.  Length-aware (r6): with a concrete batch ``l2p`` the
    bound is ``2 * l2p * max|value| < 2^24`` (operand-capped — see
    ops/bounds.py), so short-Seq2 buckets keep the exact path far past
    the static ceiling; ``l2p=None`` is the conservative whole-buffer
    bound.  Alias of :func:`ops.bounds.fits_exact_window` — the ceiling
    lives in the cert-backed bounds module, not here."""
    from .bounds import fits_exact_window

    return fits_exact_window(val_flat, l2p)


def choose_pallas_formulation(
    val_flat: np.ndarray, dims: tuple[int, ...], l2p: int | None = None
) -> tuple:
    """The single source of the fused-kernel eligibility policy, shared by
    the batch-sharded and ring paths: ('pallas', feed) — feed being the
    fastest exact MXU operand type ('i8'/'bf16'/'f32') — when float32 math
    is exact for these weights at this Seq2 bucket width (``l2p=None`` =
    static worst case) and every dimension in ``dims`` is 128-aligned;
    ('gather',) otherwise.  Raises the friendly RuntimeError when the
    pallas module itself is unavailable."""
    try:
        from .pallas_scorer import mxu_feed
    except ModuleNotFoundError as e:
        raise RuntimeError("backend 'pallas' is not available in this build") from e
    if mm_formulation_exact(val_flat, l2p) and all(d % 128 == 0 for d in dims):
        return ("pallas", mxu_feed(val_flat))
    return ("gather",)


def xla_formulation_mode(
    backend: str, val_flat: np.ndarray, l2p: int | None = None
) -> str:
    """'mm' or 'gather' for an 'xla*' backend string — the single source of
    truth for the formulation choice, shared by the local and sharded paths."""
    if backend == "xla" and mm_formulation_exact(val_flat, l2p):
        return "mm"
    return "gather"


def resolve_xla_formulation(backend: str, val_flat: np.ndarray, l2p: int | None = None):
    """Pick the jitted chunked scorer for an 'xla*' backend string."""
    if xla_formulation_mode(backend, val_flat, l2p) == "mm":
        from .matmul_scorer import mm_precision, score_chunks_mm

        return functools.partial(
            score_chunks_mm, mm_precision=mm_precision(val_flat)
        )
    from .xla_scorer import score_chunks

    return score_chunks


def effective_backend(backend: str, val_flat: np.ndarray, l2p: int | None = None) -> str:
    """The formulation a backend string actually runs: 'pallas' only when
    the fused kernel is eligible for these weights (at this Seq2 bucket
    width, when known); its overflow-risk fallback reports 'xla-gather'.
    Single source for consumers that must match the dispatch routing
    (bench's chunk policy)."""
    if (
        backend == "pallas"
        and choose_pallas_formulation(val_flat, (), l2p)[0] != "pallas"
    ):
        return "xla-gather"
    return backend


def pack_classes(feed: str, maxv: int | None = None) -> tuple[int, ...]:
    """Row-packing classes legal for one MXU feed (r6: packing covers all
    three feeds, bounded by the packed kernel's int32 epilogue).

    The packed epilogue packs ``(t1 + gdec) * 2^klb + key`` into int32
    with ``klb <= 12`` at the ``sb <= 24`` bound, so the packed score
    magnitude ``3 * l2s * max|v|`` must stay < 2^19.  i8 (|v| <= 127)
    passes at every class by construction; bf16 (|v| <= 128) likewise
    (3*64*128 < 2^19); the f32 feed keeps the classes its actual weight
    magnitude affords — {8, 16, 32} at the static ceiling, shrinking to
    none near the operand cap.  ``maxv=None`` is conservative for non-i8
    feeds (unknown weights -> no packing).  The 2^19 ceiling is imported
    from the cert-backed bounds module, never inlined here."""
    from .bounds import ROWPACK_EPILOGUE_LIMIT

    if feed == "i8":
        return (8, 16, 32, 64)
    if feed in ("bf16", "f32") and maxv is not None:
        return tuple(
            s for s in (8, 16, 32, 64) if 3 * s * int(maxv) < ROWPACK_EPILOGUE_LIMIT
        )
    return ()


def plan_buckets(
    sizes,
    *,
    packable: bool,
    min_rows: int = MIN_BUCKET_ROWS,
    classes: tuple[int, ...] = (8, 16, 32, 64),
) -> dict[int, list[int]]:
    """The length-bucketing schedule: input indices grouped by L2P shape
    bucket (plus, when ``packable``, the sub-128 row-packing ``classes``
    from :func:`pack_classes`), with straggler groups merged into the next
    wider one.  Shared by ``score_codes_async`` and the bench's
    steady-state harness so the bench times exactly the production
    dispatch schedule."""

    def bucket_key(size: int) -> int:
        l2p = round_up(max(size, 1), _LANE)
        if packable and l2p == _LANE and classes and size <= classes[-1]:
            return next(s for s in classes if s >= size)
        return l2p

    groups: dict[int, list[int]] = {}
    for i, size in enumerate(sizes):
        groups.setdefault(bucket_key(int(size)), []).append(i)
    keys = sorted(groups)
    for j, k in enumerate(keys[:-1]):
        if len(groups[k]) < min_rows:
            groups[keys[j + 1]].extend(groups.pop(k))
    return groups


def choose_rowpack(feed: str, l2p: int, lens, maxv: int | None = None) -> int | None:
    """Row-packing decision (VERDICT r3 item 3; widened to the bf16/f32
    feeds in r6), shared by the local dispatch and the bench body resolver
    so the bench times the same program the scorer runs: pack p = 128/l2s
    pairs per tile when the bucket is a single char-block (L2P == 128),
    there are >= 2 rows to share a tile, every live row fits the widest
    legal sub-tile class for this feed, and — for the non-i8 feeds — the
    concrete weight magnitude ``maxv`` keeps the packed int32 epilogue
    exact (see :func:`pack_classes`; ``maxv=None`` disables non-i8
    packing, the pre-r6 behaviour)."""
    lens = [int(x) for x in lens]
    live = [x for x in lens if x > 0]
    classes = pack_classes(feed, maxv)
    if not classes or l2p != _LANE or len(lens) < 2 or not live:
        return None
    m = max(live)
    if m > classes[-1]:
        return None
    return next(s for s in classes if s >= m)


def resolve_chunks_body(backend: str, val_flat: np.ndarray, problem_dims=None):
    """Unjitted chunked-scorer body for a backend string (bench/shard_map
    composition), including the float32-exactness fallback: a 'pallas'
    request with overflow-risk weights gets the exact int32 gather body —
    the same routing the production score paths apply.

    ``problem_dims`` = (l1p, l2p, len1, lens) with CONCRETE lens selects
    the adaptive super-block width exactly like the production dispatch,
    so bench measurements time the same program the scorer would run.
    The concrete l2p also engages the length-aware exactness bound — a
    short-Seq2 bench problem routes exactly like the scorer would route
    it, not like the static worst case.
    """
    dims_l2p = problem_dims[1] if problem_dims is not None else None
    backend = effective_backend(backend, val_flat, dims_l2p)
    if backend == "pallas":
        fm = choose_pallas_formulation(val_flat, (), dims_l2p)
        from .pallas_scorer import choose_superblock, score_chunks_pallas_body
        from .values import max_abs_value

        sb = None
        l2s = None
        if problem_dims is not None:
            l1p, l2p, len1, lens = problem_dims
            sb = choose_superblock(
                l1p // 128, l2p // 128, int(len1), lens, fm[1]
            )
            if fm[0] == "pallas":
                l2s = choose_rowpack(
                    fm[1], l2p, lens, maxv=max_abs_value(val_flat)
                )
        return functools.partial(
            score_chunks_pallas_body, feed=fm[1], sb=sb, l2s=l2s
        )
    if xla_formulation_mode(backend, val_flat, dims_l2p) == "mm":
        from .matmul_scorer import mm_precision, score_chunks_mm_body

        return functools.partial(
            score_chunks_mm_body, mm_precision=mm_precision(val_flat)
        )
    from .xla_scorer import score_chunks_body

    return score_chunks_body


def pad_batch_rows(batch: PaddedBatch, bp: int) -> tuple[np.ndarray, np.ndarray]:
    """Zero-pad the batch rows/lengths to ``bp`` total rows.

    Shared by the single-device and sharded paths so padding semantics
    (zero rows == len-0 sentinels, dropped on output) cannot diverge.
    """
    rows = np.zeros((bp, batch.l2p), dtype=np.int32)
    rows[: batch.batch_size] = batch.seq2
    lens = np.zeros(bp, dtype=np.int32)
    lens[: batch.batch_size] = batch.len2
    return rows, lens


@dataclass(frozen=True)
class PendingResult:
    """A dispatched-but-unforced scoring result (async pipelining).

    ``raw`` is the [BP, 3] device array of a jitted call (or a host array
    on the synchronous oracle/sharded paths); JAX dispatch is asynchronous,
    so holding this while doing host work (parsing the next chunk) overlaps
    host and device.  ``result()`` materialises the [B, 3] host rows.
    """

    raw: object
    count: int

    def prefetch(self) -> None:
        """Start a non-blocking device->host copy of the result so a later
        ``result()`` finds it already on the host.  On a tunnelled TPU a
        synchronous fetch costs a ~0.1 s link round trip; the streaming
        pipeline prefetches every in-flight chunk right after dispatch so
        those round trips overlap compute and each other (r5 stream
        measurement: per-chunk fetches serialised the whole pipeline)."""
        _fault("device_transfer")
        f = getattr(self.raw, "copy_to_host_async", None)
        if f is not None:
            f()

    def result(self) -> np.ndarray:
        with _deadline_guard("chunk result gather"):
            _fault("chunk_scoring")
            # The fence pins async device time onto this span instead of
            # letting it leak into whichever host op touches the array
            # first; both are single attribute checks when obs is off.
            with _obs_span("chunk_gather"):
                _obs_fence(self.raw)
                return np.asarray(self.raw).reshape(-1, 3)[: self.count]


@dataclass(frozen=True)
class BucketedPending:
    """Pending results of a length-bucketed dispatch (input order restored
    on materialisation).  All buckets are dispatched before any is forced,
    so they queue on the device back to back; one batched device_get
    fetches every part in a single host round trip (per-part .result()
    would pay the tunnel latency once per bucket)."""

    parts: list  # [(row_indices, PendingResult | ShardedPending)]
    count: int

    def prefetch(self) -> None:
        for _, pend in self.parts:
            pend.prefetch()

    def result(self) -> np.ndarray:
        with _deadline_guard("bucketed result gather"):
            with _obs_span("chunk_gather"):
                return self._result()

    def _result(self) -> np.ndarray:
        import jax

        _fault("chunk_scoring")
        out = np.zeros((self.count, 3), dtype=np.int32)
        # Batch the device_get across the local parts AND (single-process)
        # sharded parts — one host round trip for the whole batch.
        # Multi-host sharded parts own their collective gather and run in
        # list order — the same deterministic order every host derived,
        # so multi-host bucketed dispatch stays in lockstep.
        single = jax.process_count() == 1
        batched = [
            (idx, pend)
            for idx, pend in self.parts
            if isinstance(pend, PendingResult) or single
        ]
        raws = (
            jax.device_get(
                [
                    pend.raw if isinstance(pend, PendingResult) else pend.out
                    for _, pend in batched
                ]
            )
            if batched
            else []
        )
        for (idx, pend), raw in zip(batched, raws):
            out[idx] = np.asarray(raw).reshape(-1, 3)[: pend.count]
        for idx, pend in self.parts:
            if not (isinstance(pend, PendingResult) or single):
                out[idx] = pend.result()
        return out


class StagedFeed:
    """Single-use pre-transferred operands for ONE upcoming dispatch
    (feed overlap): launch-group key -> ``(seq1_dev, len1, rows_dev,
    lens_dev, val_dev)``.

    ``take`` POPS — each entry can feed at most one attempt, so a
    retried dispatch finds the handle drained and re-stages from the
    host arrays.  That single-use contract is what keeps prestaging
    compatible with operand donation: a donated prestaged buffer is
    never reachable again."""

    def __init__(self):
        self._parts: dict = {}

    def put(self, key, part) -> None:
        self._parts[key] = part

    def take(self, key):
        return self._parts.pop(key, None)

    def __len__(self) -> int:
        return len(self._parts)


def staged_matches(
    part, seq1_shape, rows_shape, lens_shape, val_shape
) -> bool:
    """A prestaged part is usable only when its shapes are EXACTLY the
    shapes the dispatch just derived — any planning drift between
    prestage time and dispatch time (bucket mix, chunk policy) makes the
    dispatch silently fall back to host staging instead of feeding the
    kernel a wrong-shaped buffer."""
    try:
        seq1_dev, _, rows_dev, lens_dev, val_dev = part
        return (
            tuple(seq1_dev.shape) == tuple(seq1_shape)
            and tuple(rows_dev.shape) == tuple(rows_shape)
            and tuple(lens_dev.shape) == tuple(lens_shape)
            and tuple(val_dev.shape) == tuple(val_shape)
        )
    except Exception:
        # advisory: staged-shape probe only — False re-stages the
        # buffers through the normal path.
        return False


class AlignmentScorer:
    """Front door to the accelerated scoring paths (the C2 offload ABI's
    Python-side equivalent).

    backend: 'auto' (pallas on a real TPU, xla otherwise — see
    resolve_auto_backend), 'xla' (the gather-free MXU matmul formulation,
    with an automatic fall-back to the gather formulation when weight
    magnitudes could exceed float32 integer exactness), 'xla-gather'
    (force the int32 gather formulation), 'pallas' (TPU kernel), or
    'oracle' (host numpy — the always-correct reference path).
    """

    def __init__(
        self,
        backend: str = "xla",
        chunk_budget: int = DEFAULT_CHUNK_BUDGET,
        sharding=None,
        check: bool | None = None,
    ):
        if backend == "auto":
            backend = resolve_auto_backend()
        if backend not in ("xla", "xla-gather", "pallas", "oracle"):
            raise ValueError(f"unknown backend {backend!r}")
        self.backend = backend
        self.chunk_budget = chunk_budget
        self.sharding = sharding  # parallel.BatchSharding or None
        if check is None:
            from ..utils.platform import env_flag

            check = env_flag("SEQALIGN_CHECK")
        # --check / SEQALIGN_CHECK: validate every concrete dispatch
        # decision against the analysis-pass contracts before launch.
        self.check = bool(check)

    # -- code-level API ----------------------------------------------------
    def score_codes(
        self,
        seq1_codes: np.ndarray,
        seq2_codes: list[np.ndarray],
        weights,
        *,
        val_table: np.ndarray | None = None,
        staged: "StagedFeed | None" = None,
    ) -> np.ndarray:
        """Returns [B, 3] int32 array of (score, n, k) rows, input order.

        ``val_table`` optionally overrides the spec-derived [27, 27] signed
        pair-value table — the native host ABI stages its own matrices
        (reference C2/C12 semantics: the host builds and uploads the lookup
        state, the device scores with whatever it was given).

        ``staged`` forwards a :class:`StagedFeed` handle from
        :meth:`prestage_codes` (single-use, advisory — see
        :meth:`score_codes_async`).
        """
        return self.score_codes_async(
            seq1_codes, seq2_codes, weights, val_table=val_table,
            staged=staged,
        ).result()

    def score_codes_async(
        self,
        seq1_codes: np.ndarray,
        seq2_codes: list[np.ndarray],
        weights,
        *,
        val_table: np.ndarray | None = None,
        staged: "StagedFeed | None" = None,
    ) -> "PendingResult | BucketedPending":
        """``score_codes`` without forcing the device->host copy.

        ``staged`` optionally carries operands pre-transferred by
        :meth:`prestage_codes` (feed overlap).  The handle is SINGLE-USE
        per launch group — a retry of this call finds it drained and
        re-stages from the host arrays, which is what keeps the donation
        contract (retries never re-read a donated device buffer).

        The local jitted paths and the sharded paths dispatch
        asynchronously, so the caller can overlap host work (e.g. parsing
        the next input chunk) with device compute and call ``.result()``
        later; only the oracle path materialises internally.  The sharded
        paths return a ``parallel.sharding.ShardedPending`` whose
        ``result()`` performs the cross-host gather — a collective on
        multi-host jobs, so every process must reach ``result()`` in the
        same order (the CLI's chunk-lockstep schedule does).
        Multi-length-bucket batches return a :class:`BucketedPending`
        (same ``.result()`` contract, input order restored).
        """
        with _deadline_guard("chunk dispatch"):
            _fault("chunk_dispatch")
        _obs_inc("chunks_dispatched")
        if not seq2_codes:
            return PendingResult(np.zeros((0, 3), dtype=np.int32), 0)
        if self.backend == "oracle":
            if val_table is not None and not np.array_equal(
                np.asarray(val_table, dtype=np.int32), value_table(weights)
            ):
                raise ValueError(
                    "backend 'oracle' scores from the spec group tables; "
                    "a custom val_table needs an accelerated backend"
                )
            out = np.array(
                score_batch_oracle(seq1_codes, seq2_codes, weights), dtype=np.int32
            )
            return PendingResult(out, out.shape[0])
        if val_table is None:
            val_flat = value_table(weights).astype(np.int32).reshape(-1)
        else:
            val_flat = np.asarray(val_table, dtype=np.int32).reshape(-1)
            if val_flat.size != ALPHABET_SIZE * ALPHABET_SIZE:
                raise ValueError(
                    f"val_table must be [27, 27]; got {val_flat.size} elements"
                )
        unbounded = bool(getattr(self.sharding, "unbounded", False))
        if not unbounded:
            # Caps validated on the WHOLE batch first so the error names
            # the caller's input index (a per-bucket pad_problem would
            # report a bucket-local one, after earlier buckets already
            # dispatched).
            if seq1_codes.size > BUF_SIZE_SEQ1:
                raise ValueError(
                    f"Seq1 length {seq1_codes.size} exceeds "
                    f"BUF_SIZE_SEQ1={BUF_SIZE_SEQ1}"
                )
            for i, c in enumerate(seq2_codes):
                if c.size > BUF_SIZE_SEQ2:
                    raise ValueError(
                        f"Seq2[{i}] length {c.size} exceeds "
                        f"BUF_SIZE_SEQ2={BUF_SIZE_SEQ2}"
                    )
        # Length-sorted bucketing (VERDICT r1 item 6, measured to pay
        # ~10% on a bimodal batch): rows grouped by their L2P shape
        # bucket dispatch as separate smaller programs — short rows
        # stop riding max-len-wide buffers (and max-len chunking) —
        # then scatter back to input order.  Applies to the local path
        # and to batch-only meshes (VERDICT r2 item 8): buckets derive
        # from the broadcast-identical global lens in sorted order, so
        # every host runs the identical per-bucket collective schedule.
        # The ring path keeps one program (its window schedule depends on
        # L2P, and a per-bucket ring would rebuild windows per bucket).
        # That exclusion is MEASURED, not just asserted (r5,
        # scripts/ring_pack_ab.py gated A/B): an input4-class tiny-Seq2
        # batch through ring-sp1 pays 1.71x the local packed path
        # (77.0 vs 45.0 us) — real but under the 2-3x that would justify
        # packing classes inside the ring program, at walls that are
        # half dispatch floor, in a regime (long-context AND all-tiny
        # Seq2) the ring rarely serves.  Recorded as headroom, not debt.
        bucketable = self.sharding is None or getattr(
            self.sharding, "bucketed", False
        )
        if bucketable:
            # Row-packing sub-classes (VERDICT r3 item 3): on the local
            # pallas-i8 path, rows short enough to pack (len2 <= 64)
            # bucket by their packing class {8, 16, 32, 64} — sub-128
            # "virtual L2P" keys — so one straggler long row cannot
            # lock a whole tiny-Seq2 batch out of the packed kernel.
            # The keys sort below 128 and merge upward through the
            # normal straggler rule (each bucket costs a compilation +
            # dispatch; on a mesh a bucket also pads to the device
            # count, so the threshold scales with it); _score_local
            # re-derives the packed decision from the sub-batch's own
            # len2 max.
            # r6: packing covers every feed whose weights keep the packed
            # int32 epilogue exact (pack_classes); the eligibility check
            # runs at the packing bucket width (L2P == 128), where the
            # length-aware exactness bound is widest.
            packable = False
            classes: tuple[int, ...] = ()
            if self.sharding is None and self.backend == "pallas":
                from .values import max_abs_value

                fm = choose_pallas_formulation(val_flat, (), _LANE)
                if fm[0] == "pallas":
                    classes = pack_classes(fm[1], max_abs_value(val_flat))
                    packable = bool(classes)
            sizes = [c.size for c in seq2_codes]
            groups = plan_buckets(
                sizes,
                packable=packable,
                min_rows=MIN_BUCKET_ROWS
                * (1 if self.sharding is None else self.sharding.n_devices),
                classes=classes or (8, 16, 32, 64),
            )
            # Launch fusion (r6): on the local pallas path the chooser
            # consults the fusion planner — `fused` is a dispatch
            # dimension decided by the same cost model that picks the
            # super-block, so the dispatched launch groups ARE the
            # production_schedule's (single-derivation invariant).
            group_keys = [(k,) for k in sorted(groups)]
            if self.sharding is None and self.backend == "pallas":
                from .schedule import plan_fusion_groups

                group_keys = plan_fusion_groups(
                    groups, sizes, int(seq1_codes.size), val_flat
                )
            _obs_gauge("config_fused_groups", len(group_keys))
            if len(groups) > 1:
                parts = []
                for gkeys in group_keys:
                    idx = np.asarray(
                        sorted(i for k in gkeys for i in groups[k]),
                        dtype=np.int64,
                    )
                    sub = pad_problem(
                        seq1_codes, [seq2_codes[i] for i in idx]
                    )
                    parts.append(
                        (
                            idx,
                            self._dispatch_batch(
                                sub,
                                val_flat,
                                staged.take(gkeys) if staged else None,
                            ),
                        )
                    )
                return BucketedPending(parts, len(seq2_codes))
        return self._dispatch_batch(
            pad_problem(seq1_codes, seq2_codes, enforce_caps=not unbounded),
            val_flat,
            staged.take(None) if staged else None,
        )

    def prestage_codes(
        self,
        seq1_codes: np.ndarray,
        seq2_codes: list[np.ndarray],
        weights,
        *,
        val_table: np.ndarray | None = None,
    ) -> "StagedFeed | None":
        """Start the host->device transfers for a FUTURE
        ``score_codes_async`` of the same operands (feed overlap): runs
        the identical bucket/fusion/pad planning the dispatch will run
        and issues one async ``jax.device_put`` set per launch group,
        so the next chunk's feed rides the interconnect while the
        current chunk computes.

        Purely advisory: returns None when prestaging does not apply
        (sharded paths own their staging; oracle never stages; empty
        batch), and the dispatch ignores any entry whose shapes drifted
        from its own derivation.  Entries are single-use
        (:class:`StagedFeed`), preserving the retries-re-stage donation
        contract."""
        if (
            self.sharding is not None
            or self.backend == "oracle"
            or not seq2_codes
        ):
            return None
        import jax

        if val_table is None:
            val_flat = value_table(weights).astype(np.int32).reshape(-1)
        else:
            val_flat = np.asarray(val_table, dtype=np.int32).reshape(-1)
        # Identical planning chain to score_codes_async: packing
        # eligibility, length buckets, fusion partition.
        packable = False
        classes: tuple[int, ...] = ()
        if self.backend == "pallas":
            from .values import max_abs_value

            fm = choose_pallas_formulation(val_flat, (), _LANE)
            if fm[0] == "pallas":
                classes = pack_classes(fm[1], max_abs_value(val_flat))
                packable = bool(classes)
        sizes = [c.size for c in seq2_codes]
        groups = plan_buckets(
            sizes,
            packable=packable,
            min_rows=MIN_BUCKET_ROWS,
            classes=classes or (8, 16, 32, 64),
        )
        if len(groups) > 1:
            group_keys = [(k,) for k in sorted(groups)]
            if self.backend == "pallas":
                from .schedule import plan_fusion_groups

                group_keys = plan_fusion_groups(
                    groups, sizes, int(seq1_codes.size), val_flat
                )
            parts = [
                (
                    gkeys,
                    [
                        seq2_codes[i]
                        for i in sorted(
                            i for k in gkeys for i in groups[k]
                        )
                    ],
                )
                for gkeys in group_keys
            ]
        else:
            parts = [(None, list(seq2_codes))]
        staged = StagedFeed()
        for key, codes in parts:
            sub = pad_problem(seq1_codes, codes)
            fm = ("gather",)
            if self.backend == "pallas":
                fm = choose_pallas_formulation(val_flat, (), sub.l2p)
            cb = choose_chunk(
                sub,
                self.chunk_budget,
                backend="pallas" if fm[0] == "pallas" else "xla",
            )
            bp = round_up(sub.batch_size, cb)
            rows, lens = pad_batch_rows(sub, bp)
            # One device_put per operand, all async; seq1/val are staged
            # PER GROUP because the jit entries donate their seq1/rows
            # operands — a shared staged seq1 would be donated by the
            # first launch and re-read by the second.
            staged.put(
                key,
                (
                    jax.device_put(sub.seq1ext),
                    sub.len1,
                    jax.device_put(rows.reshape(bp // cb, cb, sub.l2p)),
                    jax.device_put(lens.reshape(bp // cb, cb)),
                    jax.device_put(val_flat),
                ),
            )
        _obs_inc("feed_prestages")
        return staged

    def _dispatch_batch(
        self, batch: "PaddedBatch", val_flat: np.ndarray, staged=None
    ):
        """Dispatch one shape-uniform padded batch on the configured path
        (local jitted or sharded); returns a pending.  ``staged`` is one
        launch group's pre-transferred operand tuple (or None)."""
        with _obs_span("chunk_dispatch"):
            if self.sharding is None:
                return self._score_local(batch, val_flat, staged)
            # ShardedPending: dispatch returns before the gather; the fetch
            # (a collective on multi-host) happens at .result() (VERDICT r2
            # item 6 — forcing here serialised --stream's overlap on meshes).
            return self.sharding.score_async(
                batch,
                val_flat,
                backend=self.backend,
                chunk_budget=self.chunk_budget,
            )

    def _score_local(
        self, batch: PaddedBatch, val_flat: np.ndarray, staged=None
    ) -> PendingResult:
        import jax.numpy as jnp

        b = batch.batch_size
        # The formulation decides the chunk policy: a 'pallas' request
        # with overflow-risk weights runs the gather body, which needs
        # the XLA paths' l1p*l2p-sized chunks, not the kernel's.
        fm = ("gather",)
        if self.backend == "pallas":
            # Same eligibility policy as the sharded paths; the chunked
            # [NC, CB] shape buckets match the bench/sharded programs, so
            # batch sizes within one bucket share a single compilation.
            # The bucket's own l2p engages the length-aware bound, so a
            # short-Seq2 bucket keeps the exact kernel for weights past
            # the static 4095 ceiling.
            fm = choose_pallas_formulation(val_flat, (), batch.l2p)
        cb = choose_chunk(
            batch,
            self.chunk_budget,
            backend="pallas" if fm[0] == "pallas" else "xla",
        )
        bp = round_up(b, cb)
        rows, lens = pad_batch_rows(batch, bp)
        # Operand sources: host arrays by default; a matching prestaged
        # tuple (feed overlap) substitutes device-committed arrays whose
        # transfers were issued while the previous chunk computed —
        # jnp.asarray below is then a no-op alias.  The staged handle is
        # single-use (drained at take() in score_codes_async), so a
        # retried dispatch always falls back to these host sources and
        # re-stages fresh buffers for the donating jit entry.
        seq1_src = batch.seq1ext
        rows_src = rows.reshape(bp // cb, cb, batch.l2p)
        lens_src = lens.reshape(bp // cb, cb)
        val_src = val_flat
        if staged is not None and staged_matches(
            staged, seq1_src.shape, rows_src.shape, lens_src.shape,
            val_flat.shape,
        ):
            _obs_inc("feed_prestage_hits")
            seq1_src, _, rows_src, lens_src, val_src = staged
        args = (
            jnp.asarray(seq1_src),
            jnp.int32(batch.len1),
            jnp.asarray(rows_src),
            jnp.asarray(lens_src),
            jnp.asarray(val_src),
        )
        if self.backend == "pallas":
            if fm[0] == "pallas":
                from .pallas_scorer import choose_superblock, score_chunks_pallas

                sb = choose_superblock(
                    batch.l1p // 128,
                    batch.l2p // 128,
                    batch.len1,
                    batch.len2,
                    fm[1],
                )
                # Row-packed kernel (VERDICT r3 item 3): single-char-block
                # buckets whose every pair fits a 64-row sub-tile share
                # tiles p = 128/l2s pairs at a time.  ONE policy source
                # (choose_rowpack) shared with the bench resolver, or
                # the bench would time a different program.
                from .values import max_abs_value

                l2s = choose_rowpack(
                    fm[1], batch.l2p, batch.len2, maxv=max_abs_value(val_flat)
                )
                # Concrete dispatch decisions as gauges: the run report
                # names the program configuration the run actually ran.
                _obs_gauge("config_feed", fm[1])
                _obs_gauge("config_superblock", sb)
                _obs_gauge("config_rowpack", l2s if l2s is not None else 0)
                _obs_gauge("config_chunk", cb)
                if self.check:
                    # The single point where every dispatch decision is
                    # concrete: feed, chunk, superblock, rowpack class.
                    from ..analysis import contracts, vmem

                    contracts.validate_dispatch(
                        feed=fm[1],
                        maxv=int(max_abs_value(val_flat)),
                        l1p=batch.l1p,
                        l2p=batch.l2p,
                        sb=sb,
                        l2s=l2s,
                    )
                    vmem.check_config(
                        nbn=batch.l1p // 128,
                        nbi=batch.l2p // 128,
                        feed=fm[1],
                        sb=sb,
                        pp=2 if cb % 2 == 0 else 1,
                        l2s=l2s,
                    )
                out = score_chunks_pallas(*args, feed=fm[1], sb=sb, l2s=l2s)
            else:
                from .xla_scorer import score_chunks

                out = score_chunks(*args)
        else:
            out = resolve_xla_formulation(self.backend, val_flat, batch.l2p)(*args)
        return PendingResult(out, b)

    # -- text-level API ----------------------------------------------------
    def score(self, seq1: str, seq2_list: list[str], weights) -> np.ndarray:
        return self.score_codes(
            encode_normalized(seq1),
            [encode_normalized(s) for s in seq2_list],
            weights,
        )
