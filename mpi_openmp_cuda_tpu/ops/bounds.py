"""Cert-backed numeric bounds: the single source of every overflow gate.

Every ceiling the dispatch/kernel layers quote — the f32 exact-integer
window, the HIGHEST-matmul operand cap, the rowpack epilogue limit, the
packed-argmax radix and int32 ceiling — lives HERE, and nowhere else.
The value-range certifier (``analysis/ranges.py``, ``make
ranges-audit``) re-derives each one from first principles with its
interval engine and diffs the derivation against these wired values:
drift between a bound and its proof is a typed finding, so a constant
can no longer be "hand-derived once, asserted forever".

Each literal below carries a ``# cert: <row>`` marker naming the
RangeCert ``derived_constants`` row that proves it (seqlint SEQ013
enforces the markers on every numeric-bound literal in ops/ code).
"""

from __future__ import annotations

from ..utils.constants import BUF_SIZE_SEQ2

# float32 carries 24 mantissa bits: every integer of magnitude below
# 2^24 is exactly representable, so f32 adds/accumulations of in-window
# integers are exact.  Everything the mm path and the f32/bf16 pallas
# feeds promise rests on keeping accumulators inside this window.
F32_EXACT_WINDOW = 16777216  # = 2^24  # cert: f32-exact-window

# The multi-pass Precision.HIGHEST matmul resolves operands of up to 16
# mantissa bits exactly; the live operand of the delta formulation is
# |d0 - d1| <= 2 * max|v|, capping |v| at 32767 regardless of length.
MAX_HIGHEST_OPERAND = 65535  # = 2^16 - 1  # cert: operand-cap
OPERAND_CAP = MAX_HIGHEST_OPERAND // 2  # 32767  # cert: operand-cap

# Packed-argmax encoding (i8 feed): one int32 carries (g, kappa) as
# g * PACK_RADIX + (PACK_RADIX - 1 - kappa).  The radix is the smallest
# power of two that fields every kappa in a BUF_SIZE_SEQ2-capped bucket
# (kappa <= l2p <= 2048 < 4096), and the whole pack must stay inside
# int32: |g| * PACK_RADIX + (PACK_RADIX - 1) <= INT32_PACK_CEILING.
PACK_RADIX = 4096  # = 2^12  # cert: argmax-pack-radix
INT32_PACK_CEILING = 2147483647  # = 2^31 - 1  # cert: argmax-pack-bound

# Largest Seq2 bucket width the i8 packed-argmax path admits: with
# |g| <= 2 * 127 * l2p the pack fits int32 exactly up to the
# BUF_SIZE_SEQ2 bucket ceiling (520192 * 4096 + 4095 < 2^31); wider
# (ring long-context) buckets keep the unpacked path.
PACKED_L2P_CEILING = 2048  # cert: argmax-pack-bound

# Packed rowpack epilogue: spack = (t1 + gdec) * 2^klb + key with
# klb <= SUPERBLOCK_KEY_BITS, so the packed score magnitude
# 3 * l2s * max|v| must stay below 2^(31 - 12) = 2^19 for the int32
# pack to be exact (dispatch.pack_classes is gated on this).
SUPERBLOCK_KEY_BITS = 12  # cert: superblock-key-budget
ROWPACK_EPILOGUE_LIMIT = 524288  # = 2^19  # cert: rowpack-epilogue-limit

# Offset-super-block cap: sbw - 1 = sb * 128 - 1 must fit the klb <= 12
# key field.  The derived admissible maximum is 32 (4096 lanes); the
# wired chooser cap stays 24 — the measured perf plateau — which the
# cert checks as wired <= derived, not equality.
SUPERBLOCK_CAP = 24  # cert: superblock-key-budget

# Weight magnitudes up to this keep every partial sum an exact float32
# integer at ANY in-cap Seq2 length: max_exact_value() at the padded
# BUF_SIZE_SEQ2 buffer (2 * 2048 * 4095 < 2^24).
MAX_EXACT_WEIGHT = 4095  # cert: static-weight-ceiling

# Out-of-band floor for packed int32 comparisons: the largest-magnitude
# int32 whose negation is still representable, so masked lanes sort
# below every real pack without overflowing on negation.
INT32_PACKED_SENTINEL = -2147483647  # = -(2^31 - 1)  # cert: int32-packed-sentinel


def max_exact_value(l2p: int | None = None) -> int:
    """Largest |table value| for which the f32 delta formulation is exact
    when each scored row spans at most ``l2p`` Seq2 positions.

    Two binding constraints (r6, length-aware; the static 4095 ceiling is
    exactly this bound at the padded BUF_SIZE_SEQ2 cap):

    * accumulation — every partial of ``G = prefix(d0 - d1)`` is an
      integer bounded by ``2 * l2p * max|v|``, which must stay < 2^24 for
      the f32 adds (MXU accumulators and VPU epilogue alike) to be exact;
    * operand — each ``|d0 - d1| <= 2 * max|v|`` must fit the 16 mantissa
      bits the HIGHEST multi-pass matmul resolves, capping max|v| at
      :data:`OPERAND_CAP` regardless of length.

    ``l2p=None`` gives the conservative static bound for callers that do
    not know the batch shape yet.  Shared by the mm path and the fused
    Pallas kernel's f32 feed — both accumulate the same delta prefixes.
    """
    if l2p is None:
        l2p = ((BUF_SIZE_SEQ2 + 127) // 128) * 128
    l2p = max(int(l2p), 1)
    return min((F32_EXACT_WINDOW - 1) // (2 * l2p), OPERAND_CAP)


def fits_exact_window(val_flat, l2p: int | None = None) -> bool:
    """True when every partial sum of the f32 delta formulation stays an
    exact float32 integer for this value table at this Seq2 bucket width
    (``l2p=None`` = the conservative whole-buffer bound).  The dispatch
    gate formerly known as ``mm_formulation_exact`` — now consuming the
    certified ceiling instead of re-deriving it locally."""
    from .values import max_abs_value

    return max_abs_value(val_flat) <= max_exact_value(l2p)
