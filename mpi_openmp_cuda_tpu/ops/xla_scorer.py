"""Pure-XLA alignment scorer (reference parity: C13 kernel + C14 launcher).

The reference's CUDA kernel walks the (offset n, mutant k) candidate grid
serially, re-scoring all L2 characters per candidate with shared-memory
atomics (cudaFunctions.cu:116-168).  The TPU formulation (SURVEY §7.2)
vectorises the whole grid with diagonal prefix sums:

* ``v0[n, i]`` = signed value of pairing seq2[i] with seq1[n+i] (unshifted
  diagonal); ``v1[n, i]`` pairs with seq1[n+i+1] (hyphen-shifted diagonal).
* ``score(n, k) = prefix(v0[n])[k] + suffix(v1[n])[k]`` — one cumsum pass per
  diagonal family, then a single argmax over the masked grid.

This turns O((L1-L2)*L2^2) work into O(L1*L2) and replaces the serial
candidate loop, the `__shared__` histogram and the `atomicAdd` reductions
with lane-parallel cumulative sums — no atomics exist or are needed.

Semantics parity (tested against the numpy oracles and the Appendix C
goldens): offsets n in [0, len1-len2); k=0 encodes hyphen-after-end; ties
resolve to the first candidate in offset-major, k-ascending-with-0-first
order (jnp.argmax's first-hit rule over a grid laid out in exactly the
reference's iteration order, cudaFunctions.cu:161); len2 == len1 scores
positionally as (score, 0, 0); len2 > len1 (or len2 == 0) yields INT32_MIN.

Shapes are static per (L1P, L2P, chunk) bucket — no data-dependent Python
control flow; everything under jit is lax-traced once per bucket.
"""

from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.constants import ALPHABET_SIZE, INT32_MIN

_NEG = jnp.int32(INT32_MIN)


def _score_pair(vw, len1, seq2row, len2):
    """Score one (seq1, seq2) pair over the full padded candidate grid.

    vw      : [27 * (L1P + L2P + 1)] int32 — flattened window-value table
              ``vw[c * wext + t] = val[c, seq1ext[t]]``, precomputed ONCE
              per batch by :func:`score_chunks_body` (r6 hoist: the Seq1
              side of the value lookup is pair-independent, so the old
              per-pair ``g0``/``g1`` char gathers chained into a value
              gather collapse to a single gather per diagonal family).
    len1    : scalar int32 actual length of seq1.
    seq2row : [L2P] int32 padded seq2 codes.
    len2    : scalar int32 actual length.

    Returns (score, n, k) int32 scalars.
    """
    l2p = seq2row.shape[0]
    wext = vw.shape[0] // ALPHABET_SIZE  # == L1P + L2P + 1
    noff = wext - l2p - 1  # == L1P: covers all valid offsets

    n = jnp.arange(noff, dtype=jnp.int32)[:, None]
    i = jnp.arange(l2p, dtype=jnp.int32)[None, :]
    idx0 = n + i

    vw_base = seq2row[None, :].astype(jnp.int32) * wext
    charmask = i < len2  # zero out padded seq2 positions
    v0 = jnp.where(charmask, jnp.take(vw, vw_base + idx0), 0)
    v1 = jnp.where(charmask, jnp.take(vw, vw_base + idx0 + 1), 0)

    c0 = jnp.cumsum(v0, axis=1)
    c1 = jnp.cumsum(v1, axis=1)
    t0 = c0[:, -1:]  # full unshifted sum per offset (k=0 candidate)
    t1 = c1[:, -1:]

    # Column j holds mutant k=j: k=0 -> t0; k>=1 -> prefix0(k) + shifted suffix1(k).
    scores = jnp.concatenate([t0, c0[:, :-1] + (t1 - c1[:, :-1])], axis=1)

    k = jnp.arange(l2p, dtype=jnp.int32)[None, :]
    valid = (n < jnp.maximum(len1 - len2, 0)) & ((k == 0) | (k < len2))
    flat = jnp.where(valid, scores, _NEG).reshape(-1)

    # First max in n-major, k=0,1,... order == the reference's strict-> loop.
    bi = jnp.argmax(flat).astype(jnp.int32)
    best_score = flat[bi]
    best_n = bi // l2p
    best_k = bi % l2p

    eq_score = c0[0, -1]  # positional score at n=0 (branch-A analogue)
    searchable = (len2 < len1) & (len2 > 0)
    score = jnp.where(
        len2 == len1, eq_score, jnp.where(searchable, best_score, _NEG)
    )
    out_n = jnp.where(searchable, best_n, 0)
    out_k = jnp.where(searchable, best_k, 0)
    return jnp.stack([score, out_n, out_k])


def score_chunks_body(seq1ext, len1, seq2_chunks, len2_chunks, val_flat):
    """Score a [NC, CB, L2P] chunked batch; returns [NC, CB, 3] int32.

    ``vmap`` handles intra-chunk batch parallelism (the per-sequence kernel
    launches of cudaFunctions.cu:204-220, minus the host synchronisation);
    ``lax.map`` walks chunks sequentially to bound live memory — the
    device-memory-manager role of C14, without per-call mallocs.

    Unjitted body so the distribution layer can reuse it inside shard_map;
    single-device callers use the jitted ``score_chunks`` below.
    """
    # r6 window-value hoist: vw[c, t] = val[c, seq1ext[t]] is shared by
    # every pair and chunk — build it once ([27, L1P+L2P+1] int32, a few
    # hundred KB at cap) instead of re-gathering seq1 chars per pair.
    vw = jnp.take(
        val_flat.reshape(ALPHABET_SIZE, ALPHABET_SIZE), seq1ext, axis=1
    ).reshape(-1)

    def chunk_fn(args):
        rows, lens = args
        return jax.vmap(lambda r, l: _score_pair(vw, len1, r, l))(rows, lens)

    return lax.map(chunk_fn, (seq2_chunks, len2_chunks))


# donate_argnums per the DonationPlan (analysis/dataflow.py): seq1ext
# and seq2_chunks are staged fresh per dispatch and provably dead after
# the call at every site; len1/len2_chunks/val_flat are pinned (scalar /
# below the 16 KiB large-buffer bound).  `make donation-audit` fails on
# drift between this literal and the proof.
score_chunks = jax.jit(score_chunks_body, donate_argnums=(0, 2))

# Backends that cannot alias a donated input into an output (CPU for
# mismatched shapes) warn once per compile; the donation is still
# correct, just unused there.
warnings.filterwarnings("ignore", message="Some donated buffers were not usable")
