"""The composed production bucket schedule, as a first-class object.

``production_schedule`` used to live in ``bench.py``; it moved into the
package so the trace-level analysis layer (``analysis.costmodel`` /
``analysis.traceaudit``) can derive the EXACT schedule the production
dispatch runs — buckets, chunk shapes, padded lens, resolved bodies —
without importing the bench harness.  ``bench.py`` re-exports it, so
the steady-state measurement, the FLOP/VPU accounting, and the static
cost sheet all price one derivation (the r4 "the bench times and
accounts exactly the production schedule" invariant, now extended to
"…and the auditor audits exactly it" too).

``kernel_configs`` additionally resolves each bucket's kernel-side
decisions (formulation, MXU feed, super-block width, row-packing class)
the same way the dispatch layer does at scoring time — the static facts
the cost model prices and the AOT warm-set ranking is keyed on.

Launch fusion (r6): ``plan_fusion_groups`` partitions the 128-aligned
length buckets into LAUNCH GROUPS — contiguous runs of sorted bucket
keys that share one ``pallas_call`` at the widest member's L2P — priced
with the same super-block cost model the dispatch chooser minimises,
plus the cost model's per-launch overhead term.  The fused kernel needs
no new lowering: the lens plane is already scalar-prefetched per grid
cell, so a merged launch is the existing lens-adaptive kernel over the
concatenated rows padded to the group's L2P (per-pair ``nbi_live``
truncation masks the extra lanes exactly).  ``production_schedule``
emits one entry per launch group, and because every accounting plane
derives from it, the cost sheet, trace audit, warm set and bench all
follow the fused schedule automatically.
"""

from __future__ import annotations

import dataclasses

import numpy as np

# Near-tie band for the fusion planner: among launch partitions whose
# modelled wall is within this fraction of the minimum, prefer the
# FEWEST launches.  The in-model launch price (2 us) only counts the
# dispatch floor; the measured between-launch loss on real hardware
# (BENCH_r05: 0.217 measured vs 0.446 predicted MFU) is an order of
# magnitude larger and unmodelled, so a modelled near-tie is a real win
# for the fused side.
FUSED_TIE_FRACTION = 0.02

# Partition enumeration is 2^(k-1) over k sorted bucket keys; real
# schedules have <= 4-5 buckets, anything past this cap falls back to
# the unfused per-bucket schedule rather than an exponential host scan.
_MAX_FUSABLE_BUCKETS = 10


def _group_cost(keys, groups, sizes, len1, l1p, val_flat):
    """Modelled wall of fusing buckets ``keys`` into ONE launch group at
    the widest member's L2P: ``(wall_s, launches)``, or None when the
    group cannot run on the fused kernel (off-kernel formulation at the
    group width, or the group super-block over the VMEM budget)."""
    from .dispatch import choose_chunk_dims, choose_pallas_formulation
    from .pallas_scorer import (
        choose_superblock,
        fused_emittable,
        model_constants,
        superblock_model_cost,
    )

    l2p = max(keys)
    nbn, nbi = l1p // 128, l2p // 128
    fm = choose_pallas_formulation(val_flat, (), l2p)
    if fm[0] != "pallas":
        return None
    feed = fm[1]
    lens = [int(sizes[i]) for k in keys for i in groups[k]]
    sb = choose_superblock(nbn, nbi, len1, lens, feed)
    if not fused_emittable(nbn, nbi, feed, sb):
        return None
    hist: dict[int, int] = {}
    for l2 in lens:
        if l2 <= 0:
            continue
        l2r = -(-l2 // 128) * 128
        hist[l2r] = hist.get(l2r, 0) + 1
    base, per_sb, rate = model_constants(feed)
    wall = superblock_model_cost(
        nbn, nbi, len1, tuple(sorted(hist.items())), sb,
        base=base, per_sb=per_sb, rate=rate,
    )
    from .dispatch import round_up

    cb = choose_chunk_dims(l1p, l2p, len(lens), backend="pallas")
    launches = round_up(len(lens), cb) // cb
    return wall, launches


def plan_fusion_groups(groups, sizes, len1, val_flat):
    """Partition the bucket keys of ``groups`` into launch groups.

    Returns a list of key tuples, sorted by first key — each tuple is
    the set of ``plan_buckets`` keys that dispatch as ONE program (one
    ``pallas_call`` per chunk).  Singletons reproduce the pre-fusion
    per-bucket schedule exactly.

    Only unpacked 128-aligned buckets fuse (the packed kernel's sub-128
    class keys keep their own launches — "one per feed class"); every
    candidate group must route to the pallas formulation at the GROUP
    L2P and fit the VMEM budget at the group super-block.  Contiguous
    partitions of the sorted keys are priced with the dispatch chooser's
    own super-block cost model plus the cost model's launch-overhead
    term; among partitions within :data:`FUSED_TIE_FRACTION` of the
    cheapest, the planner picks the FEWEST launches (the cost model as
    prior — the unmodelled between-launch loss favours fusion).
    """
    singletons = [(k,) for k in sorted(groups)]
    fusable = [k for k in sorted(groups) if k % 128 == 0]
    packed = [(k,) for k in sorted(groups) if k % 128 != 0]
    if len(fusable) < 2 or len(fusable) > _MAX_FUSABLE_BUCKETS:
        return singletons
    try:
        from ..analysis.costmodel import LAUNCH_OVERHEAD_S
    except ImportError:  # pragma: no cover - analysis plane always ships
        LAUNCH_OVERHEAD_S = 2.0e-6
    l1p = max(128, 128 * (-(-int(len1) // 128)))
    # Every singleton must itself be priceable, or fusion planning has
    # no comparable baseline — fall back to the unfused schedule.
    cost_cache: dict[tuple, tuple | None] = {}

    def cost(keys):
        if keys not in cost_cache:
            cost_cache[keys] = _group_cost(
                keys, groups, sizes, len1, l1p, val_flat
            )
        return cost_cache[keys]

    if any(cost((k,)) is None for k in fusable):
        return singletons

    n = len(fusable)
    best: list[tuple[float, int, tuple]] = []
    for mask in range(1 << (n - 1)):
        parts, start = [], 0
        for j in range(n - 1):
            if mask & (1 << j):
                parts.append(tuple(fusable[start : j + 1]))
                start = j + 1
        parts.append(tuple(fusable[start:]))
        wall = 0.0
        launches = 0
        ok = True
        for part in parts:
            c = cost(part)
            if c is None:
                ok = False
                break
            wall += c[0] + c[1] * LAUNCH_OVERHEAD_S
            launches += c[1]
        if ok:
            best.append((wall, launches, tuple(parts)))
    if not best:
        return singletons
    w_min = min(w for w, _, _ in best)
    near = [b for b in best if b[0] <= w_min * (1.0 + FUSED_TIE_FRACTION)]
    _, _, parts = min(near, key=lambda b: (b[1], b[0]))
    return sorted(packed + list(parts), key=lambda g: g[0])


def production_schedule(problem, backend: str):
    """The bucket schedule the production dispatch would run for this
    problem — one entry per length bucket (including the r4 row-packing
    sub-classes) with its padded chunked rows and resolved chunks body.

    SHARED by the steady-state harness (which times it), the MFU /
    VPU-floor accounting (which counts it), and the static schedule
    auditor (which prices it): a single derivation is the only way "the
    bench times and accounts exactly the production schedule" stays
    true (r4 code review).  Entries carry the PADDED per-chunk lens —
    the packed kernel executes super-block 0 even for all-padding
    tiles, and the accounting must count them.
    """
    from .dispatch import (
        choose_chunk,
        choose_pallas_formulation,
        DEFAULT_CHUNK_BUDGET,
        effective_backend,
        pack_classes,
        pad_batch_rows,
        pad_problem,
        plan_buckets,
        resolve_chunks_body,
        round_up,
    )
    from .values import max_abs_value, value_table

    val = value_table(problem.weights).astype(np.int32).reshape(-1)
    # Row packing only applies to 128-row buckets, so gate the packing
    # sub-classes on the l2p=128 formulation (mirrors score_codes_async).
    packable = False
    classes: tuple = ()
    if backend == "pallas":
        fm = choose_pallas_formulation(val, (), 128)
        if fm[0] == "pallas":
            classes = pack_classes(fm[1], max_abs_value(val))
            packable = bool(classes)
    sizes = [c.size for c in problem.seq2_codes]
    groups = plan_buckets(
        sizes,
        packable=packable,
        classes=classes or (8, 16, 32, 64),
    )
    # Launch fusion (r6): partition the bucket keys into launch groups
    # — the SAME planner the dispatch layer consults, so the schedule
    # every accounting plane derives from is the schedule that runs.
    if backend == "pallas":
        group_keys = plan_fusion_groups(
            groups, sizes, int(problem.seq1_codes.size), val
        )
    else:
        group_keys = [(k,) for k in sorted(groups)]
    sched = []
    for gkeys in group_keys:
        idx = sorted(i for k in gkeys for i in groups[k])
        codes = [problem.seq2_codes[i] for i in idx]
        batch = pad_problem(problem.seq1_codes, codes)
        # Same chunk policy the dispatch layer applies: pallas-sized
        # chunks only when the kernel actually runs (wide weights route
        # to gather).
        cb = choose_chunk(
            batch,
            DEFAULT_CHUNK_BUDGET,
            backend=effective_backend(backend, val, batch.l2p),
        )
        bp = round_up(batch.batch_size, cb)
        rows, lens = pad_batch_rows(batch, bp)
        body = resolve_chunks_body(
            backend,
            val,
            problem_dims=(batch.l1p, batch.l2p, batch.len1, batch.len2),
        )
        sched.append(
            {
                "batch": batch,
                "cb": cb,
                "rows": rows.reshape(bp // cb, cb, batch.l2p),
                "lens": lens.reshape(bp // cb, cb),
                "body": body,
                "bucket_keys": tuple(gkeys),
            }
        )
    return val, sched


@dataclasses.dataclass(frozen=True)
class FusedScheduleConfig:
    """The launch structure of one production schedule, as declared by
    the fusion planner: the bucket-key partition and the EXACT number of
    ``pallas_call`` launches the lowered schedule must show.  This is
    the contract the trace auditor's launch-budget gate enforces — a
    schedule that lowers to more launches than it declared is a silent
    de-fusion regression."""

    groups: tuple  # tuple of bucket-key tuples, one per launch group
    declared_launches: int  # exact lowered pallas_call count
    feed: str | None  # MXU feed of the schedule; None when off-kernel


def fused_schedule_config(problem, backend: str) -> FusedScheduleConfig:
    """Resolve the declared launch structure of ``problem``'s production
    schedule (the fusion planner's output, re-derived from the single
    ``production_schedule`` derivation all accounting shares)."""
    _, sched = production_schedule(problem, backend)
    configs = kernel_configs(problem, backend)
    return FusedScheduleConfig(
        groups=tuple(p["bucket_keys"] for p in sched),
        declared_launches=sum(p["lens"].shape[0] for p in sched),
        feed=configs[0].feed if configs else None,
    )


@dataclasses.dataclass(frozen=True)
class BucketKernelConfig:
    """The static kernel-side facts of ONE bucket of the production
    schedule — everything the dispatch layer decides before tracing,
    i.e. exactly what an AOT compile cache would key an executable on
    (plus the chunk walk the cost model prices)."""

    l1p: int
    l2p: int
    len1: int
    cb: int  # chunk batch (rows per kernel launch)
    n_chunks: int  # launches this bucket contributes per dispatch
    formulation: str  # 'pallas' | 'xla-gather' | 'xla-mm'
    feed: str | None  # MXU feed; None off the fused kernel
    sb: int | None  # offset-super-block width
    l2s: int | None  # row-packing class (packed kernel) or None
    chunk_lens: tuple  # per-chunk PADDED lens, tuple of int tuples
    # plan_buckets keys fused into this launch group; () when the part
    # was derived outside the bucketed schedule (buckets=False).  NOT
    # part of cache_key — fusion changes the shapes, not the identity
    # scheme.
    bucket_keys: tuple = ()

    @property
    def cache_key(self) -> tuple:
        """The executable identity: one compiled program per distinct
        key across the schedule (shape bucket x kernel decisions)."""
        return (
            self.formulation, self.feed, self.l1p, self.l2p, self.cb,
            self.sb, self.l2s,
        )

    @property
    def executable_key(self) -> tuple:
        """``cache_key`` extended with the traced leading chunk count —
        the FULL static identity of one compiled program as the AOT warm
        plane keys it (``aot/warmset.WarmEntry.executable_key`` mirrors
        this): two buckets sharing a cache_key but walking different
        ``n_chunks`` trace different [NC, CB, L2P] programs.  ``len1``
        stays excluded — it is a runtime scalar operand."""
        return self.cache_key + (self.n_chunks,)


def kernel_configs(problem, backend: str, buckets: bool = True):
    """Resolve the per-bucket kernel decisions of ``problem``'s
    production schedule, exactly as the dispatch layer would.

    ``buckets=False`` describes the UNBUCKETED whole-batch program
    instead (one entry), mirroring ``bench.kernel_floor_counts``'s
    single-program accounting.  Returns ``None`` when any bucket falls
    off the fused kernel (wide weights / unaligned shapes) — counts for
    work that never runs must not be recorded.
    """
    from .dispatch import (
        DEFAULT_CHUNK_BUDGET,
        choose_chunk,
        choose_pallas_formulation,
        choose_rowpack,
        effective_backend,
        pad_batch_rows,
        pad_problem,
        round_up,
    )
    from .pallas_scorer import choose_superblock
    from .values import max_abs_value, value_table

    val_flat = value_table(problem.weights).reshape(-1)
    if buckets:
        _, sched = production_schedule(problem, backend)
        parts = [
            (p["batch"], np.asarray(p["lens"]), p["bucket_keys"])
            for p in sched
        ]
    else:
        batch = pad_problem(problem.seq1_codes, problem.seq2_codes)
        cb = choose_chunk(
            batch, DEFAULT_CHUNK_BUDGET,
            backend=effective_backend(backend, val_flat, batch.l2p),
        )
        bp = round_up(batch.batch_size, cb)
        _, lens = pad_batch_rows(batch, bp)
        parts = [(batch, lens.reshape(bp // cb, cb), ())]

    configs = []
    maxv = max_abs_value(val_flat)
    for sub, lens_chunks, bucket_keys in parts:
        fm = choose_pallas_formulation(val_flat, (sub.l1p, sub.l2p), sub.l2p)
        if fm[0] != "pallas":
            return None
        feed = fm[1]
        sb = choose_superblock(
            sub.l1p // 128, sub.l2p // 128, sub.len1, sub.len2, feed
        )
        l2s = choose_rowpack(feed, sub.l2p, sub.len2, maxv=maxv)
        chunk_lens = tuple(
            tuple(int(x) for x in chunk) for chunk in lens_chunks
        )
        configs.append(
            BucketKernelConfig(
                l1p=int(sub.l1p),
                l2p=int(sub.l2p),
                len1=int(sub.len1),
                cb=int(lens_chunks.shape[1]),
                n_chunks=int(lens_chunks.shape[0]),
                formulation=fm[0],
                feed=feed,
                sb=sb,
                l2s=l2s,
                chunk_lens=chunk_lens,
                bucket_keys=tuple(bucket_keys),
            )
        )
    return configs
