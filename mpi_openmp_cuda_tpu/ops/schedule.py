"""The composed production bucket schedule, as a first-class object.

``production_schedule`` used to live in ``bench.py``; it moved into the
package so the trace-level analysis layer (``analysis.costmodel`` /
``analysis.traceaudit``) can derive the EXACT schedule the production
dispatch runs — buckets, chunk shapes, padded lens, resolved bodies —
without importing the bench harness.  ``bench.py`` re-exports it, so
the steady-state measurement, the FLOP/VPU accounting, and the static
cost sheet all price one derivation (the r4 "the bench times and
accounts exactly the production schedule" invariant, now extended to
"…and the auditor audits exactly it" too).

``kernel_configs`` additionally resolves each bucket's kernel-side
decisions (formulation, MXU feed, super-block width, row-packing class)
the same way the dispatch layer does at scoring time — the static facts
the cost model prices and the AOT warm-set ranking is keyed on.
"""

from __future__ import annotations

import dataclasses

import numpy as np


def production_schedule(problem, backend: str):
    """The bucket schedule the production dispatch would run for this
    problem — one entry per length bucket (including the r4 row-packing
    sub-classes) with its padded chunked rows and resolved chunks body.

    SHARED by the steady-state harness (which times it), the MFU /
    VPU-floor accounting (which counts it), and the static schedule
    auditor (which prices it): a single derivation is the only way "the
    bench times and accounts exactly the production schedule" stays
    true (r4 code review).  Entries carry the PADDED per-chunk lens —
    the packed kernel executes super-block 0 even for all-padding
    tiles, and the accounting must count them.
    """
    from .dispatch import (
        choose_chunk,
        choose_pallas_formulation,
        DEFAULT_CHUNK_BUDGET,
        effective_backend,
        pack_classes,
        pad_batch_rows,
        pad_problem,
        plan_buckets,
        resolve_chunks_body,
        round_up,
    )
    from .values import max_abs_value, value_table

    val = value_table(problem.weights).astype(np.int32).reshape(-1)
    # Row packing only applies to 128-row buckets, so gate the packing
    # sub-classes on the l2p=128 formulation (mirrors score_codes_async).
    packable = False
    classes: tuple = ()
    if backend == "pallas":
        fm = choose_pallas_formulation(val, (), 128)
        if fm[0] == "pallas":
            classes = pack_classes(fm[1], max_abs_value(val))
            packable = bool(classes)
    groups = plan_buckets(
        [c.size for c in problem.seq2_codes],
        packable=packable,
        classes=classes or (8, 16, 32, 64),
    )
    sched = []
    for key in sorted(groups):
        codes = [problem.seq2_codes[i] for i in groups[key]]
        batch = pad_problem(problem.seq1_codes, codes)
        # Same chunk policy the dispatch layer applies: pallas-sized
        # chunks only when the kernel actually runs (wide weights route
        # to gather).
        cb = choose_chunk(
            batch,
            DEFAULT_CHUNK_BUDGET,
            backend=effective_backend(backend, val, batch.l2p),
        )
        bp = round_up(batch.batch_size, cb)
        rows, lens = pad_batch_rows(batch, bp)
        body = resolve_chunks_body(
            backend,
            val,
            problem_dims=(batch.l1p, batch.l2p, batch.len1, batch.len2),
        )
        sched.append(
            {
                "batch": batch,
                "cb": cb,
                "rows": rows.reshape(bp // cb, cb, batch.l2p),
                "lens": lens.reshape(bp // cb, cb),
                "body": body,
            }
        )
    return val, sched


@dataclasses.dataclass(frozen=True)
class BucketKernelConfig:
    """The static kernel-side facts of ONE bucket of the production
    schedule — everything the dispatch layer decides before tracing,
    i.e. exactly what an AOT compile cache would key an executable on
    (plus the chunk walk the cost model prices)."""

    l1p: int
    l2p: int
    len1: int
    cb: int  # chunk batch (rows per kernel launch)
    n_chunks: int  # launches this bucket contributes per dispatch
    formulation: str  # 'pallas' | 'xla-gather' | 'xla-mm'
    feed: str | None  # MXU feed; None off the fused kernel
    sb: int | None  # offset-super-block width
    l2s: int | None  # row-packing class (packed kernel) or None
    chunk_lens: tuple  # per-chunk PADDED lens, tuple of int tuples

    @property
    def cache_key(self) -> tuple:
        """The executable identity: one compiled program per distinct
        key across the schedule (shape bucket x kernel decisions)."""
        return (
            self.formulation, self.feed, self.l1p, self.l2p, self.cb,
            self.sb, self.l2s,
        )

    @property
    def executable_key(self) -> tuple:
        """``cache_key`` extended with the traced leading chunk count —
        the FULL static identity of one compiled program as the AOT warm
        plane keys it (``aot/warmset.WarmEntry.executable_key`` mirrors
        this): two buckets sharing a cache_key but walking different
        ``n_chunks`` trace different [NC, CB, L2P] programs.  ``len1``
        stays excluded — it is a runtime scalar operand."""
        return self.cache_key + (self.n_chunks,)


def kernel_configs(problem, backend: str, buckets: bool = True):
    """Resolve the per-bucket kernel decisions of ``problem``'s
    production schedule, exactly as the dispatch layer would.

    ``buckets=False`` describes the UNBUCKETED whole-batch program
    instead (one entry), mirroring ``bench.kernel_floor_counts``'s
    single-program accounting.  Returns ``None`` when any bucket falls
    off the fused kernel (wide weights / unaligned shapes) — counts for
    work that never runs must not be recorded.
    """
    from .dispatch import (
        DEFAULT_CHUNK_BUDGET,
        choose_chunk,
        choose_pallas_formulation,
        choose_rowpack,
        effective_backend,
        pad_batch_rows,
        pad_problem,
        round_up,
    )
    from .pallas_scorer import choose_superblock
    from .values import max_abs_value, value_table

    val_flat = value_table(problem.weights).reshape(-1)
    if buckets:
        _, sched = production_schedule(problem, backend)
        parts = [(p["batch"], np.asarray(p["lens"])) for p in sched]
    else:
        batch = pad_problem(problem.seq1_codes, problem.seq2_codes)
        cb = choose_chunk(
            batch, DEFAULT_CHUNK_BUDGET,
            backend=effective_backend(backend, val_flat, batch.l2p),
        )
        bp = round_up(batch.batch_size, cb)
        _, lens = pad_batch_rows(batch, bp)
        parts = [(batch, lens.reshape(bp // cb, cb))]

    configs = []
    maxv = max_abs_value(val_flat)
    for sub, lens_chunks in parts:
        fm = choose_pallas_formulation(val_flat, (sub.l1p, sub.l2p), sub.l2p)
        if fm[0] != "pallas":
            return None
        feed = fm[1]
        sb = choose_superblock(
            sub.l1p // 128, sub.l2p // 128, sub.len1, sub.len2, feed
        )
        l2s = choose_rowpack(feed, sub.l2p, sub.len2, maxv=maxv)
        chunk_lens = tuple(
            tuple(int(x) for x in chunk) for chunk in lens_chunks
        )
        configs.append(
            BucketKernelConfig(
                l1p=int(sub.l1p),
                l2p=int(sub.l2p),
                len1=int(sub.len1),
                cb=int(lens_chunks.shape[1]),
                n_chunks=int(lens_chunks.shape[0]),
                formulation=fm[0],
                feed=feed,
                sb=sb,
                l2s=l2s,
                chunk_lens=chunk_lens,
            )
        )
    return configs
